"""Device-resident chunk store: the HBM arena the serving path reads from.

The reference serves queries from off-heap block memory with
reclaim-on-demand eviction (reference: memory/src/main/scala/filodb.memory/
BlockManager.scala:142 PageAlignedBlockManager, Block.scala:90; eviction
callbacks into TimeSeriesShard.scala:279-301).  The TPU equivalent keeps
frozen chunk data **on device** as time-bucketed grids so queries read HBM
directly instead of re-uploading numpy per query:

- Per (shard, schema, column) a :class:`DeviceGridCache` assigns each
  partition a fixed lane and materializes time **blocks** — device arrays
  ``[BLOCK_BUCKETS, lanes]`` covering ``BLOCK_BUCKETS`` consecutive
  buckets of width ``gstep``.  Blocks stay COMPRESSED in HBM when it
  pays (round 5): uniform-phase blocks elide the ts plane entirely
  (reconstructed on device from one phase row), and value planes pack
  into fixed-width XOR-residual classes decoded inside the serving
  program — the reference's serve-compressed-vectors-in-place trick
  (BlockManager.scala:142, doc/compression.md) restated with static
  shapes for XLA.
- Blocks are built once from the partitions' frozen chunks (host decode ->
  one ``device_put``) and then serve every later query from HBM; a repeat
  query performs **zero** host->device chunk transfer.
- Blocks are evicted oldest-first when the arena exceeds its byte budget
  (``StoreConfig.device_cache_bytes``) — reclaim-on-demand in time order,
  like the reference's time-ordered block lists.
- Chunk freezes invalidate overlapping blocks (the shard wires
  ``partition.on_freeze`` to :meth:`note_freeze`); the mutable write-buffer
  tail is served through a version-tagged tail block rebuilt only when new
  data arrived.

The grid layout contract matches :mod:`filodb_tpu.ops.grid`: row ``c``
holds the (single) sample with ``ts in (epoch0+(c-1)*gstep, epoch0+c*gstep]``.
Partitions whose samples violate the one-per-bucket invariant disable the
grid for this cache generation; queries then fall back to the general
:mod:`filodb_tpu.ops.windows` path, so the fast path is never wrong, only
absent.
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Optional, Sequence

import numpy as np

from filodb_tpu.ops.grid import (DENSE_ONLY_OPS, PHASE_OPS, TS_FREE_OPS,
                                 GridQuery, max_k_for, on_tpu_backend,
                                 phase_eligible, supports_grid)
from filodb_tpu.query.logical import RangeFunctionId as F
from filodb_tpu.utils import devicewatch
from filodb_tpu.utils.devicewatch import FLIGHT, LEDGER

BLOCK_BUCKETS = 128
_LANE_PAD = 128
_I32_SPAN = 2**31 - 2

# range functions the aligned grid can serve, mapped to the fused
# kernel op (ops/grid.py GridQuery.op); None = the bare instant
# selector's staleness lookback (last sample in the window)
_GRID_OPS = {
    F.RATE: "rate", F.INCREASE: "increase",
    F.SUM_OVER_TIME: "sum", F.COUNT_OVER_TIME: "count",
    F.AVG_OVER_TIME: "avg", F.MIN_OVER_TIME: "min",
    F.MAX_OVER_TIME: "max", F.LAST_OVER_TIME: "last",
    F.STDDEV_OVER_TIME: "stddev", F.STDVAR_OVER_TIME: "stdvar",
    F.CHANGES: "changes", F.RESETS: "resets",
    F.IRATE: "irate", F.IDELTA: "idelta",
    F.DERIV: "deriv", F.PREDICT_LINEAR: "predict_linear",
    F.Z_SCORE: "zscore",
    F.QUANTILE_OVER_TIME: "quantile", F.MAD_OVER_TIME: "mad",
    F.DELTA: "delta", F.TIMESTAMP: "timestamp",
    F.HOLT_WINTERS: "holt_winters",
    None: "last",
}

# timestamp() outputs epoch-relative seconds from the kernel (int32 grid
# timestamps); the serving path re-bases to absolute and excludes the op
# from the fused grouped reduce (summing absolute timestamps would need
# a count-scaled re-base)
_REBASE_OPS = {"timestamp"}

# grid ops taking scalar function arguments: op -> arity
# (GridQuery.farg / farg2)
_ARG_OPS = {"predict_linear": 1, "quantile": 1, "holt_winters": 2}

# the subset defined on first-class histogram columns (per-bucket
# semantics; matches the host path in query/rangefns.py _HIST_FNS)
_HIST_GRID_FNS = {F.RATE, F.INCREASE, F.SUM_OVER_TIME, None}


_ONEHOT_MAX_G = 2048  # one-hot matmul reduce beyond this costs too much VMEM

# ---------------------------------------------------------------------------
# compressed HBM residents (round 5, VERDICT r4 #4; fused in ISSUE 3)
#
# Grid blocks may keep their VALUE plane in XOR-class form and (for
# uniform-phase data) drop the ts plane entirely; both decode ON DEVICE
# inside the serving program (reference: queries read compressed
# BinaryVectors straight from block memory, BlockManager.scala:142,
# doc/compression.md:96-99).  The layout lives in codecs/xorgrid.py —
# the encode side guarantees the lane-block alignment and meta tiles
# the FUSED Pallas kernels (ops/grid.py rate_grid_packed) rely on, so
# eligible queries decode inside the grid kernel itself and HBM serves
# ~2.5 B/sample instead of 4; the pure-XLA decode below remains the
# path for multi-block spans, f64 (CPU) residents, and ts-streaming
# ops.  Incompressible planes stay raw; a block only compresses when
# it saves >= 25%.
# ---------------------------------------------------------------------------

# tests flip this to exercise the fused packed kernels on CPU CI
# (devicestore then passes interpret=True through to pallas); never set
# in production — on a TPU backend the kernels compile natively
_PACKED_INTERPRET = False
# tripped if the fused packed program ever fails to compile/run on this
# backend: serving falls back to the XLA decode path permanently (the
# fused kernel is an optimization, never a correctness dependency)
_PACKED_BROKEN = False


def _seg_vals_device(seg):
    """Traced: materialize one value-plane segment — raw array pass-
    through or on-device XOR-class decode."""
    if not isinstance(seg, dict):
        return seg
    import jax.numpy as jnp
    from jax import lax

    raw = seg["raw"]
    word = jnp.uint32 if raw.dtype.itemsize == 4 else jnp.uint64
    parts = []
    for w in (8, 16, 32):
        p = seg.get(f"p{w}")
        if p is None:
            continue
        parts.append(p.astype(word) << seg[f"z{w}"].astype(word)[None, :])
    parts.append(lax.bitcast_convert_type(raw, word))
    u = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    u = lax.associative_scan(jnp.bitwise_xor, u, axis=0)
    u = u ^ lax.bitcast_convert_type(seg["first"], word)[None, :]
    vals = lax.bitcast_convert_type(u, raw.dtype)
    return vals[:, seg["inv"]]


def _seg_ts_device(seg):
    """Traced: materialize one ts-plane segment — raw int32 array or the
    uniform-phase reconstruction ``(c-1)*g + phase`` (the block proved
    every lane uniform-phase at build time, so this is bit-exact for
    every cell the kernels read through the finite-value mask)."""
    if not isinstance(seg, dict):
        return seg
    import jax.numpy as jnp

    rows = jnp.arange(BLOCK_BUCKETS, dtype=jnp.int32)[:, None]
    return seg["base"] + rows * seg["g"] + seg["phase"][None, :]


def hist_slot_garr(garr: np.ndarray, lane_idx: np.ndarray,
                   gid_arr: np.ndarray, hb: int) -> None:
    """Fill ``garr`` in place with the histogram group-slot layout:
    series slot s, bucket j -> group slot gid*hb + j, so a plain
    segment reduce sums each bucket lane independently (the bucket-wise
    hist sum).  ONE definition — the single-device fused path and the
    mesh staging must never drift on this layout."""
    cols = lane_idx[:, None] * hb + np.arange(hb)[None, :]
    garr[cols] = gid_arr[:, None] * hb + np.arange(hb)


def hist_planes_split(both, num_groups: int, hb: int):
    """[2, G*hb, T] sum+count planes -> ``(hist_sum [G, T, hb],
    count [G, T])`` (count from the +Inf total bucket).  np/jnp
    agnostic — ONE definition shared by the host present path below and
    the fused mesh histq program (parallel/meshgrid.py), so the
    on-device cluster-wide quantile and the scatter-gather oracle read
    bucket state through the same reshape."""
    G, T = num_groups, both.shape[-1]
    hist_sum = both[0].reshape(G, hb, T).transpose(0, 2, 1)
    count = both[1].reshape(G, hb, T)[:, -1, :]
    return hist_sum, count


def hist_state_from_planes(both: np.ndarray, num_groups: int, hb: int,
                           tops) -> dict:
    """[2, G*hb, T] sum+count planes -> the MomentAggregator hist state
    ({"hist_sum": [G, T, hb], "count": [G, T] from the total bucket},
    plus bucket_tops).  Shared by the single-device and mesh paths."""
    hist_sum, count = hist_planes_split(both, num_groups, hb)
    return {"hist_sum": hist_sum, "count": count, "bucket_tops": tops}


def _grouped_reduce_impl(stepped, garr, num_groups, op):
    """Device-side segment reduce of the grid kernel's [T, lanes] output:
    only [G, T] partials ever cross the host link.  ``garr`` maps lane ->
    group (num_groups = drop bucket for unrequested/padding lanes).

    For sum/count at modest G the reduce is a one-hot matmul so it runs
    on the MXU — TPU scatter-adds (segment_sum) serialize and dominate
    the served latency otherwise."""
    import jax
    import jax.numpy as jnp

    from filodb_tpu.ops import aggregate as segops

    v = stepped.T                                # [lanes, T]
    G = num_groups
    if op in ("sum", "avg", "count", "moments"):
        fin = jnp.isfinite(v)
        vz = jnp.where(fin, v, 0.0)
        fz = fin.astype(v.dtype)
        planes = [vz, fz]
        if op == "moments":                      # stddev/stdvar partials
            planes.append(vz * vz)
        if G + 1 <= _ONEHOT_MAX_G:
            onehot = (garr[:, None] ==
                      jnp.arange(G, dtype=garr.dtype)[None, :]
                      ).astype(v.dtype)          # [lanes, G]
            # HIGHEST precision: the TPU default truncates f32 matmul
            # inputs to bf16, which would make fused sums diverge from
            # the host segment-sum path by up to ~0.4%
            hp = jax.lax.Precision.HIGHEST
            outs = [jnp.matmul(onehot.T, p, precision=hp)  # MXU: [G, T]
                    for p in planes]
        else:
            outs = [jax.ops.segment_sum(p, garr, G + 1)[:G]
                    for p in planes]
        return jnp.stack(outs)                   # one readback downstream
    if op == "min":
        return segops.seg_min(v, garr, G + 1)[:G]
    if op == "max":
        return segops.seg_max(v, garr, G + 1)[:G]
    raise ValueError(f"unsupported grouped op {op}")


_FUSED_PROGS: dict = {}


def _fused_progs():
    """The two one-dispatch query programs, jitted lazily.  A sync-mode
    tunnel pays a round-trip per dispatched XLA program, so the whole
    serving pipeline — block concat, row slice, grid kernel, segment
    reduce — must be ONE program: splitting it into eager slices + two
    jit calls costs 4-6 round-trips per query (measured: 160 -> ~60 ms
    at 20k series)."""
    if _FUSED_PROGS:
        return _FUSED_PROGS
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from filodb_tpu.ops.grid import (rate_grid_auto, rate_grid_batch_impl,
                                     rate_grid_packed)

    def _concat(parts, decode):
        if not parts:
            return None    # phase mode: no ts plane in the program
        segs = [decode(s) for s in parts]
        return segs[0] if len(segs) == 1 \
            else jnp.concatenate(segs, axis=0)

    def _sliced(parts, row0, nrows, decode):
        all_ = _concat(parts, decode)
        if all_ is None:
            return None
        return lax.dynamic_slice_in_dim(all_, row0, nrows, axis=0)

    @functools.partial(devicewatch.jit, program="devicestore.series",
                       static_argnames=("q", "lanes", "nrows"))
    def series_prog(ts_parts, val_parts, row0, steps0, phase=None, *,
                    q, lanes, nrows):
        ts_sl = _sliced(ts_parts, row0, nrows, _seg_ts_device)
        val_sl = _sliced(val_parts, row0, nrows, _seg_vals_device)
        return rate_grid_auto(ts_sl, val_sl, steps0, q, lanes, phase=phase)

    @functools.partial(devicewatch.jit, program="devicestore.grouped",
                       static_argnames=("q", "lanes", "nrows",
                                        "num_groups", "op"))
    def grouped_prog(ts_parts, val_parts, row0, steps0, garr, phase=None,
                     *, q, lanes, nrows, num_groups, op):
        ts_sl = _sliced(ts_parts, row0, nrows, _seg_ts_device)
        val_sl = _sliced(val_parts, row0, nrows, _seg_vals_device)
        stepped = rate_grid_auto(ts_sl, val_sl, steps0, q, lanes,
                                 phase=phase)
        return _grouped_reduce_impl(stepped, garr, num_groups, op)

    # fused compressed-resident programs (ISSUE 3 tentpole): the XOR-
    # class decode runs INSIDE the grid kernel, so HBM serves the
    # packed ~2.5 B/sample planes — no decoded plane is ever written.
    # row0 is static (the kernel's window slices need compile-time
    # sublane offsets); outputs are in PACKED lane order.
    @functools.partial(devicewatch.jit,
                       program="devicestore.series_packed",
                       static_argnames=("q", "row0", "use_phase",
                                        "interpret"))
    def series_prog_packed(packed, steps0, *, q, row0, use_phase,
                           interpret=False):
        return rate_grid_packed(packed, steps0, q, row0=row0,
                                interpret=interpret, use_phase=use_phase)

    @functools.partial(devicewatch.jit,
                       program="devicestore.grouped_packed",
                       static_argnames=("q", "row0", "use_phase",
                                        "num_groups", "op", "interpret"))
    def grouped_prog_packed(packed, steps0, garr, *, q, row0, use_phase,
                            num_groups, op, interpret=False):
        stepped = rate_grid_packed(packed, steps0, q, row0=row0,
                                   interpret=interpret,
                                   use_phase=use_phase)
        return _grouped_reduce_impl(stepped, garr, num_groups, op)

    # fleet-batched programs (ISSUE 20): B shape-compatible queries
    # against the SAME resident planes — decode + concat happen ONCE,
    # then the per-member row slice and grid kernel run vmapped over
    # the leading member axis, so a whole co-arrival group costs one
    # launch and one stacked readback instead of B of each.
    @functools.partial(devicewatch.jit,
                       program="devicestore.series_batch",
                       static_argnames=("q", "lanes", "nrows"))
    def series_batch_prog(ts_parts, val_parts, row0s, steps0s,
                          phase=None, *, q, lanes, nrows):
        ts_all = _concat(ts_parts, _seg_ts_device)
        val_all = _concat(val_parts, _seg_vals_device)
        ts_b = None if ts_all is None else jax.vmap(
            lambda r: lax.dynamic_slice_in_dim(ts_all, r, nrows,
                                               axis=0))(row0s)
        val_b = jax.vmap(
            lambda r: lax.dynamic_slice_in_dim(val_all, r, nrows,
                                               axis=0))(row0s)
        return rate_grid_batch_impl(ts_b, val_b, steps0s, q, lanes,
                                    phase=phase)

    @functools.partial(devicewatch.jit,
                       program="devicestore.grouped_batch",
                       static_argnames=("q", "lanes", "nrows",
                                        "num_groups", "op"))
    def grouped_batch_prog(ts_parts, val_parts, row0s, steps0s, garr,
                           phase=None, *, q, lanes, nrows, num_groups,
                           op):
        ts_all = _concat(ts_parts, _seg_ts_device)
        val_all = _concat(val_parts, _seg_vals_device)

        def one(r, s):
            ts_sl = None if ts_all is None else \
                lax.dynamic_slice_in_dim(ts_all, r, nrows, axis=0)
            val_sl = lax.dynamic_slice_in_dim(val_all, r, nrows, axis=0)
            stepped = rate_grid_auto(ts_sl, val_sl, s, q, lanes,
                                     phase=phase)
            return _grouped_reduce_impl(stepped, garr, num_groups, op)
        return jax.vmap(one)(row0s, steps0s)

    _FUSED_PROGS["series"] = series_prog
    _FUSED_PROGS["grouped"] = grouped_prog
    _FUSED_PROGS["series_packed"] = series_prog_packed
    _FUSED_PROGS["grouped_packed"] = grouped_prog_packed
    _FUSED_PROGS["series_batch"] = series_batch_prog
    _FUSED_PROGS["grouped_batch"] = grouped_batch_prog
    return _FUSED_PROGS


def _run_packed(dispatch):
    """Run a fused packed-kernel dispatch; on the FIRST failure (a
    backend whose Mosaic build rejects the decode ops) trip the
    process-wide breaker and return None so the caller falls back to
    the XLA decode path — the fused kernel is an optimization, never a
    correctness dependency."""
    global _PACKED_BROKEN
    if _PACKED_BROKEN:
        # memoized plans keep their .packed field after the breaker
        # trips; never re-attempt the failing (uncached) Pallas build
        return None
    try:
        return dispatch()
    except Exception as e:
        import logging
        _PACKED_BROKEN = True
        FLIGHT.record("breaker.trip", breaker="packed_kernel",
                      error=repr(e)[:200])
        logging.getLogger(__name__).exception(
            "fused packed grid kernel failed; falling back to the XLA "
            "decode path for this process")
        return None


_HBM_METRIC = None


def _hbm_metric():
    global _HBM_METRIC
    if _HBM_METRIC is None:
        from filodb_tpu.utils.observability import query_metrics
        _HBM_METRIC = query_metrics()["hbm_read_bytes"]
    return _HBM_METRIC


def _note_hbm(plan: "_GridPlan") -> None:
    """Account the serving program's HBM reads by resident format:
    the filodb_query_hbm_read_bytes_total counter (format label) and
    the active query's QueryStats.hbm_read_bytes buckets — so the
    format actually serving traffic is observable (ISSUE 3; the
    compressed-hist bucket-plane format is ISSUE 14)."""
    if not (plan.hbm_dense or plan.hbm_comp or plan.hbm_comp_hist):
        return
    m = _hbm_metric()
    if plan.hbm_dense:
        m.inc(plan.hbm_dense, format="dense")
    if plan.hbm_comp:
        m.inc(plan.hbm_comp, format="compressed")
    if plan.hbm_comp_hist:
        m.inc(plan.hbm_comp_hist, format="compressed-hist")
    from filodb_tpu.query.exec import active_exec_ctx
    ctx = active_exec_ctx()
    if ctx is not None:
        ctx.note_counts(hbm_dense=plan.hbm_dense,
                        hbm_compressed=plan.hbm_comp,
                        hbm_hist=plan.hbm_comp_hist)


def _note_kernel_bytes(prog_fn, plan: "_GridPlan") -> None:
    """Kernel flight deck (ISSUE 15): attribute the plan's HBM reads to
    the fused program that actually dispatched — the numerator of the
    per-program live achieved-bytes/s join on /admin/kernels.  The
    program name comes off the wrapped callable itself
    (``devicewatch.jit`` stamps ``_program``), so a rename at the jit
    declaration can never decouple the bytes/launches join."""
    program = getattr(prog_fn, "_program", None)
    if program:
        devicewatch.KERNEL_TIMER.note_bytes(
            program, plan.hbm_dense + plan.hbm_comp + plan.hbm_comp_hist)


class _GridPlan(NamedTuple):
    """Everything needed to dispatch one fused serving program."""

    ts_parts: tuple       # device arrays, one per covered block; () when
                          # the program needs no ts plane (phase mode)
    val_parts: tuple
    row0: int             # first slice row in the concatenated blocks
    steps0_rel: int       # first window end, epoch-relative ms
    q: "GridQuery"
    lane_mult: int
    nrows: int
    ncols: int
    lane_idx: np.ndarray  # requested pid -> lane slot, in request order
    phase: object = None  # [ncols] int32 device array (uniform-phase mode)
    segs: tuple = ()      # the covered _Block objects (mesh staging)
    # fused compressed-resident dispatch (ISSUE 3): when set, the scan
    # runs the packed kernels on this single block's class planes —
    # decode happens inside the kernel, output in packed lane order
    packed: object = None          # the block's XOR-class plane dict
    packed_row0: int = 0           # static row offset within the block
    packed_use_phase: bool = False
    packed_inv: object = None      # np [ncols] orig lane -> packed pos
    # logical HBM bytes the serving program reads, by resident format
    # (QueryStats.hbm_read_bytes; approximate: whole covered planes).
    # Histogram caches account their packed planes under the dedicated
    # "compressed-hist" format (ISSUE 14) so the bucket-plane substrate
    # is observable separately from scalar compressed residents.
    hbm_dense: int = 0
    hbm_comp: int = 0
    hbm_comp_hist: int = 0


class MeshShardPlan(NamedTuple):
    """One shard's device-resident contribution to a mesh grid query."""

    ts: object            # [nrows, ncols] int32, on this shard's device
    vals: object          # [nrows, ncols] f32/f64, same device
    phase: object         # [ncols] int32 device array or None
    garr: np.ndarray      # [ncols] int32 col -> group slot (-1 = drop;
    #                       hist: slot = gid*hb + bucket)
    q: "GridQuery"
    steps0_rel: int
    ncols: int
    device: object
    hb: int = 0           # bucket lanes per series (0 = scalar column)
    bucket_tops: object = None     # [hb] np array (hist only)
    col_pids: object = None        # [ncols] int64 partition id per lane
    #                                (-1 = unassigned); lets the k-slot
    #                                mesh path resolve selected lanes back
    #                                to series tags (scalar columns only)


_MESH_STAGE_FN = None


def _mesh_stage(ts_parts, val_parts: tuple, row0: int, nrows: int):
    """Device-side block concat + row slice for the mesh path: inputs
    are committed to the shard's device, so the outputs stay there (a
    pure HBM->HBM copy, no host transfer).  Jitted per shape.

    ``ts_parts=None`` (uniform-phase plans, ISSUE 3) stages only the
    value plane — the mesh program's phase mode reconstructs timestamp
    geometry from the per-lane phase row, so no [nrows, ncols] ts plane
    is ever materialized or assembled for those queries (half the
    staged resident bytes)."""
    global _MESH_STAGE_FN
    if _MESH_STAGE_FN is None:
        import functools

        import jax.numpy as jnp
        from jax import lax

        @functools.partial(devicewatch.jit,
                           program="devicestore.mesh_stage",
                           static_argnames=("nrows",))
        def stage(ts_parts, val_parts, row0, *, nrows):
            val_segs = [_seg_vals_device(s) for s in val_parts]
            val_all = val_segs[0] if len(val_segs) == 1 \
                else jnp.concatenate(val_segs, axis=0)
            val_sl = lax.dynamic_slice_in_dim(val_all, row0, nrows, axis=0)
            if ts_parts is None:
                return None, val_sl
            ts_segs = [_seg_ts_device(s) for s in ts_parts]
            ts_all = ts_segs[0] if len(ts_segs) == 1 \
                else jnp.concatenate(ts_segs, axis=0)
            return (lax.dynamic_slice_in_dim(ts_all, row0, nrows, axis=0),
                    val_sl)
        _MESH_STAGE_FN = stage
    return _MESH_STAGE_FN(ts_parts, val_parts, row0, nrows=nrows)


def _ids_fingerprint(part_ids) -> int:
    """Content hash guarding the id()-keyed prep cache against address
    reuse and keying the big-K deny set.  Position-dependent mix over
    EVERY id (vectorized: ~1ms/1M ids, small next to the query it
    gates) — a sampled fingerprint could let one lookup result's
    denial suppress the dense fast path for an unrelated id list of
    the same length (ADVICE r2)."""
    n = len(part_ids)
    ids = np.asarray(part_ids, dtype=np.uint64)
    with np.errstate(over="ignore"):
        mixed = (ids + np.arange(1, n + 1, dtype=np.uint64)) \
            * np.uint64(0x9E3779B97F4A7C15)
    return n * 1_000_003 + int(np.bitwise_xor.reduce(mixed))


class _Block:
    """One resident time block: device arrays [BLOCK_BUCKETS, lanes].

    ``fmin/fmax/fcnt`` (host numpy, per lane) record the filled-bucket
    range so queries can prove the dense-lane contract (ops/grid.py
    GridQuery.dense) without touching device data: a lane is
    *contiguous* iff fcnt == fmax - fmin + 1, dense over local rows
    [a, b] iff contiguous and fmin <= a <= b <= fmax, and empty over
    [a, b] iff fcnt == 0 or fmax < a or fmin > b.

    ``pmin/pmax`` (host numpy, per lane) record the within-bucket scrape
    offset range (``ts - bucket_start``, in (0, gstep]) of the lane's
    filled cells: a lane with ``pmin == pmax`` in every covered block is
    UNIFORM-PHASE and rate/increase/delta queries reconstruct its
    timestamps from one phase scalar — the ts plane is never streamed
    (ops/grid.py PHASE_OPS)."""

    __slots__ = ("ts", "vals", "lanes", "nbytes", "last_used",
                 "fmin", "fmax", "fcnt", "pmin", "pmax", "staged_hi",
                 "ts_desc", "width", "pack_inv")

    def __init__(self, ts, vals, lanes: int, seq: int, fill_stats,
                 phase_stats, staged_hi: int, ts_desc=None,
                 nbytes: Optional[int] = None, width: int = 0,
                 pack_inv=None):
        # ts: device int32 plane, or None when every lane proved
        # uniform-phase at build time — ``ts_desc`` then reconstructs it
        # on device.  vals: device plane, or the XOR-class dict.
        self.ts = ts
        self.vals = vals
        self.lanes = lanes
        self.width = width          # columns (lanes * hist stride)
        self.nbytes = nbytes if nbytes is not None else \
            int(ts.size * ts.dtype.itemsize + vals.size * vals.dtype.itemsize)
        self.last_used = seq
        self.fmin, self.fmax, self.fcnt = fill_stats
        self.pmin, self.pmax = phase_stats
        self.ts_desc = ts_desc
        # host copy of the pack's original-lane -> packed-position map
        # (codecs/xorgrid.py); None for decoded-plane blocks.  Lets the
        # fused packed kernels run in packed lane order while callers
        # compose their lane indirections host-side.
        self.pack_inv = pack_inv
        # lanes < staged_hi were populated at build time; a lane at or
        # beyond it belongs to a partition that joined later and is NOT
        # represented in this block (it must rebuild, never serve NaN)
        self.staged_hi = staged_hi

    @property
    def ts_seg(self):
        """The ts-plane segment descriptor the serving program consumes."""
        return self.ts if self.ts is not None else self.ts_desc

    def dense_or_empty(self, a: int, b: int):
        """Per-lane (dense, empty) bool masks: lane is provably dense
        over local rows [a, b] / provably empty there."""
        contiguous = self.fcnt == self.fmax - self.fmin + 1
        dense = contiguous & (self.fmin <= a) & (self.fmax >= b)
        empty = (self.fcnt == 0) | (self.fmax < a) | (self.fmin > b)
        return dense, empty


class DeviceGridCache:
    """Per-(shard, schema, value-column) device grid with eviction."""

    def __init__(self, shard, schema_hash: int, column_id: int,
                 budget_bytes: int, gstep_ms: Optional[int] = None,
                 hist: bool = False):
        self._shard = shard
        self.schema_hash = schema_hash
        self.column_id = column_id
        self.budget = budget_bytes
        # HBM-ledger owner tag for every resident byte this cache
        # commits (devicewatch: filodb_device_hbm_bytes{owner,format})
        self.owner = (f"grid:{getattr(shard, 'dataset', '?')}/"
                      f"{getattr(shard, 'shard_num', '?')}:c{column_id}")
        self.gstep = gstep_ms          # None until detected
        # histogram columns: each partition slot spans ``hb`` device
        # columns (one per cumulative bucket); the SAME scalar kernel
        # then computes per-bucket rates (the reference's per-bucket
        # HistRateFunction semantics, rangefn/RangeFunction.scala:376)
        self.hist = hist
        self.hb: Optional[int] = None          # bucket lanes per slot
        self.bucket_tops: Optional[np.ndarray] = None
        self.epoch0: Optional[int] = None
        self.lane_of: dict[int, int] = {}
        self._next_lane = 0
        self.blocks: dict[int, _Block] = {}
        self._tails: dict[int, tuple[int, _Block]] = {}  # bi -> (ver, blk)
        self.version = 0               # bumped on invalidating freezes
        # quarantine epoch the resident blocks were staged under: a
        # chunk quarantined AFTER staging must stop being served, so a
        # changed epoch drops every block for a re-stage through the
        # (exclusion-applying) partition read path
        self._quarantine_epoch = -1
        self.disabled_until_version = -1
        self._disable_count = 0        # exponential re-try backoff
        self._disk_floor: Optional[tuple[int, int]] = None  # (ver, floor_ms)
        self._preps: dict[int, dict] = {}   # id(part_ids) -> prep
        # large-K shapes that failed the dense proof: deny until data
        # changes, so a refreshing dashboard doesn't re-pay speculative
        # block staging every cycle
        self._bigk_deny: dict[tuple, tuple] = {}
        # (bi_lo, bi_hi, version) -> (host phases, device phases): the
        # uniform-phase vector for the frozen block range (see
        # _phase_device); stale keys never match, single-entry by design
        self._phase_memo: dict[tuple, tuple] = {}
        # mesh staging memo: (row0, nrows) -> (parts identity, staged
        # ts, staged vals) — see mesh_plan
        self._mesh_stage_memo: dict[tuple, tuple] = {}
        # full-plan memo: a repeat dashboard query re-pays the dense/
        # phase proof walk (~40ms at 20k lanes) without it.  Keys carry
        # every invalidation axis (cache version, ingest epoch, removal
        # epoch, id-list fingerprint); cleared on freeze/repin/reclaim
        self._plan_memo: dict[tuple, "_GridPlan"] = {}
        self._seq = 0
        self._lock = threading.Lock()
        # stats
        self.builds = 0
        self.hits = 0
        self.dense_hits = 0
        self.evictions = 0

    # ------------------------------------------------------------ bookkeeping

    @property
    def bytes_resident(self) -> int:
        n = sum(b.nbytes for b in self.blocks.values())
        n += sum(blk.nbytes for _v, blk in self._tails.values())
        return n

    def note_repin(self) -> None:
        """The shard was pinned to a different mesh device: resident
        blocks (and the device-side memos holding arrays) live on the
        old device — drop them so they rebuild in place on the new one
        (shard.pin_grid_device)."""
        with self._lock:
            n = len(self.blocks) + len(self._tails)
            if n:
                LEDGER.note_eviction(self.owner, "epoch_purge", n=n,
                                     nbytes=self.bytes_resident)
            self.blocks.clear()
            self._tails.clear()
            self._phase_memo.clear()
            self._mesh_stage_memo.clear()
            self._plan_memo.clear()
            self.version += 1

    def note_freeze(self, cs) -> None:
        """A chunk froze: blocks overlapping it are stale (a lagging series
        back-filled an old bucket), and the tail moved.  (The shard bumps
        its ``ingest_epoch`` — our tail version — separately.)"""
        with self._lock:
            self._tails.clear()
            self._plan_memo.clear()       # tail plans reference old epoch
            if self.gstep is None or self.epoch0 is None:
                return
            lo_block = (cs.info.start_time - self.epoch0) // (
                self.gstep * BLOCK_BUCKETS)
            stale = [bi for bi in self.blocks if bi >= lo_block]
            nbytes = sum(self.blocks[bi].nbytes for bi in stale)
            for bi in stale:
                del self.blocks[bi]
            if stale:
                LEDGER.note_eviction(self.owner, "epoch_purge",
                                     n=len(stale), nbytes=nbytes)
                self.version += 1

    _STD_STEPS = (1_000, 2_000, 5_000, 10_000, 15_000, 30_000, 60_000,
                  120_000, 300_000, 600_000, 900_000, 1_800_000, 3_600_000)

    def _detect_gstep(self, part) -> Optional[int]:
        """Median inter-sample delta snapped to the nearest standard scrape
        interval (jitter skews the raw median; the block build verifies the
        one-sample-per-bucket invariant regardless)."""
        ts, _ = part.read_range(0, 2**62, self.column_id)
        if len(ts) < 3:
            return None
        deltas = np.diff(ts)
        deltas = deltas[deltas > 0]
        if len(deltas) == 0:
            return None
        med = float(np.median(deltas))
        best = min(self._STD_STEPS, key=lambda c: abs(c - med))
        if abs(best - med) <= 0.5 * best:
            return best
        return int(med)

    def _disable(self) -> None:  # holds-lock: _lock
        """Turn the fast path off; retries back off exponentially so a
        shard whose frozen history permanently violates the layout
        invariant doesn't re-stage a full block on every query."""
        self._disable_count += 1
        backoff = 2 ** min(self._disable_count, 16)
        self.disabled_until_version = self._shard.ingest_epoch + backoff
        n = len(self.blocks) + len(self._tails)
        if n:
            LEDGER.note_eviction(self.owner, "epoch_purge", n=n,
                                 nbytes=self.bytes_resident)
        self.blocks.clear()
        self._tails.clear()
        self._plan_memo.clear()            # plans pin the dropped blocks
        # re-probe the bucket scheme on the next attempt: a widened
        # histogram (16 -> 20 buckets) must not disable the fast path
        # forever once the narrow chunks age out
        self.hb = None
        self.bucket_tops = None

    # ---------------------------------------------------------------- serving

    def scan_rate(self, part_ids: Sequence[int], func: F, steps0: int,
                  nsteps: int, step_ms: int, window_ms: int,
                  fargs: tuple = ()):
        """Serve any _GRID_OPS window function (rate/increase, the
        *_over_time family, the bare instant selector's last-sample scan)
        on the query step grid from device-resident blocks.  Returns
        values ``[S_req, T]`` (``[S_req, T, hb]`` per-bucket for
        histogram columns) as numpy, or None when the fast path cannot
        serve this query (caller falls back).  Histogram results come
        paired with the bucket tops snapshotted under the same lock (a
        concurrent _disable may null ``self.bucket_tops``)."""
        if func not in _GRID_OPS:
            return None
        if self.hist and func not in _HIST_GRID_FNS:
            return None
        if len(fargs) != _ARG_OPS.get(_GRID_OPS[func], 0):
            return None        # unexpected / missing function argument
        with self._lock:
            plan = self._plan_locked(  # filolint: disable=blocking-under-lock — staging under the grid lock is the design: one query stages the block, contenders reuse it instead of duplicating the HBM upload; the breaker bounds pathological re-staging
                part_ids, func, steps0, nsteps,
                step_ms, window_ms, fargs)
            if plan is None:
                return None
            _note_hbm(plan)
            tops = np.asarray(self.bucket_tops) if self.hist else None
        # dispatch + readback run OUTSIDE the grid lock (the
        # scan_rate_grouped structure): the plan tuple holds live refs
        # to its device arrays, so a concurrent eviction cannot free
        # them mid-dispatch — and concurrent shape-compatible queries
        # can now rendezvous in the fleet batching tier
        vals = self._dispatch_series(plan)
        return vals, tops

    def scan_rate_grouped(self, part_ids: Sequence[int], func: F,
                          steps0: int, nsteps: int, step_ms: int,
                          window_ms: int, group_ids: Sequence[int],
                          num_groups: int, op: str = "sum",
                          fargs: tuple = ()):
        """Fused serve of ``agg by (g)(<grid window fn>(...))``: any
        _GRID_OPS window function under a distributive aggregate; the
        grid kernel's
        [T, lanes] output is segment-reduced ON DEVICE, so only the tiny
        [G, T] partials cross the host link (the full per-series matrix
        readback + re-upload otherwise dominates served latency on a
        tunnel-attached device).  Returns the mergeable partial state
        dict ({"sum","count"} / {"min"} / {"max"}) or None to fall back."""
        if func not in _GRID_OPS:
            return None
        if self.hist and (func not in _HIST_GRID_FNS or op != "sum"):
            return None
        if _GRID_OPS[func] in _REBASE_OPS:
            return None        # re-based ops skip the fused reduce
        if len(fargs) != _ARG_OPS.get(_GRID_OPS[func], 0):
            return None        # unexpected / missing function argument
        with self._lock:
            plan = self._plan_locked(  # filolint: disable=blocking-under-lock — staging under the grid lock is the design: one query stages the block, contenders reuse it instead of duplicating the HBM upload; the breaker bounds pathological re-staging
                part_ids, func, steps0, nsteps,
                step_ms, window_ms, fargs)
            if plan is None:
                return None
            stride = self.hb if self.hist else 1
            tops = np.asarray(self.bucket_tops) if self.hist else None
            garr = np.full(plan.ncols, num_groups * stride, dtype=np.int32)
            lane_idx = plan.lane_idx
            gid_arr = np.asarray(group_ids, dtype=np.int32)
            if stride == 1:
                garr[lane_idx] = gid_arr
            else:
                hist_slot_garr(garr, lane_idx, gid_arr, stride)
            _note_hbm(plan)
        def grouped_solo():
            # today's per-query fused reduce: also the batching tier's
            # bit-identical fallback (it IS the same dispatch)
            o = _fused_progs()["grouped"](
                plan.ts_parts, plan.val_parts, plan.row0, plan.steps0_rel,
                garr, plan.phase, q=plan.q, lanes=plan.lane_mult,
                nrows=plan.nrows, num_groups=num_groups * stride, op=op)
            _note_kernel_bytes(_fused_progs()["grouped"], plan)
            return np.asarray(o, dtype=np.float64)  # host-sync-ok: ONE blocked readback of the reduced partials — each blocked transfer pays the tunnel round-trip

        both = None
        if plan.packed is not None and not _PACKED_BROKEN:
            # packed lane order: scatter the group map through inv;
            # pack pad lanes keep the drop bucket
            n_pk = int(plan.packed["first"].shape[0])
            garr_pk = np.full(n_pk, num_groups * stride, dtype=np.int32)
            garr_pk[plan.packed_inv] = garr
            out = _run_packed(
                lambda: _fused_progs()["grouped_packed"](
                    plan.packed, plan.steps0_rel, garr_pk, q=plan.q,
                    row0=plan.packed_row0,
                    use_phase=plan.packed_use_phase,
                    num_groups=num_groups * stride, op=op,
                    interpret=_PACKED_INTERPRET))
            if out is not None:
                _note_kernel_bytes(_fused_progs()["grouped_packed"], plan)
                both = np.asarray(out, dtype=np.float64)  # host-sync-ok: the one designed readback of the fused reduce
        if both is None and not self.hist:
            both = self._batched_grouped(plan, garr,
                                         num_groups * stride, op,
                                         grouped_solo)
        if both is None:
            both = grouped_solo()
        if self.hist:
            # both: [2, G*hb, T] hist planes
            return hist_state_from_planes(both, num_groups, stride, tops)
        if op in ("sum", "avg", "count", "moments"):
            if op == "count":
                return {"count": both[1]}
            if op == "moments":
                return {"sum": both[0], "count": both[1],
                        "sumsq": both[2]}
            return {"sum": both[0], "count": both[1]}
        return {op: both}

    def _batched_grouped(self, plan, garr, num_groups, op, grouped_solo):
        """Offer a fused grouped reduce to the fleet batching tier.
        Members must share the group map exactly (``garr`` bytes are
        part of the key): the stacked program reduces every member
        with the one shared map.  Returns the member's float64
        partial-planes slice, or None for the solo fallback."""
        batcher = getattr(self._shard, "query_batcher", None)
        if batcher is None or not batcher.enabled:
            return None
        from filodb_tpu.query.exec import active_exec_ctx
        ctx = active_exec_ctx()
        qctx = ctx.query_context if ctx is not None else None
        key = ("grouped", tuple(id(p) for p in plan.ts_parts),
               tuple(id(p) for p in plan.val_parts), id(plan.phase),
               plan.q, plan.lane_mult, plan.nrows, num_groups, op,
               garr.tobytes())
        prog = _fused_progs()["grouped_batch"]

        def batch_launch(row0s, steps0s):
            out = _fused_progs()["grouped_batch"](
                plan.ts_parts, plan.val_parts, row0s, steps0s, garr,
                plan.phase, q=plan.q, lanes=plan.lane_mult,
                nrows=plan.nrows, num_groups=num_groups, op=op)
            _note_kernel_bytes(prog, plan)
            return np.asarray(out, dtype=np.float64)  # host-sync-ok: ONE stacked readback of the group's reduced partials

        return batcher.dispatch(key, plan.row0, plan.steps0_rel, qctx,
                                batch_launch, grouped_solo)

    def mesh_plan(self, part_ids: Sequence[int], func: F, steps0: int,
                  nsteps: int, step_ms: int, window_ms: int,
                  group_ids: Sequence[int], fargs: tuple = ()):
        """Plan + device-RESIDENT staging for the SPMD mesh serving path
        (parallel/meshgrid.py): the composition of the device grid with
        the shard-axis mesh (VERDICT r2 #1).  Returns a MeshShardPlan
        whose staged arrays live on this shard's pinned device — the
        mesh program reads them in place, zero per-query host upload —
        or None to fall back to the host-batch mesh path.

        Staging (block concat + row slice) runs once per (range,
        version) and is memoized by block identity, so a repeat
        dashboard query performs no device work here at all."""
        if func not in _GRID_OPS:
            return None
        if self.hist and func not in _HIST_GRID_FNS:
            return None
        op = _GRID_OPS[func]
        if op in _REBASE_OPS or len(fargs) != _ARG_OPS.get(op, 0):
            return None
        with self._lock:
            plan = self._plan_locked(  # filolint: disable=blocking-under-lock — staging under the grid lock is the design: one query stages the block, contenders reuse it instead of duplicating the HBM upload; the breaker bounds pathological re-staging
                part_ids, func, steps0, nsteps,
                step_ms, window_ms, fargs)
            if plan is None or not plan.segs:
                return None
            _note_hbm(plan)
            # phase mode never stages the ts plane: the SPMD program's
            # phase kernels reconstruct the geometry from the phase row
            phase_mode = plan.phase is not None
            key = (plan.row0, plan.nrows, phase_mode)
            parts_id = tuple(id(b) for b in plan.segs)
            memo = self._mesh_stage_memo.get(key)
            if memo is not None and memo[0] == parts_id:
                _, ts_st, val_st, segs_ref = memo
            else:
                ts_st, val_st = _mesh_stage(
                    None if phase_mode
                    else tuple(b.ts_seg for b in plan.segs),
                    tuple(b.vals for b in plan.segs),
                    plan.row0, nrows=plan.nrows)
                # the staged planes are HBM residents held by the memo:
                # they belong on the ledger like any committed block
                LEDGER.track(ts_st, owner=self.owner, fmt="mesh-staged")
                LEDGER.track(val_st, owner=self.owner, fmt="mesh-staged")
                if len(self._mesh_stage_memo) > 4:
                    self._mesh_stage_memo.clear()
                # hold the block refs: id() stays unambiguous while the
                # memo entry lives
                self._mesh_stage_memo[key] = (parts_id, ts_st, val_st,
                                              plan.segs)
            # -1 = unrequested lane; serve_grid_mesh rewrites it to the
            # query's drop bucket (num_groups isn't final until every
            # shard's group ids are assigned)
            garr = np.full(plan.ncols, -1, dtype=np.int32)
            gid_arr = np.asarray(group_ids, dtype=np.int32)
            col_pids = None
            if self.hist:
                hb = self.hb
                hist_slot_garr(garr, plan.lane_idx, gid_arr, hb)
                tops = np.asarray(self.bucket_tops)
            else:
                garr[plan.lane_idx] = gid_arr
                hb, tops = 0, None
                col_pids = np.full(plan.ncols, -1, dtype=np.int64)
                col_pids[plan.lane_idx] = np.asarray(part_ids,
                                                     dtype=np.int64)
            return MeshShardPlan(ts_st, val_st, plan.phase, garr, plan.q,
                                 plan.steps0_rel, plan.ncols,
                                 self._shard.grid_device, hb=hb,
                                 bucket_tops=tops, col_pids=col_pids)

    def _series_solo(self, plan):
        """Today's per-query series launch + readback: the unchanged
        chain every batching fallback demotes to (bit-identical by
        construction — it IS the same dispatch)."""
        stepped = _fused_progs()["series"](
            plan.ts_parts, plan.val_parts, plan.row0, plan.steps0_rel,
            plan.phase, q=plan.q, lanes=plan.lane_mult,
            nrows=plan.nrows)
        _note_kernel_bytes(_fused_progs()["series"], plan)
        return np.asarray(stepped)  # host-sync-ok: the designed stepped readback — only [T, lanes] crosses the host link

    def _batched_series(self, plan):
        """Offer this dispatch to the fleet batching tier (ISSUE 20).
        Returns the member's ``[T, lanes]`` readback slice, or None
        when the batcher declined (absent, disabled, breaker open,
        deadline too short, group demoted) — the caller then runs the
        unchanged solo chain."""
        batcher = getattr(self._shard, "query_batcher", None)
        if batcher is None or not batcher.enabled or self.hist:
            return None
        from filodb_tpu.query.exec import active_exec_ctx
        ctx = active_exec_ctx()
        qctx = ctx.query_context if ctx is not None else None
        # batch-compatibility at the device boundary: the SAME resident
        # planes (segment identity), the same static kernel signature,
        # and the same grid shape — members differ only in the traced
        # (row0, steps0) stack axis.  lane_idx may differ per member:
        # the series program computes every lane, request slicing is
        # host-side on the member's own slice.
        key = ("series", tuple(id(p) for p in plan.ts_parts),
               tuple(id(p) for p in plan.val_parts), id(plan.phase),
               plan.q, plan.lane_mult, plan.nrows)
        prog = _fused_progs()["series_batch"]

        def batch_launch(row0s, steps0s):
            out = _fused_progs()["series_batch"](
                plan.ts_parts, plan.val_parts, row0s, steps0s,
                plan.phase, q=plan.q, lanes=plan.lane_mult,
                nrows=plan.nrows)
            _note_kernel_bytes(prog, plan)
            return np.asarray(out)  # host-sync-ok: ONE stacked [B, T, lanes] readback serves the whole co-arrival group

        return batcher.dispatch(key, plan.row0, plan.steps0_rel, qctx,
                                batch_launch, lambda: self._series_solo(plan))

    def _dispatch_series(self, plan):
        lanes_req = plan.lane_idx
        used_packed = False
        out_np = None
        if plan.packed is not None:
            stepped = _run_packed(
                lambda: _fused_progs()["series_packed"](
                    plan.packed, plan.steps0_rel, q=plan.q,
                    row0=plan.packed_row0,
                    use_phase=plan.packed_use_phase,
                    interpret=_PACKED_INTERPRET))
            if stepped is not None:
                used_packed = True
                if not self.hist:
                    # packed lane order: compose request map with inv
                    lanes_req = plan.packed_inv[plan.lane_idx]
                _note_kernel_bytes(_fused_progs()["series_packed"], plan)
                out_np = np.asarray(stepped)  # host-sync-ok: the designed stepped readback — only [T, lanes] crosses the host link
        if out_np is None:
            out_np = self._batched_series(plan)
        if out_np is None:
            out_np = self._series_solo(plan)
        if self.hist:
            # COLUMN-granular indirection: a hist series' device columns
            # are lane*hb + bucket, so the pack's inv must compose with
            # the expanded column map, never the lane map alone
            cols = plan.lane_idx[:, None] * self.hb \
                + np.arange(self.hb)[None, :]
            if used_packed:
                cols = plan.packed_inv[cols]
            return out_np[:, cols].transpose(1, 0, 2)     # [S_req, T, hb]
        out = out_np[:, lanes_req].T                      # [S_req, T]
        if plan.q.op in _REBASE_OPS:
            # absolute window-end seconds, re-based in f64 on only the
            # requested lanes (the kernel emits window-relative seconds
            # so f32 stays exact)
            q = plan.q
            abs_s = (self.epoch0 + plan.steps0_rel
                     + np.arange(q.nsteps, dtype=np.int64)
                     * q.gstep_ms * q.stride) / 1000.0
            out = out.astype(np.float64) + np.where(
                np.isfinite(out), abs_s[None, :], 0.0)
        return out

    def _prep_for(self, part_ids, fp=None):
        """Memoized resolution of one lookup result: validate every pid
        (present + matching schema), assign lanes, and build the lane
        index.  Keyed on the lookup cache's array identity and the
        shard's partition removal epoch — repeated dashboard queries
        skip the 20k-dict walk entirely (it otherwise dominates
        host-side serving time at high cardinality).  ``fp`` lets the
        caller reuse an already-computed content fingerprint (the
        full-array hash is O(n))."""
        shard = self._shard
        n = len(part_ids)
        if n == 0:
            return None
        key = id(part_ids)
        if fp is None:
            fp = _ids_fingerprint(part_ids)
        prep = self._preps.get(key)
        if (prep is not None and prep["epoch"] == shard.removal_epoch
                and prep["fp"] == fp and prep["obj"] is part_ids):
            return prep
        # snapshot the epoch BEFORE the walk: an eviction racing the
        # validation must leave the prep stamped stale, not fresh
        epoch = shard.removal_epoch
        ids = [int(p) for p in part_ids]
        for pid in ids:
            part = shard.grid_partition(pid)
            if part is None:
                return None                    # evicted/paged: fall back
            if part.schema.schema_hash != self.schema_hash:
                return None                    # mixed-schema id list
            if pid not in self.lane_of:
                self.lane_of[pid] = self._next_lane
                self._next_lane += 1
        lane_idx = np.fromiter((self.lane_of[pid] for pid in ids),
                               dtype=np.int64, count=n)
        # "obj" holds a STRONG reference to the keyed array: id() stays
        # unambiguous for the entry's lifetime (no address reuse)
        prep = {"epoch": epoch, "fp": fp, "obj": part_ids, "ids": ids,
                "lane_idx": lane_idx}
        if len(self._preps) > 16:
            self._preps.clear()
        self._preps[key] = prep
        return prep

    def _plan_locked(self, part_ids, func, steps0, nsteps, step_ms,
                     window_ms, fargs=()):
        """Shared grid preamble: eligibility checks, block assembly, and
        the dense-contract proof.  Returns a :class:`_GridPlan` (device
        block refs + kernel config — NO device dispatch happens here; the
        caller runs ONE fused program) or None to fall back."""
        shard = self._shard
        if self.disabled_until_version >= shard.ingest_epoch:
            return None
        if len(part_ids) == 0:
            return None
        from filodb_tpu.integrity import QUARANTINE
        qepoch = QUARANTINE.epoch()
        if qepoch != self._quarantine_epoch:
            # blocks staged before a quarantine still CONTAIN the
            # quarantined chunk's rows — serving them would defeat the
            # exclusion the partition read path applies.  Quarantine is
            # rare; a full re-stage is the correct price.
            if self._quarantine_epoch >= 0 and (self.blocks or self._tails):
                LEDGER.note_eviction(self.owner, "integrity_quarantine",
                                     n=len(self.blocks) + len(self._tails),
                                     nbytes=self.bytes_resident)
                self.blocks.clear()
                self._tails.clear()
                self._plan_memo.clear()
                self._phase_memo.clear()
                self._mesh_stage_memo.clear()
                self.version += 1
            self._quarantine_epoch = qepoch
        # ALL eligibility checks run before _prep_for assigns lanes —
        # an ineligible query must not widen the lane count (that would
        # clear every resident block on the next eligible query)
        first = shard.grid_partition(int(part_ids[0]))
        if first is None or first.schema.schema_hash != self.schema_hash:
            return None
        if self.gstep is None:
            g = shard.config.grid_step_ms or self._detect_gstep(first)
            if not g or g <= 0:
                self._disable()                # don't re-detect every query
                return None
            self.gstep = g
        g = self.gstep
        # optimistic K cap: K-free ops may take large windows IF the
        # dense proof below succeeds (checked again once dense is known)
        if not supports_grid(window_ms, step_ms, g, nsteps,
                             max_k=max_k_for(_GRID_OPS[func], dense=True)):
            return None
        ids_fp = _ids_fingerprint(part_ids)
        deny_key = (func, window_ms, step_ms, ids_fp)
        if self._bigk_deny.get(deny_key) == \
                (self.version, shard.ingest_epoch):
            return None     # dense proof failed for this shape; data unchanged
        pkey = (func, steps0, nsteps, step_ms, window_ms, fargs, ids_fp,
                self.version, shard.ingest_epoch, shard.removal_epoch)
        cached = self._plan_memo.get(pkey)
        if cached is not None:
            self._seq += 1
            for blk in cached.segs:
                blk.last_used = self._seq
            self.hits += 1
            return cached
        if self.hist and self.hb is None:
            # probe a narrow leading slice for the bucket scheme — a
            # full-history read_range would decode (and memoize) every
            # chunk of the partition while holding the cache lock
            e0 = first.earliest_timestamp
            _pts, pvals = first.read_range(e0, e0 + 64 * g, self.column_id)
            buckets = pvals[0] if isinstance(pvals, tuple) else None
            if buckets is None or buckets.num_buckets == 0:
                self._disable()
                return None
            self.hb = int(buckets.num_buckets)
            self.bucket_tops = np.asarray(buckets.bucket_tops(), np.float64)
        if self.epoch0 is None:
            parts0 = (shard.grid_partition(int(pid)) for pid in part_ids)
            earliest = [p.earliest_timestamp for p in parts0 if p is not None]
            first_ts = min((t for t in earliest if t >= 0), default=-1)
            if first_ts < 0:
                return None
            self.epoch0 = (first_ts // g) * g
        if (steps0 - self.epoch0) % g != 0:
            return None                        # windows don't land on edges
        K = window_ms // g
        stride_r = step_ms // g                # query step in buckets
        # first window ends at steps0 and covers buckets [c0, c0+K-1];
        # window t starts stride_r buckets after window t-1
        c0 = (steps0 - self.epoch0) // g - K + 1
        c_last = c0 + (nsteps - 1) * stride_r + K - 1     # inclusive
        if c0 < 0:
            return None
        if (c_last + 1) * g > _I32_SPAN:
            return None                        # int32-relative overflow
        if hasattr(shard, "paged"):
            # ODP shard: residents may hold only their post-recovery tail,
            # with older chunks on disk; the grid would serve NaN there.
            # This runs BEFORE _prep_for so a rejected query cannot
            # widen the lane count (see the invariant above).
            parts = [shard.grid_partition(int(pid)) for pid in part_ids]
            if any(p is None for p in parts):
                return None
            lo_ms = self.epoch0 + (c0 - 1) * g
            if lo_ms < self._disk_floor_ms(parts):
                return None
        prep = self._prep_for(part_ids, fp=ids_fp)
        if prep is None:
            return None
        lanes = max(_LANE_PAD,
                    -(-self._next_lane // _LANE_PAD) * _LANE_PAD)
        if any(b.lanes != lanes for b in self.blocks.values()):
            self.blocks.clear()                # widths must match to concat
            self._tails.clear()
            self._plan_memo.clear()            # plans pin old-width blocks
        frozen_hi = self._frozen_high()
        bi_lo = c0 // BLOCK_BUCKETS
        bi_hi = c_last // BLOCK_BUCKETS
        # a block built BEFORE some requested partition got its lane has
        # that lane unstaged (all-NaN): it would pass the dense proof as
        # "empty" and silently serve NaN for a series that has data —
        # any such block must rebuild with the current lane roster
        need_hi = int(prep["lane_idx"].max()) + 1
        segments = []
        self._seq += 1
        for bi in range(bi_lo, bi_hi + 1):
            blk = self._block_for(bi, lanes, frozen_hi, need_hi)
            if blk is None:
                return None                    # invariant violated
            blk.last_used = self._seq
            segments.append(blk)
        self._evict(keep=set(range(bi_lo, bi_hi + 1)))

        row0 = c0 - bi_lo * BLOCK_BUCKETS
        nrows = c_last - c0 + 1
        ncols = segments[0].width
        # prove the dense-lane contract from per-block fill ranges: a
        # lane must be dense in EVERY covered block segment, or empty in
        # every one (a series that starts/stops mid-range is neither).
        # Only the REQUESTED lanes matter — per-lane outputs are
        # independent, and unrequested lanes are sliced away / mapped to
        # the drop bucket downstream.
        req = prep["lane_idx"]
        if self.hist:
            req = (req[:, None] * self.hb
                   + np.arange(self.hb)[None, :]).ravel()
        op = _GRID_OPS[func]
        # phase proof piggybacks on the dense walk: every requested lane
        # must be uniform-phase within each covered block AND carry the
        # SAME phase across blocks.  Tail blocks are excluded (their
        # contents change per ingest epoch; the memoized device phase
        # vector below would churn) — queries touching the tail keep the
        # ts-streaming kernels.  Final eligibility is grid.phase_eligible
        # on the built query (adds dense + K>=2); this is the cheap
        # pre-filter for the proof walk.
        want_phase = op in PHASE_OPS and K >= 2 and \
            bi_hi * BLOCK_BUCKETS + BLOCK_BUCKETS - 1 <= frozen_hi
        ph_req = np.full(len(req), -1, np.int64)
        ph_ok = want_phase
        all_dense = np.ones(len(req), bool)
        all_empty = np.ones(len(req), bool)
        for off, blk in zip(range(bi_lo, bi_hi + 1), segments):
            a = max(c0 - off * BLOCK_BUCKETS, 0)
            b = min(c_last - off * BLOCK_BUCKETS, BLOCK_BUCKETS - 1)
            d, e = blk.dense_or_empty(a, b)
            all_dense &= d[req]
            all_empty &= e[req]
            if ph_ok:
                nonempty = ~e[req]
                uniform = blk.pmin[req] == blk.pmax[req]
                bph = blk.pmin[req].astype(np.int64)
                conflict = nonempty & (ph_req >= 0) & (ph_req != bph)
                if (nonempty & ~uniform).any() or conflict.any():
                    ph_ok = False
                else:
                    ph_req = np.where(nonempty & (ph_req < 0), bph, ph_req)
        dense = bool((all_dense | all_empty).all())
        if (op in DENSE_ONLY_OPS and not dense) \
                or K > max_k_for(op, dense):
            # adjacency ops need every row present; large windows need
            # the proven-dense K-free path.  Either way, memoize the
            # denial so a refreshing dashboard doesn't re-stage blocks
            # every cycle; the data changing (version/epoch) retries.
            # The key includes the request fingerprint: a gappy series
            # set must not disable the fast path for a dense one that
            # happens to share the query shape.
            # LRU-on-write: re-denied hot shapes move to the back so the
            # overflow eviction below drops a stale one-off, not them
            self._bigk_deny.pop(deny_key, None)
            self._bigk_deny[deny_key] = (self.version, shard.ingest_epoch)
            if len(self._bigk_deny) > 64:
                self._bigk_deny.pop(next(iter(self._bigk_deny)))
            return None
        if dense:
            self.dense_hits += 1
        q = GridQuery(nsteps=nsteps, kbuckets=K, gstep_ms=g,
                      is_rate=(func == F.RATE), op=op,
                      dense=dense, stride=stride_r,
                      farg=float(fargs[0]) if fargs else 0.0,
                      farg2=float(fargs[1]) if len(fargs) > 1 else 0.0)
        phase_dev = None
        if ph_ok and phase_eligible(q):
            phase_dev = self._phase_device(ph_req, req, ncols,
                                           (bi_lo, bi_hi, self.version))
        # tall strided slices read more input rows per tile: keep the
        # VMEM footprint bounded by narrowing the lane tile
        lane_mult = 1024 if (ncols % 1024 == 0 and nrows <= 256) \
            else _LANE_PAD
        self.hits += 1
        # phase mode and ts-free ops need no ts plane in the program
        ts_parts = () if (phase_dev is not None or op in TS_FREE_OPS) \
            else tuple(b.ts_seg for b in segments)
        # fused compressed-resident dispatch (ISSUE 3; histograms since
        # ISSUE 14): one compressed block covering the whole row span
        # serves through the packed kernels — the XOR-class decode runs
        # inside the grid kernel, so HBM reads the ~2.5 B/sample planes.
        # Phase mode reads the block's own meta phase row (identical to
        # phase_dev on every requested lane; unrequested lanes are
        # sliced/dropped).  Histogram caches qualify like scalar ones:
        # each bucket column is an independent packed lane and callers
        # compose their ``lane*hb + bucket`` indirections through the
        # pack's ``inv``.  Multi-block spans, ts-streaming ops, and f64
        # (no meta) residents keep the XLA decode path.
        seg0 = segments[0]
        packed = packed_inv = None
        packed_phase = False
        if (len(segments) == 1 and isinstance(seg0.vals, dict)
                and seg0.pack_inv is not None
                and not _PACKED_BROKEN
                and (on_tpu_backend() or _PACKED_INTERPRET)
                and any(k.startswith("m") for k in seg0.vals)):
            if op in TS_FREE_OPS:
                packed, packed_inv = seg0.vals, seg0.pack_inv
            elif phase_dev is not None and op in PHASE_OPS:
                packed, packed_inv = seg0.vals, seg0.pack_inv
                packed_phase = True
        hbm_dense = hbm_comp = hbm_hist = 0
        for blk in segments:
            if isinstance(blk.vals, dict):
                nb_c = sum(int(a.nbytes) for a in blk.vals.values())
                if self.hist:
                    hbm_hist += nb_c
                else:
                    hbm_comp += nb_c
            else:
                hbm_dense += int(blk.vals.nbytes)
        for t in ts_parts:
            if isinstance(t, dict):
                nb_c = int(t["phase"].nbytes)
                if self.hist:
                    hbm_hist += nb_c
                else:
                    hbm_comp += nb_c
            else:
                hbm_dense += int(t.nbytes)
        plan = _GridPlan(ts_parts,
                         tuple(b.vals for b in segments), row0,
                         steps0 - self.epoch0, q, lane_mult, nrows, ncols,
                         prep["lane_idx"], phase_dev, tuple(segments),
                         packed=packed, packed_row0=row0,
                         packed_use_phase=packed_phase,
                         packed_inv=packed_inv,
                         hbm_dense=hbm_dense, hbm_comp=hbm_comp,
                         hbm_comp_hist=hbm_hist)
        if len(self._plan_memo) > 8:
            self._plan_memo.clear()
        self._plan_memo[pkey] = plan
        return plan

    def _phase_device(self, ph_req, req, ncols: int, key) -> object:  # holds-lock: _lock
        """Device [ncols] phase vector for the uniform-phase kernels,
        memoized per (block range, cache version) — re-uploading ~4 B/
        lane per query would cost more than it saves on a tunnel link.
        Unrequested lanes get phase 1; their outputs are sliced away or
        segment-dropped downstream, so any value is safe."""
        phases = np.where(ph_req > 0, ph_req, 1).astype(np.int32)
        memo = self._phase_memo.get(key)
        if memo is not None and memo[0].shape[0] == ncols:
            host, dev = memo
            if np.array_equal(host[req], phases):
                return dev
            # different id-lists over the same blocks accumulate into
            # one merged vector so alternating dashboards don't ping-
            # pong uploads
            ph_cols = host.copy()
            ph_cols[req] = phases
        else:
            ph_cols = np.ones(ncols, np.int32)
            ph_cols[req] = phases
        dev = LEDGER.device_put(ph_cols, self._shard.grid_device,
                                owner=self.owner, fmt="scratch")
        self._phase_memo.clear()
        self._phase_memo[key] = (ph_cols, dev)
        return dev

    # ---------------------------------------------------------------- blocks

    def _disk_floor_ms(self, parts) -> int:
        """Highest timestamp below which some requested partition's data
        lives only in the column store (recovery tail / re-ingested after
        eviction).  Cached per shard ingest epoch."""
        epoch = self._shard.ingest_epoch
        if self._disk_floor is not None and self._disk_floor[0] == epoch:
            return self._disk_floor[1]
        floor = -(2**62)
        index = self._shard.index
        for part in parts:
            earliest = part.earliest_timestamp
            if earliest < 0:
                continue
            try:
                idx_start = index.start_time(part.part_id)
            except KeyError:
                continue
            if idx_start < earliest:
                floor = max(floor, earliest)
        self._disk_floor = (epoch, floor)
        return floor

    def _frozen_high(self) -> int:
        """Highest bucket (exclusive) fully covered by frozen chunks: the
        earliest write-buffer row across THIS cache's lanes bounds it —
        an unrelated metric's laggy buffer must not demote this cache's
        recent blocks to per-epoch-rebuilt tail blocks."""
        lo = None
        for pid in self.lane_of:
            part = self._shard.grid_partition(pid)
            if part is None:
                continue
            if part._buf_n:
                t = int(part._buf_ts[0])
                lo = t if lo is None or t < lo else lo
        if lo is None:
            return 2**62
        # bucket containing lo is NOT fully frozen
        return (lo - self.epoch0 + self.gstep - 1) // self.gstep - 1

    def _block_for(self, bi: int, lanes: int,  # holds-lock: _lock
                   frozen_hi: int,
                   need_hi: int):
        b_lo = bi * BLOCK_BUCKETS          # first bucket index of the block
        b_hi = b_lo + BLOCK_BUCKETS - 1
        blk = self.blocks.get(bi)
        if blk is not None and blk.lanes == lanes \
                and blk.staged_hi >= need_hi and b_hi <= frozen_hi:
            # a cached FROZEN block is only valid while its whole bucket
            # range stays below the frozen frontier: once write-buffer
            # rows land inside it (live ingest after the block was
            # staged), the staged copy is missing them and the dense
            # proof would read the hole as "no samples" — serving a
            # silently-partial window.  Such ranges take the per-epoch
            # tail path below; note_freeze drops the stale copy when
            # the buffer flushes.
            return blk
        if b_hi > frozen_hi:
            # tail block: includes mutable write-buffer rows; cache under
            # the shard's ingest epoch so repeat queries skip the rebuild
            epoch = self._shard.ingest_epoch
            got = self._tails.get(bi)
            if got is not None and got[0] == epoch \
                    and got[1].lanes == lanes \
                    and got[1].staged_hi >= need_hi:
                return got[1]
            # tail blocks rebuild every ingest epoch: the host-side
            # pack would be pure added latency on the live-ingest path
            blk = self._build(bi, lanes, compress=False)
            if blk is not None:
                self._tails[bi] = (epoch, blk)
                while len(self._tails) > 8:      # bound lagging-replay spans
                    self._tails.pop(next(iter(self._tails)))
            return blk
        blk = self._build(bi, lanes)
        if blk is not None:
            self.blocks[bi] = blk
            self.version += 1
        return blk

    def _val_dtype(self):
        """f32 on TPU (matching the Pallas kernels); f64 on CPU backends so
        the portable reference path keeps full double precision."""
        import jax

        from filodb_tpu.ops.grid import on_tpu_backend
        if on_tpu_backend():
            return np.float32
        return np.float64 if jax.config.jax_enable_x64 else np.float32

    def _build(self, bi: int, lanes: int, compress: bool = True):
        """Host staging + one upload for block ``bi``."""
        g = self.gstep
        stride = self.hb if self.hist else 1
        # block bi holds buckets [bi*BB, bi*BB+BB-1]; bucket c covers
        # (epoch0+(c-1)*g, epoch0+c*g]
        b_lo_ms = self.epoch0 + (bi * BLOCK_BUCKETS - 1) * g  # left edge excl
        b_hi_ms = b_lo_ms + BLOCK_BUCKETS * g                 # right edge incl
        ts_stage = np.zeros((BLOCK_BUCKETS, lanes * stride), np.int32)
        val_stage = np.full((BLOCK_BUCKETS, lanes * stride), np.nan,
                            self._val_dtype())
        dropped_lane = False
        for pid, lane in list(self.lane_of.items()):
            part = self._shard.grid_partition(pid)
            if part is None:
                # A laned partition with no resolvable data (ODP
                # page-evicted, or evicted/purged from memory) must not
                # stay laned: the block cache is keyed only by (bucket,
                # lanes, staged_hi) and page-in does not invalidate
                # blocks, so a cached NaN lane would silently serve
                # "empty" for history that exists on disk (round-4
                # ADVICE, medium).  PRUNE the lane — a re-materialized
                # partition then gets a FRESH lane >= every cached
                # block's staged_hi, forcing a rebuild — AND fail THIS
                # build: an in-flight query whose pre-eviction prep
                # still maps the pid to this lane must fall back to the
                # host path, not read a cached NaN lane.  The next
                # build succeeds (the lane is gone), so a permanent
                # eviction cannot wedge future builds.
                del self.lane_of[pid]
                dropped_lane = True
                continue
            ts, vals = part.read_range(b_lo_ms + 1, b_hi_ms, self.column_id)
            if len(ts) == 0:
                continue
            if self.hist:
                hbk, rows = vals
                if rows.size == 0:
                    continue
                if rows.shape[1] > self.hb:
                    self._disable()             # bucket scheme widened
                    return None
                arr = rows.astype(self._val_dtype())
                if arr.shape[1] < self.hb:
                    # narrower cumulative hist: top bucket IS the total,
                    # edge-pad (same convention as scan_batch)
                    arr = np.pad(arr, ((0, 0), (0, self.hb - arr.shape[1])),
                                 mode="edge")
            elif not isinstance(vals, np.ndarray):
                self._disable()                 # string column
                return None
            else:
                arr = vals
            buckets = (ts - self.epoch0 + g - 1) // g - bi * BLOCK_BUCKETS
            if len(np.unique(buckets)) != len(buckets):
                self._disable()                 # >1 sample per bucket
                return None
            col0 = lane * stride
            ts_stage[buckets, col0:col0 + stride] = \
                (ts - self.epoch0).astype(np.int32)[:, None]
            val_stage[buckets, col0:col0 + stride] = \
                arr if self.hist else arr[:, None]
        if dropped_lane:
            return None
        self.builds += 1
        fin = np.isfinite(val_stage)
        fcnt = fin.sum(axis=0).astype(np.int32)
        fmin = fin.argmax(axis=0).astype(np.int32)
        fmax = (BLOCK_BUCKETS - 1 - fin[::-1].argmax(axis=0)).astype(np.int32)
        fmax[fcnt == 0] = -1
        # per-lane within-bucket offset range over the filled cells:
        # cell (local row r, lane) holds ts_rel in ((c-1)*g, c*g] for
        # global bucket c = bi*BB + r, so phase = ts_rel - (c-1)*g
        cstart = ((np.arange(BLOCK_BUCKETS, dtype=np.int64)
                   + bi * BLOCK_BUCKETS - 1) * g)[:, None]
        ph = ts_stage.astype(np.int64) - cstart
        pmin = np.where(fin, ph, 2**31).min(axis=0).astype(np.int32)
        pmax = np.where(fin, ph, -1).max(axis=0).astype(np.int32)
        dev = self._shard.grid_device      # mesh-pinned; None = default
        # compressed residents (VERDICT r4 #4): drop the ts plane when
        # every lane is uniform-phase (reconstructed on device), and
        # keep the value plane in XOR-class form when it pays.  BOTH
        # forms honor the device-cache-compress kill switch — the flag
        # documents itself as covering ts-plane elision too
        # (storeconfig.py), and an operator reverting a reconstruction
        # bug must actually get decoded planes back
        do_compress = compress and self._shard.config.device_cache_compress
        uniform = do_compress \
            and bool(((pmin == pmax) | (fcnt == 0)).all())
        nbytes = 0
        ts_desc = None
        phase = None
        if uniform:
            ts_dev = None
            phase = np.where(fcnt > 0, pmin, 1).astype(np.int32)
            ts_desc = {"base": int((bi * BLOCK_BUCKETS - 1) * g),
                       "g": int(g),
                       "phase": LEDGER.device_put(phase, dev,
                                                  owner=self.owner,
                                                  fmt="compressed")}
            nbytes += phase.nbytes
        else:
            ts_dev = LEDGER.device_put(ts_stage, dev, owner=self.owner,
                                       fmt="dense")
            nbytes += ts_stage.nbytes
        from filodb_tpu.codecs import xorgrid
        # histogram caches pack at SERIES granularity (stride=hb): a
        # series' bucket columns classify together and stay contiguous
        # in bucket order — the layout contract of the fused hist
        # kernels (ops/grid.py hist_grid_grouped_packed)
        packed = xorgrid.pack_vals(val_stage, phase=phase,
                                   stride=stride) \
            if do_compress else None
        pack_inv = None
        if packed is not None:
            vals_dev = {k: LEDGER.device_put(v, dev, owner=self.owner,
                                             fmt="compressed")
                        for k, v in packed.planes.items()}
            pack_inv = packed.inv
            nbytes += packed.nbytes
        else:
            vals_dev = LEDGER.device_put(val_stage, dev, owner=self.owner,
                                         fmt="dense")
            nbytes += val_stage.nbytes
        return _Block(ts_dev, vals_dev,
                      lanes, self._seq, (fmin, fmax, fcnt), (pmin, pmax),
                      staged_hi=self._next_lane, ts_desc=ts_desc,
                      nbytes=nbytes, width=val_stage.shape[1],
                      pack_inv=pack_inv)

    def _reclaim(self, target_bytes: int,  # holds-lock: _lock
                 keep: set) -> int:
        """Oldest-first reclaim down to ``target_bytes`` (the reference's
        reclaim-on-demand over time-ordered block lists).  Caller holds
        the lock.  Returns bytes freed."""
        freed = 0
        evicted = 0
        while self.bytes_resident > target_bytes and len(self.blocks) > 1:
            victims = [bi for bi in sorted(self.blocks) if bi not in keep]
            if not victims:
                break
            freed += self.blocks[victims[0]].nbytes
            del self.blocks[victims[0]]
            self.evictions += 1
            evicted += 1
        if evicted:
            LEDGER.note_eviction(self.owner, "budget_overflow", n=evicted,
                                 nbytes=freed)
        if freed:
            # memoized plans hold strong block refs: drop them so the
            # reclaim actually releases HBM
            self._plan_memo.clear()
        return freed

    def _evict(self, keep: set) -> None:
        self._reclaim(self.budget, keep)

    def ensure_headroom(self, frac: float) -> int:
        """Proactive reclaim down to ``(1-frac)`` of the budget, run OFF
        the query path (the shard calls it from flush tasks) so queries
        rarely pay inline eviction — the reference's background headroom
        task (BlockManager.scala ensureHeadroomPercentAvailable :142)."""
        with self._lock:
            return self._reclaim(int(self.budget * (1.0 - frac)), set())
