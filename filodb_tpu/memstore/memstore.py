"""TimeSeriesMemStore: dataset -> shards facade.

Matches the reference's TimeSeriesMemStore (reference: core/src/main/scala/
filodb.core/memstore/TimeSeriesMemStore.scala:22): ``setup`` creates shards,
``ingest`` routes containers to a shard, ``recover_stream`` replays a source
from checkpoints with per-group watermark skipping, and the query surface
(lookup/scan/labels) delegates to shards.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

import numpy as np

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.core.schemas import Schemas
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore.shard import PartLookupResult, TimeSeriesShard
from filodb_tpu.store.columnstore import ColumnStore, NullColumnStore
from filodb_tpu.store.metastore import InMemoryMetaStore, MetaStore


class ShardNotSetup(Exception):
    pass


class TimeSeriesMemStore:
    def __init__(self, column_store: Optional[ColumnStore] = None,
                 meta_store: Optional[MetaStore] = None):
        self.store = column_store or NullColumnStore()
        self.meta = meta_store or InMemoryMetaStore()
        self._datasets: dict[str, dict[int, TimeSeriesShard]] = {}
        self._schemas: dict[str, Schemas] = {}
        # elastic resharding (ISSUE 13): runs on every new shard BEFORE
        # any ingest can reach it — the split participant installs the
        # child half-filter here, so a child shard can never materialize
        # the parent half even if its consumer starts racing the
        # controller (standalone.py wires this to SplitController)
        self.shard_setup_hook = None

    # ------------------------------------------------------------------ setup

    def setup(self, dataset: str, schemas: Schemas, shard_num: int,
              config: Optional[StoreConfig] = None) -> TimeSeriesShard:
        shards = self._datasets.setdefault(dataset, {})
        if shard_num in shards:
            raise ValueError(f"shard {shard_num} already set up for {dataset}")
        cfg = config or StoreConfig()
        if cfg.demand_paging_enabled and not isinstance(self.store,
                                                       NullColumnStore):
            from filodb_tpu.memstore.odp import OnDemandPagingShard
            shard = OnDemandPagingShard(dataset, schemas, shard_num, cfg,
                                        self.store, self.meta)
        else:
            shard = TimeSeriesShard(dataset, schemas, shard_num, cfg,
                                    self.store, self.meta)
        shards[shard_num] = shard
        self._schemas[dataset] = schemas
        if self.shard_setup_hook is not None:
            self.shard_setup_hook(dataset, shard)
        return shard

    def drop_shard(self, dataset: str, shard_num: int) -> bool:
        """Remove one shard's in-memory state entirely (split abort
        discards children; the persisted side is the caller's job).
        Returns True when a shard was dropped."""
        shard = self._datasets.get(dataset, {}).pop(shard_num, None)
        if shard is None:
            return False
        shard.close()
        return True

    def has_shard(self, dataset: str, shard_num: int) -> bool:
        return shard_num in self._datasets.get(dataset, ())

    def get_shard(self, dataset: str, shard_num: int) -> TimeSeriesShard:
        try:
            return self._datasets[dataset][shard_num]
        except KeyError:
            raise ShardNotSetup(f"{dataset} shard {shard_num} not set up")

    def shards(self, dataset: str) -> list[TimeSeriesShard]:
        return list(self._datasets.get(dataset, {}).values())

    def active_shards(self, dataset: str) -> list[int]:
        return sorted(self._datasets.get(dataset, {}).keys())

    # ----------------------------------------------------------------- ingest

    def ingest(self, dataset: str, shard_num: int, container: bytes,
               offset: int) -> int:
        return self.get_shard(dataset, shard_num).ingest_container(container, offset)

    def ingest_stream(self, dataset: str, shard_num: int,
                      stream: Iterable[tuple[int, bytes]],
                      flush_each: Optional[int] = None,
                      flush_interval_ms: Optional[int] = None,
                      flush_parallelism: int = 2) -> int:
        """Consume an (offset, container) stream, interleaving flushes the
        way ingestStream interleaves createFlushTasks (reference:
        TimeSeriesMemStore.scala:106-129).

        Two flush modes:
        - ``flush_each=N``: synchronous flush every N containers (simple,
          test-friendly).
        - ``flush_interval_ms``: the reference's production mode — per-group
          time-boundary scheduling with encode+IO pipelined onto a
          dedicated flush executor (memstore/flush.py), so ingestion never
          stalls behind a flush (reference TimeSeriesShard.scala:804-846).
        """
        if flush_each is not None and flush_interval_ms is not None:
            raise ValueError("pass flush_each OR flush_interval_ms, not both")
        shard = self.get_shard(dataset, shard_num)
        total = 0
        if flush_interval_ms is not None:
            from filodb_tpu.memstore.flush import FlushScheduler
            sched = FlushScheduler(shard, flush_interval_ms,
                                   flush_parallelism)
            shard.flush_scheduler = sched
            try:
                for offset, container in stream:
                    total += shard.ingest_container(container, offset)
                    sched.note_ingested()
            finally:
                try:
                    sched.close(flush_remaining=True)
                finally:
                    shard.flush_scheduler = None
            return total
        for i, (offset, container) in enumerate(stream):
            total += shard.ingest_container(container, offset)
            if flush_each and (i + 1) % flush_each == 0:
                shard.flush_all()
        return total

    def prepare_recovery(self, dataset: str, shard_num: int
                         ) -> tuple[Optional[int], int]:
        """Set group watermarks from persisted checkpoints and return
        (resume_offset, highest_checkpoint); resume_offset is None when no
        checkpoints exist (reference: IngestionActor.scala:193-217 reads
        checkpoints, TimeSeriesMemStore.recoverStream applies them)."""
        shard = self.get_shard(dataset, shard_num)
        cps = self.meta.read_checkpoints(dataset, shard_num)
        for group, offset in cps.items():
            shard.group_watermarks[group] = max(
                shard.group_watermarks[group], offset)
        if not cps:
            return None, -1
        return min(cps.values()) + 1, max(cps.values())

    def recover_stream(self, dataset: str, shard_num: int,
                       stream: Iterable[tuple[int, bytes]]) -> int:
        """Replay from checkpoints: set group watermarks from the meta store,
        then ingest — below-watermark records skip (reference:
        recoverStream TimeSeriesMemStore.scala:136-173)."""
        shard = self.get_shard(dataset, shard_num)
        self.prepare_recovery(dataset, shard_num)
        total = 0
        for offset, container in stream:
            total += shard.ingest_container(container, offset)
        return total

    def recover_index(self, dataset: str, shard_num: int) -> int:
        """Rebuild the tag index from persisted partkeys (reference:
        IndexBootstrapper.scala:12, TimeSeriesShard.recoverIndex)."""
        from filodb_tpu.core.record import parse_partkey
        shard = self.get_shard(dataset, shard_num)
        n = 0
        for rec in self.store.scan_part_keys(dataset, shard_num):
            if rec.partkey in shard.part_set:
                continue
            pid = shard._next_part_id
            shard._next_part_id += 1
            shard.index.add_partkey(pid, rec.partkey, parse_partkey(rec.partkey),
                                    rec.start_time, rec.end_time)
            shard.part_schema_hash[pid] = rec.schema_hash
            # register in the part set so resumed ingest reuses this part id
            # instead of creating a duplicate index entry
            shard.part_set[rec.partkey] = pid
            n += 1
        # bootstrap completes the index BEFORE the shard serves (reference:
        # IndexBootstrapper.scala:12 refreshes the Lucene reader after the
        # bulk add) — without this the first lookup pays the whole deferred
        # label backlog inside its own latency
        shard.index.apply_pending()
        return n

    # ------------------------------------------------------------------ query

    def lookup_partitions(self, dataset: str, shard_num: int,
                          filters: Sequence[ColumnFilter], start: int,
                          end: int, limit: Optional[int] = None) -> PartLookupResult:
        return self.get_shard(dataset, shard_num).lookup_partitions(
            filters, start, end, limit)

    def label_values(self, dataset: str, label: str,
                     filters: Sequence[ColumnFilter] = (),
                     shard_nums: Optional[Sequence[int]] = None,
                     start: int = 0, end: int = np.iinfo(np.int64).max,
                     limit: Optional[int] = None) -> list[str]:
        nums = shard_nums if shard_nums is not None else self.active_shards(dataset)
        vals: set[str] = set()
        for sn in nums:
            vals.update(self.get_shard(dataset, sn).label_values(
                label, filters, start, end, limit))
        out = sorted(vals)
        return out[:limit] if limit is not None else out

    def flush(self, dataset: str, shard_num: Optional[int] = None) -> int:
        if shard_num is not None:
            return self.get_shard(dataset, shard_num).flush_all()
        return sum(s.flush_all() for s in self.shards(dataset))

    def reset(self) -> None:
        for shards in self._datasets.values():
            for sh in shards.values():
                sh.close()
        self._datasets.clear()
