"""Command-line interface.

Capability match for the reference's CliMain (reference:
cli/src/main/scala/filodb.cli/CliMain.scala:65-96 — commands: init,
create, importcsv, list, promql queries, labelValues,
timeseriesMetadata, and the debug decoders promFilterToPartKeyBR /
partKeyBrAsString / decodeChunkInfo / decodeVector).

Query commands talk to a running server over HTTP; import/debug commands
run locally.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request


def _http_get(server: str, path: str, params: dict | None = None) -> dict:
    """GET returning the server's JSON even for 4xx/5xx responses (the
    error body carries the message the user needs)."""
    qs = urllib.parse.urlencode({k: v for k, v in (params or {}).items()
                                 if v is not None})
    url = f"{server.rstrip('/')}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=60) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read())
        except Exception:  # non-JSON error body
            return {"status": "error", "errorType": "http",
                    "error": f"HTTP {e.code}"}
    except urllib.error.URLError as e:  # connection refused, DNS, timeout
        return {"status": "error", "errorType": "connection",
                "error": f"cannot reach {server}: {e.reason}"}


def cmd_query(args) -> int:
    path = f"/promql/{args.dataset}/api/v1/query_range"
    body = _http_get(args.server, path,
                     {"query": args.promql, "start": args.start,
                      "end": args.end, "step": args.step})
    print(json.dumps(body, indent=2))
    return 0 if body.get("status") == "success" else 1


def cmd_instant_query(args) -> int:
    path = f"/promql/{args.dataset}/api/v1/query"
    body = _http_get(args.server, path,
                     {"query": args.promql, "time": args.time})
    print(json.dumps(body, indent=2))
    return 0 if body.get("status") == "success" else 1


def cmd_chunkmeta(args) -> int:
    """Chunk-level metadata for matching series (reference:
    CliMain.scala decodeChunkInfo debugging; served by the RawChunkMeta
    plan behind /admin/chunkmeta)."""
    path = f"/admin/chunkmeta/{args.dataset}"
    body = _http_get(args.server, path, {"match[]": args.match})
    print(json.dumps(body, indent=2))
    return 0 if body.get("status") == "success" else 1


def cmd_labelvalues(args) -> int:
    path = f"/promql/{args.dataset}/api/v1/label/{args.label}/values"
    body = _http_get(args.server, path)
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    for v in body.get("data", []):
        print(v)
    return 0


def cmd_timeseries_metadata(args) -> int:
    path = f"/promql/{args.dataset}/api/v1/series"
    body = _http_get(args.server, path, {"match[]": args.match})
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    print(json.dumps(body.get("data", []), indent=2))
    return 0


def cmd_cardinality_report(args) -> int:
    """Cardinality explorer (ISSUE 6): per-shard top-k label names x
    values by active-series count, tenant breakdown, churn rates — the
    online answer to the reference's offline cardinality-busting jobs
    (served by /admin/cardinality)."""
    body = _http_get(args.server, "/admin/cardinality",
                     {"dataset": args.dataset, "topk": args.topk,
                      "shard": args.shard})
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    data = body["data"]
    if args.json:
        print(json.dumps(data, indent=2))
        return 0
    print(f"dataset {data['dataset']}: "
          f"{data['total_active_series']} active series, "
          f"tenant label {data['tenant_label']!r}")
    for tenant, n in sorted(data.get("tenants", {}).items(),
                            key=lambda kv: -kv[1]):
        print(f"  tenant {tenant or '(untagged)'}: {n}")
    for row in data.get("shards", []):
        ch = row.get("churn", {})
        print(f"shard {row['shard']}: {row['active_series']} series, "
              f"{row['labels']} labels "
              f"(+{ch.get('created_total', 0)}/-{ch.get('removed_total', 0)}"
              f" churned, {ch.get('create_rate_per_s', 0)}/s create)")
        for lab in row.get("top_labels", []):
            print(f"  {lab['label']}: {lab['values']} values / "
                  f"{lab['series']} series")
            for v in lab.get("top_values", [])[:args.topk]:
                print(f"    {v['value']!r}: {v['series']}")
    return 0


def cmd_rollup_status(args) -> int:
    """Tiered-resolution rollup state (ISSUE 11, served by
    /admin/rollup): per-dataset/tier cursor positions, lag vs the flush
    watermark, last-pass duration, rows written."""
    body = _http_get(args.server, "/admin/rollup")
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    data = body["data"]
    if args.json:
        print(json.dumps(data, indent=2))
        return 0
    for ds in data.get("datasets", []):
        ladder = "/".join(f"{r // 1000}s" for r in ds["resolutions_ms"])
        print(f"dataset {ds['dataset']}: tiers {ladder}, "
              f"{ds['passes']} passes ({ds['deferred']} deferred), "
              f"last pass {ds['last_pass_s'] * 1000:.1f}ms")
        for res, n in sorted(ds.get("samples_written", {}).items(),
                             key=lambda kv: int(kv[0])):
            err = ds.get("tier_errors", {}).get(res)
            rolled = ds.get("rolled_through_ms", {}).get(res)
            print(f"  tier {int(res) // 1000}s: {n} rows written, "
                  f"rolled through {rolled}"
                  + (f", ERROR: {err}" if err else ""))
        for sh in ds.get("shards", []):
            tiers = ", ".join(
                f"{int(r) // 1000}s@{t['emitted_through_ms']}"
                f"(lag {t['lag_s']}s)" if t["emitted_through_ms"]
                is not None else f"{int(r) // 1000}s@-"
                for r, t in sorted(sh["tiers"].items(),
                                   key=lambda kv: int(kv[0])))
            print(f"  shard {sh['shard']}: "
                  f"{'active' if sh['active'] else 'standby'}, "
                  f"{sh['buffered_series']} series / "
                  f"{sh['buffered_samples']} samples buffered, "
                  f"queue {sh['queue_depth']} | {tiers}")
    return 0


def _http_post(server: str, path: str, params: dict | None = None) -> dict:
    """POST with query params, returning JSON like _http_get."""
    import urllib.error
    qs = urllib.parse.urlencode({k: v for k, v in (params or {}).items()
                                 if v is not None})
    url = f"{server.rstrip('/')}{path}" + (f"?{qs}" if qs else "")
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read())
        except Exception:  # non-JSON error body
            return {"status": "error", "errorType": "http",
                    "error": f"HTTP {e.code}"}
    except urllib.error.URLError as e:
        return {"status": "error", "errorType": "connection",
                "error": f"cannot reach {server}: {e.reason}"}


def _print_split_state(st: dict) -> None:
    print(f"dataset {st['dataset']}: phase {st['phase']}, "
          f"{st.get('num_shards')} serving / {st.get('total_shards')} "
          f"total shards, generation {st.get('generation')}")
    if st.get("cutover_seconds") is not None:
        print(f"  cutover took {st['cutover_seconds'] * 1000:.1f}ms")
    if st.get("grace_remaining_s") is not None:
        print(f"  retire grace remaining: {st['grace_remaining_s']:.1f}s")
    if st.get("abort_reason"):
        print(f"  abort reason: {st['abort_reason']}")
    for ch in st.get("children_status", []):
        print(f"  child {ch['shard']} (parent {ch['parent']}) on "
              f"{','.join(ch['nodes'])}: {ch['status']} "
              f"{ch.get('progress', 0)}% wm={ch.get('watermark')}"
              f"/head={ch.get('group_head')} "
              f"rows={ch.get('rows_replayed', '?')}")


def cmd_split(args) -> int:
    """Trigger a live power-of-two shard split (ISSUE 13, doc/ha.md):
    children catch up as Recovery replicas, cutover flips routing
    atomically, the parent's migrated half retires after the grace
    window.  Lossless abort any time before retire via split-abort."""
    body = _http_post(args.server, f"/admin/split/{args.dataset}",
                      {"action": "start", "grace-s": args.grace_s})
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    _print_split_state(body["data"])
    return 0


def cmd_split_status(args) -> int:
    """Phase/progress of a live split (served by /admin/split)."""
    body = _http_get(args.server, f"/admin/split/{args.dataset}")
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    if args.json:
        print(json.dumps(body["data"], indent=2))
        return 0
    _print_split_state(body["data"])
    return 0


def cmd_split_abort(args) -> int:
    """Lossless split abort: children discarded, the parent topology
    restored in one generation bump (refused once retire has purged)."""
    body = _http_post(args.server, f"/admin/split/{args.dataset}",
                      {"action": "abort", "reason": args.reason})
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    _print_split_state(body["data"])
    return 0


def cmd_shards(args) -> int:
    """Ingest watermark / shard-health tree (served by /admin/shards)."""
    body = _http_get(args.server, "/admin/shards")
    print(json.dumps(body, indent=2))
    return 0 if body.get("status") == "success" else 1


def cmd_insights(args) -> int:
    """Fleet workload insights (ISSUE 19): top-k query fingerprints by
    cost/latency/QPS with per-tenant rollup and batching headroom
    (served by /admin/insights), or — with ``--fleet`` — the merged
    whole-cluster view (served by /admin/fleet)."""
    if args.fleet:
        body = _http_get(args.server, "/admin/fleet",
                         {"refresh": "true" if args.refresh else None})
        print(json.dumps(body.get("data", body), indent=2))
        return 0 if body.get("status") == "success" else 1
    body = _http_get(args.server, "/admin/insights",
                     {"top": args.top, "sort": args.sort,
                      "raw": "true" if args.raw else None})
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    data = body["data"]
    if args.raw or args.json:
        print(json.dumps(data, indent=2))
        return 0
    print(f"nodes {','.join(data.get('nodes') or ['?'])}: "
          f"{data['fingerprints']} fingerprints "
          f"({data['dropped']} evicted), window {data['window_s']}s, "
          f"sort {data['sort']}")
    for row in data.get("top", []):
        rc = row["resultcache"]
        print(f"  {row['query'] or row['fingerprint']!r} "
              f"[{row['dataset']}]")
        print(f"    count {row['count']} ({row['errors']} errors, "
              f"{row['qps']}/s)  p50 {row['p50_ms']}ms "
              f"p95 {row['p95_ms']}ms p99 {row['p99_ms']}ms")
        print(f"    samples {row['samples']}  device "
              f"{row['device_ms']}ms/{row['device_programs']} launches  "
              f"hbm {row['hbm_bytes']}B  cache "
              f"{rc['hit']}/{rc['partial']}/{rc['miss']} h/p/m"
              + (f"  sheds {row['sheds']}" if row["sheds"] else ""))
    bat = data.get("batching") or {}
    print(f"batching headroom: {bat.get('headroom', 0)} "
          f"co-arriving shape-identical queries at peak; realized "
          f"{bat.get('realized_peak', 0)} at peak "
          f"({bat.get('realized_members', 0)} queries in "
          f"{bat.get('realized_groups', 0)} vmapped launches)")
    for row in bat.get("keys", [])[:args.top]:
        print(f"  {row['batch_key']}: peak {row['peak']}, "
              f"{row['co_arrived']}/{row['arrivals']} co-arrived; "
              f"realized peak {row.get('realized_peak', 0)}, "
              f"{row.get('batched_members', 0)} batched in "
              f"{row.get('batched_groups', 0)} launches")
    for tenant, t in sorted((data.get("tenants") or {}).items()):
        avg = t["latency_us"] / 1000.0 / t["count"] if t["count"] else 0
        print(f"tenant {tenant or '(untagged)'}: {t['count']} queries, "
              f"{t['errors']} errors, avg {avg:.3f}ms, "
              f"{t['samples']} samples")
    for row in data.get("slo") or []:
        print(f"slo {row['objective']} tenant {row['tenant']}: "
              f"fast burn {row['fast_burn']}x, slow burn "
              f"{row['slow_burn']}x ({row['bad']}/{row['total']} bad, "
              f"budget {1 - row['target']:.4g})")
    return 0


def cmd_status(args) -> int:
    body = _http_get(args.server, f"/api/v1/cluster/{args.dataset}/status")
    if body.get("status") != "success":
        print(json.dumps(body, indent=2))
        return 1
    print(json.dumps(body.get("data", []), indent=2))
    return 0


def cmd_list(args) -> int:
    from filodb_tpu.store.persistence import DiskMetaStore
    meta = DiskMetaStore(f"{args.data_dir}/meta.db")
    for name in meta.list_datasets():
        print(name)
    return 0


def cmd_create(args) -> int:
    from filodb_tpu.store.persistence import DiskMetaStore
    meta = DiskMetaStore(f"{args.data_dir}/meta.db")
    conf = {"name": args.dataset, "num-shards": args.num_shards,
            "schema": args.schema}
    meta.write_dataset(args.dataset, json.dumps(conf))
    print(f"created dataset {args.dataset}")
    return 0


def cmd_importcsv(args) -> int:
    """Load a CSV into a local disk store (offline bulk import)."""
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.gateway.producer import csv_stream_elements
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.store.persistence import DiskColumnStore, DiskMetaStore

    colstore = DiskColumnStore(f"{args.data_dir}/chunks.db")
    metastore = DiskMetaStore(f"{args.data_dir}/meta.db")
    ms = TimeSeriesMemStore(colstore, metastore)
    ms.setup(args.dataset, DEFAULT_SCHEMAS, args.shard)
    with open(args.file) as f:
        elements = csv_stream_elements(
            f.read(), DEFAULT_SCHEMAS, args.schema,
            tag_columns=args.tag_columns.split(","),
            timestamp_column=args.timestamp_column)
    n = 0
    for off, c in elements:
        n += ms.ingest(args.dataset, args.shard, c, offset=off)
    ms.get_shard(args.dataset, args.shard).flush_all()
    print(f"imported {n} rows into {args.dataset} shard {args.shard}")
    return 0


def _open_tier_store(args):
    """The store a tier-aware offline command scans: local sqlite
    (default) or the cold object bucket (doc/coldstore.md)."""
    if getattr(args, "tier", "local") == "cold":
        from filodb_tpu.coldstore import ColdChunkStore, LocalFSBucket
        bucket_dir = getattr(args, "bucket_dir", None) \
            or f"{args.data_dir}/coldstore"
        return ColdChunkStore(LocalFSBucket(bucket_dir))
    from filodb_tpu.store.persistence import DiskColumnStore
    return DiskColumnStore(f"{args.data_dir}/chunks.db")


def cmd_verify_chunks(args) -> int:
    """Offline integrity scan: recompute every persisted chunk's CRC32C
    against its stored checksum (and with --deep, decode every vector)
    and report per-shard pass/fail counts (doc/integrity.md).  With
    ``--tier=cold`` the same scan runs over the object bucket — every
    object fetched and CRC-checked against its key (doc/coldstore.md).
    Exits 1 when any chunk fails."""
    from filodb_tpu.integrity.scan import verify_chunks

    store = _open_tier_store(args)
    shards = [int(s) for s in args.shards.split(",")] if args.shards \
        else None
    report = verify_chunks(store, args.dataset, shards, deep=args.deep)
    print(json.dumps(report, indent=2))
    return 1 if report["total_failed"] else 0


def cmd_age_out(args) -> int:
    """Offline cold-tier migration pass (doc/coldstore.md): move every
    local chunk row wholly older than ``--retention`` into the object
    bucket (upload, read-back CRC verify, then delete locally) and
    advance the per-shard watermarks.  ``--dry-run`` prints the plan —
    chunk/byte counts per shard — and moves nothing."""
    from filodb_tpu.coldstore import (AgeOutManager, ColdChunkStore,
                                      LocalFSBucket)
    from filodb_tpu.http.model import parse_duration_ms
    from filodb_tpu.store.persistence import DiskColumnStore, DiskMetaStore

    local = DiskColumnStore(f"{args.data_dir}/chunks.db")
    meta = DiskMetaStore(f"{args.data_dir}/meta.db")
    meta.initialize()
    bucket_dir = args.bucket_dir or f"{args.data_dir}/coldstore"
    cold = ColdChunkStore(LocalFSBucket(bucket_dir))
    mgr = AgeOutManager(local, cold, metastore=meta)
    retention_ms = parse_duration_ms(args.retention)
    shards = [int(s) for s in args.shards.split(",")] if args.shards \
        else None
    try:
        if args.dry_run:
            report = mgr.plan(args.dataset, retention_ms, shards)
        else:
            report = mgr.run(args.dataset, retention_ms, shards)
    finally:
        local.shutdown()
        cold.shutdown()
        meta.shutdown()
    report["dry_run"] = bool(args.dry_run)
    print(json.dumps(report, indent=2))
    return 0


def cmd_rules_check(args) -> int:
    """promtool-style offline rule validation (doc/rules.md): every
    expr through the real PromQL parser, duplicate rule/group names,
    bad ``for:``/interval durations, unknown fields.  ``--builtin``
    additionally checks the shipped self-monitoring pack.  Exit 0 =
    every file valid; 1 = findings (all printed, not just the first);
    2 = nothing to check."""
    import json as _json

    from filodb_tpu.rules.config import validate_rule_config

    targets: list[tuple[str, dict]] = []
    failed = False
    for path in args.files:
        try:
            with open(path) as f:
                targets.append((path, _json.load(f)))
        except (OSError, _json.JSONDecodeError) as e:
            print(f"{path}: FAILED: {e}")
            failed = True
    if args.builtin:
        from filodb_tpu.rules.selfmon import selfmon_pack, slo_pack
        targets.append(("builtin:self-monitoring", selfmon_pack()))
        targets.append(("builtin:slo-burn", slo_pack()))
    if not targets and not failed:
        print("rules-check: no rule files given (pass paths and/or "
              "--builtin)", file=sys.stderr)
        return 2
    for source, config in targets:
        errors = validate_rule_config(config, source=source)
        if errors:
            failed = True
            print(f"{source}: FAILED ({len(errors)} problem(s))")
            for e in errors:
                print(f"  {e}")
        else:
            groups = config.get("groups") or []
            nrules = sum(len(g.get("rules") or []) for g in groups
                         if isinstance(g, dict))
            print(f"{source}: OK ({len(groups)} group(s), "
                  f"{nrules} rule(s))")
    return 1 if failed else 0


def cmd_lint(args) -> int:
    """filolint static analysis (doc/analysis.md): lock-discipline race
    detection, blocking-under-lock, resource lifecycle, and the eight
    migrated sentinel lints over the whole tree.  Exit 0 = zero
    unsuppressed findings.  Every argument passes straight through to
    ``python -m filodb_tpu.analysis`` — one parser, no drift."""
    from filodb_tpu.analysis.__main__ import main as lint_main
    return lint_main(args.args)


def cmd_partkey(args) -> int:
    """Debug: render a hex partkey as tags (reference: partKeyBrAsString)."""
    from filodb_tpu.core.record import parse_partkey
    print(json.dumps(parse_partkey(bytes.fromhex(args.hex))))
    return 0


def cmd_make_partkey(args) -> int:
    """Debug: tags JSON -> canonical partkey hex (reference:
    promFilterToPartKeyBR)."""
    from filodb_tpu.core.record import canonical_partkey
    print(canonical_partkey(json.loads(args.tags)).hex())
    return 0


def cmd_decode_vector(args) -> int:
    """Debug: decode a hex-encoded vector blob (reference: decodeVector)."""
    from filodb_tpu.codecs import deltadelta, doublecodec
    from filodb_tpu.codecs.wire import WireType
    blob = bytes.fromhex(args.hex)
    wire = blob[0]
    if wire in (WireType.CONST_LONG, WireType.DELTA2):
        vals = deltadelta.decode(blob)
    else:
        vals = doublecodec.decode(blob)
    print(f"wire_type={wire} n={len(vals)}")
    print(list(vals[:args.limit]))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="filodb-tpu",
                                description="FiloDB-TPU command line")
    sub = p.add_subparsers(dest="command", required=True)

    def server_args(sp):
        sp.add_argument("--server", default="http://127.0.0.1:8080")
        sp.add_argument("--dataset", default="prom")

    q = sub.add_parser("query", help="PromQL range query")
    server_args(q)
    q.add_argument("promql")
    q.add_argument("--start", required=True, help="unix seconds")
    q.add_argument("--end", required=True, help="unix seconds")
    q.add_argument("--step", default="15s")
    q.set_defaults(fn=cmd_query)

    qi = sub.add_parser("instant-query", help="PromQL instant query")
    server_args(qi)
    qi.add_argument("promql")
    qi.add_argument("--time", default=None, help="unix seconds")
    qi.set_defaults(fn=cmd_instant_query)

    lv = sub.add_parser("labelvalues", help="values of one label")
    server_args(lv)
    lv.add_argument("label")
    lv.set_defaults(fn=cmd_labelvalues)

    md = sub.add_parser("timeseries-metadata",
                        help="series matching a selector")
    server_args(md)
    md.add_argument("match")
    md.set_defaults(fn=cmd_timeseries_metadata)

    st = sub.add_parser("status", help="shard statuses")
    server_args(st)
    st.set_defaults(fn=cmd_status)

    cd = sub.add_parser("cardinality-report",
                        help="top-k label/value cardinality + tenant "
                             "breakdown + churn per shard")
    server_args(cd)
    cd.add_argument("--topk", type=int, default=10)
    cd.add_argument("--shard", type=int, default=None)
    cd.add_argument("--json", action="store_true",
                    help="raw JSON instead of the text summary")
    cd.set_defaults(fn=cmd_cardinality_report)

    ru = sub.add_parser("rollup-status",
                        help="per-dataset/tier rollup cursors, lag vs "
                             "flush watermark, rows written")
    server_args(ru)
    ru.add_argument("--json", action="store_true",
                    help="raw JSON instead of the text summary")
    ru.set_defaults(fn=cmd_rollup_status)

    iw = sub.add_parser("insights",
                        help="fleet workload insights: top query "
                             "fingerprints, tenant SLO burn, batching "
                             "headroom (/admin/insights, /admin/fleet)")
    iw.add_argument("--server", default="http://localhost:8080")
    iw.add_argument("--top", type=int, default=20)
    iw.add_argument("--sort", default="cost",
                    choices=["cost", "latency", "count", "qps", "errors"])
    iw.add_argument("--raw", action="store_true",
                    help="print the raw mergeable snapshot bundle")
    iw.add_argument("--json", action="store_true",
                    help="print the view as JSON instead of text")
    iw.add_argument("--fleet", action="store_true",
                    help="print the merged whole-cluster /admin/fleet "
                         "tree instead of this node's view")
    iw.add_argument("--refresh", action="store_true",
                    help="with --fleet: force a synchronous peer poll")
    iw.set_defaults(fn=cmd_insights)

    sh = sub.add_parser("shards",
                        help="ingest watermark chain / lag / shard "
                             "health tree")
    server_args(sh)
    sh.set_defaults(fn=cmd_shards)

    sp = sub.add_parser("split",
                        help="trigger a live power-of-two shard split "
                             "(N -> 2N, zero downtime)")
    server_args(sp)
    sp.add_argument("--grace-s", type=float, default=30.0,
                    help="seconds between cutover and parent retire — "
                         "the lossless-abort horizon")
    sp.set_defaults(fn=cmd_split)

    ss = sub.add_parser("split-status",
                        help="phase/progress of a live shard split")
    server_args(ss)
    ss.add_argument("--json", action="store_true",
                    help="raw JSON instead of the text summary")
    ss.set_defaults(fn=cmd_split_status)

    sa = sub.add_parser("split-abort",
                        help="losslessly abort an in-flight shard split")
    server_args(sa)
    sa.add_argument("--reason", default="operator abort")
    sa.set_defaults(fn=cmd_split_abort)

    cm = sub.add_parser("chunkmeta",
                        help="chunk-level metadata for matching series")
    server_args(cm)
    cm.add_argument("match", help="PromQL selector, e.g. 'm{inst=\"i0\"}'")
    cm.set_defaults(fn=cmd_chunkmeta)

    ls = sub.add_parser("list", help="list datasets in a local store")
    ls.add_argument("--data-dir", required=True)
    ls.set_defaults(fn=cmd_list)

    cr = sub.add_parser("create", help="register a dataset in a local store")
    cr.add_argument("--data-dir", required=True)
    cr.add_argument("--dataset", required=True)
    cr.add_argument("--num-shards", type=int, default=4)
    cr.add_argument("--schema", default="gauge")
    cr.set_defaults(fn=cmd_create)

    ic = sub.add_parser("importcsv", help="bulk import a CSV file")
    ic.add_argument("--data-dir", required=True)
    ic.add_argument("--dataset", required=True)
    ic.add_argument("--file", required=True)
    ic.add_argument("--schema", default="gauge")
    ic.add_argument("--tag-columns", required=True,
                    help="comma-separated tag column names")
    ic.add_argument("--timestamp-column", default="timestamp")
    ic.add_argument("--shard", type=int, default=0)
    ic.set_defaults(fn=cmd_importcsv)

    rc = sub.add_parser("rules-check",
                        help="validate rule files offline (promtool "
                             "check rules analog)")
    rc.add_argument("files", nargs="*",
                    help="JSON rule files ({\"groups\": [...]})")
    rc.add_argument("--builtin", action="store_true",
                    help="also validate the shipped self-monitoring "
                         "pack")
    rc.set_defaults(fn=cmd_rules_check)

    vc = sub.add_parser("verify-chunks",
                        help="offline checksum/decode scan of a "
                             "dataset's persisted chunks")
    vc.add_argument("--data-dir", required=True)
    vc.add_argument("--dataset", required=True)
    vc.add_argument("--shards", default=None,
                    help="comma-separated shard list (default: all)")
    vc.add_argument("--deep", action="store_true",
                    help="also decode every vector, not just checksums")
    vc.add_argument("--tier", choices=("local", "cold"), default="local",
                    help="which storage tier to scan (cold = the "
                         "object bucket, doc/coldstore.md)")
    vc.add_argument("--bucket-dir", default=None,
                    help="cold bucket root (default: "
                         "{data-dir}/coldstore)")
    vc.set_defaults(fn=cmd_verify_chunks)

    ao = sub.add_parser("age-out",
                        help="move chunks older than the retention "
                             "cutoff into the cold object bucket")
    ao.add_argument("--data-dir", required=True)
    ao.add_argument("--dataset", required=True)
    ao.add_argument("--retention", required=True,
                    help="age cutoff as a duration, e.g. 30d")
    ao.add_argument("--bucket-dir", default=None,
                    help="cold bucket root (default: "
                         "{data-dir}/coldstore)")
    ao.add_argument("--shards", default=None,
                    help="comma-separated shard list (default: all)")
    ao.add_argument("--dry-run", action="store_true",
                    help="print the migration plan, move nothing")
    ao.set_defaults(fn=cmd_age_out)

    lt = sub.add_parser("lint", add_help=False,
                        help="filolint static analysis: lock-discipline "
                             "races, blocking-under-lock, lock-order "
                             "deadlocks, device discipline, resource "
                             "lifecycle + the sentinel lints")
    lt.add_argument("args", nargs=argparse.REMAINDER,
                    help="passed through VERBATIM to python -m "
                         "filodb_tpu.analysis (--changed REF, --format, "
                         "--json, --rules, --list-rules, "
                         "--show-suppressed, --vmem-budget-mib, paths) "
                         "— no flags are hand-mirrored here, so new "
                         "analysis options never silently drop")
    lt.set_defaults(fn=cmd_lint)

    pk = sub.add_parser("partkey", help="decode a hex partkey")
    pk.add_argument("hex")
    pk.set_defaults(fn=cmd_partkey)

    mpk = sub.add_parser("make-partkey", help="tags JSON -> partkey hex")
    mpk.add_argument("tags")
    mpk.set_defaults(fn=cmd_make_partkey)

    dv = sub.add_parser("decode-vector", help="decode a hex vector blob")
    dv.add_argument("hex")
    dv.add_argument("--limit", type=int, default=20)
    dv.set_defaults(fn=cmd_decode_vector)

    return p


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "lint":
        # hand the rest straight to the filolint parser BEFORE argparse:
        # an option-first spelling (`lint --json`) would otherwise be
        # matched against the main parser instead of the REMAINDER
        from filodb_tpu.analysis.__main__ import main as lint_main
        return lint_main(argv[1:])
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
