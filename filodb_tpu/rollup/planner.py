"""Resolution-routed query planning: raw vs rolled tiers, stitched.

The serving half of the rollup subsystem: every query against a
rollup-enabled dataset plans through :class:`RollupRouterPlanner`,
which

1. computes the query's **resolution limit** — the coarsest period
   length that still puts >=1 rolled sample in every window the plan
   evaluates (min over step, range-function windows, and instant-
   selector lookbacks);
2. picks the **coarsest tier** within that limit (a month-long
   dashboard query at 1h step reads the 1h tier: thousands of samples
   instead of tens of millions — the tsdownsample decimation argument,
   arXiv:2307.05389).  ``?resolution=raw|auto|<duration>`` overrides;
3. **stitches at the tier boundary**: the rolled tier serves only up
   to the engine's per-tier closure watermark (and raw only down to
   its retention floor); the split/snap/stitch math is the reference's
   ``LongTimeRangePlanner`` (coordinator/planners.py), instantiated
   per query with the live boundary.  The ds-gauge column rewrites
   (query/dsrewrite.py) apply at the tier leaves exactly as on any
   downsampled dataset — ``sum_over_time`` reads the ``sum`` column,
   never a sum of averages;
4. **reports the chosen resolution**: stamped on the QueryContext at
   materialize time, folded into ``QueryStats.resolution_ms`` /
   ``data.stats.resolutionMs`` / the ``query.execute`` span by the
   HTTP layer, and counted per tier in
   ``filodb_rollup_queries_routed_total``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from filodb_tpu.coordinator.planner import QueryPlanner
from filodb_tpu.coordinator.planners import (LongTimeRangePlanner,
                                             plan_lookback_ms)
from filodb_tpu.query import logical as lp
from filodb_tpu.query.model import QueryContext

_NEG = -(1 << 62)

# instant selectors carry the Prometheus staleness lookback; a rolled
# period longer than it would leave every step empty
_DEFAULT_LOOKBACK_MS = 300_000


def resolution_limit_ms(plan: lp.LogicalPlan, step_ms: int) -> int:
    """Coarsest usable period length for this plan: every evaluation
    window (range-function window or instant lookback) and the step
    itself must hold >= 1 rolled sample."""
    limit = max(int(step_ms), 1)

    def walk(p):
        nonlocal limit
        if isinstance(p, lp.PeriodicSeriesWithWindowing):
            limit = min(limit, int(p.window_ms))
        elif isinstance(p, lp.PeriodicSeries):
            look = p.raw_series.lookback_ms or _DEFAULT_LOOKBACK_MS
            limit = min(limit, int(look))
        if dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    walk(v)
    walk(plan)
    return limit


def parse_resolution_pref(pref: str) -> Optional[object]:
    """``?resolution=`` values: '' / 'auto' -> None (router decides),
    'raw' -> 0, a duration ('1m') -> that many ms."""
    pref = (pref or "").strip().lower()
    if pref in ("", "auto"):
        return None
    if pref == "raw":
        return 0
    from filodb_tpu.http.model import parse_duration_ms
    return parse_duration_ms(pref)


#: canonical stats/span ordering for stitched tiers, oldest data first
TIER_ORDER = ("rolled-cold", "rolled-local", "raw")


def canonical_tiers(tiers) -> str:
    """'+'-joined tier attribution in canonical (oldest-first) order —
    the legs materialize in planner-internal order, so the raw append
    sequence is not presentation-stable."""
    seen = [t for t in TIER_ORDER if t in tiers]
    seen += [t for t in tiers if t not in seen]
    return "+".join(seen)


class _TierNotePlanner(QueryPlanner):
    """Wraps a leg planner purely for ATTRIBUTION: when the stitch
    math materializes this leg, the tier name lands on
    ``qctx.rollup_tiers`` (folded into QueryStats.tiers + the
    query.execute span by the HTTP layer) and the per-tier routing
    counter bumps.  Correctness never depends on it — both rolled legs
    read the same tier dataset through the TieredColumnStore merge."""

    def __init__(self, inner: QueryPlanner, tier: str, dataset: str,
                 routed_counter=None):
        self.inner = inner
        self.tier = tier
        self.dataset = dataset
        self._routed = routed_counter

    def materialize(self, plan, qctx=None):
        if qctx is not None and self.tier not in qctx.rollup_tiers:
            qctx.rollup_tiers.append(self.tier)
            if self._routed is not None:
                self._routed.inc(dataset=self.dataset, tier=self.tier)
        return self.inner.materialize(plan, qctx)


class RollupRouterPlanner(QueryPlanner):
    """Routes one dataset's queries across its resolution ladder."""

    def __init__(self, dataset: str, raw_planner: QueryPlanner,
                 tier_planners: dict[int, QueryPlanner],
                 rolled_through_fn: Callable[[int], int],
                 raw_retention_ms: Optional[int] = None,
                 now_ms_fn: Optional[Callable[[], int]] = None,
                 cold_floor_fn: Optional[Callable[[int], int]] = None):
        self.dataset = dataset
        self.raw = raw_planner
        self.tiers = dict(sorted(tier_planners.items()))
        self.rolled_through = rolled_through_fn
        self.raw_retention_ms = raw_retention_ms
        self.now_ms = now_ms_fn or (lambda: int(time.time() * 1000))
        # cold tier (ISSUE 16): resolution -> age-out floor of that
        # tier's dataset (epoch ms; 0 = nothing archived yet).  Chunks
        # ending before the floor live in the object bucket; the router
        # adds a rolled-local/rolled-cold stitch at it for attribution
        self.cold_floor = cold_floor_fn
        from filodb_tpu.utils.observability import rollup_metrics
        self._routed = rollup_metrics()["routed"]
        self._tier_served = rollup_metrics()["tier_served"]

    # ------------------------------------------------------------ selection

    def _pick_tier(self, limit_ms: int, start_ms: int,
                   pref: Optional[int]) -> Optional[int]:
        """Coarsest tier that fits the limit and has rolled data the
        query's range can use; None -> raw only."""
        if pref == 0:
            return None
        if pref is not None:
            if pref not in self.tiers:
                # an explicit pin to a duration outside the ladder is a
                # client mistake — silently serving raw would defeat the
                # very reproduction the pin exists for (400 upstream)
                ladder = ", ".join(f"{r // 1000}s" for r in self.tiers)
                raise ValueError(
                    f"resolution {pref}ms is not a configured rollup "
                    f"tier of {self.dataset!r} (ladder: {ladder}, or "
                    f"'raw'/'auto')")
            return pref
        best = None
        for res in self.tiers:
            if res <= limit_ms and self.rolled_through(res) > start_ms:
                best = res
        return best

    def _earliest_raw_ms(self) -> int:
        if self.raw_retention_ms is None:
            return _NEG
        return self.now_ms() - self.raw_retention_ms

    # --------------------------------------------------------- materialize

    def materialize(self, plan: lp.LogicalPlan,
                    qctx: Optional[QueryContext] = None):
        qctx = qctx or QueryContext()
        if not isinstance(plan, lp.PeriodicSeriesPlan):
            return self.raw.materialize(plan, qctx)
        try:
            start, step, end = lp.time_range(plan)
        except ValueError:
            return self.raw.materialize(plan, qctx)
        pref = parse_resolution_pref(qctx.resolution_pref)
        limit = resolution_limit_ms(plan, step)
        # the router IS deciding for this query (even when it decides
        # "raw"): mark the qctx so the HTTP layer tags the
        # query.execute span with the decision (ISSUE 15 — previously
        # only stats=true carried it, so slowlog traces of un-routed
        # raw serving were indistinguishable from un-tiered datasets)
        qctx.rollup_routed = True
        res = self._pick_tier(limit, start, pref)
        retention_floor = self._earliest_raw_ms()
        if res is None and retention_floor > start and self.tiers:
            # raw can't serve the head of the range: best-effort route
            # the finest tier even past the fidelity limit (partial
            # rolled data beats a silent hole; reference behavior)
            res = next(iter(self.tiers))
        if res is None:
            self._routed.inc(dataset=self.dataset, resolution="raw")
            if "raw" not in qctx.rollup_tiers:
                qctx.rollup_tiers.append("raw")
            return self.raw.materialize(plan, qctx)
        rolled_hwm = self.rolled_through(res)
        if rolled_hwm <= start:
            self._routed.inc(dataset=self.dataset, resolution="raw")
            if "raw" not in qctx.rollup_tiers:
                qctx.rollup_tiers.append("raw")
            return self.raw.materialize(plan, qctx)
        # the boundary raw serving starts at: everything the tier has
        # closed serves rolled, the live tail serves raw.  Unlike the
        # retention case LongTimeRangePlanner was built for, raw DOES
        # hold the data below this profit boundary — so the raw side's
        # "first step whose full lookback is raw-served" rule must be
        # offset by the lookback, or the one step whose window SPANS
        # the boundary would be served by neither side (a gap at every
        # stitch).  Raw retention (when configured) still floors it.
        look = plan_lookback_ms(plan)
        boundary = rolled_hwm + 1 - look
        if _NEG < retention_floor <= rolled_hwm:
            boundary = max(boundary, retention_floor)
        # retention past the rolled watermark is unenforceable without
        # a hole: the tier has nothing there yet, and raw still HOLDS
        # the data (raw-retention is a routing knob, it deletes
        # nothing) — so the raw side serves the gap instead of every
        # fresh step coming back empty
        qctx.rollup_resolution_ms = int(res)
        self._routed.inc(dataset=self.dataset, resolution=str(res))
        # the reference's raw/downsample split+stitch math, instantiated
        # with THIS query's live boundary (snap to step, lookback-aware)
        ltr = LongTimeRangePlanner(
            _TierNotePlanner(self.raw, "raw", self.dataset,
                             self._tier_served),
            self._rolled_leg(res, start, look),
            earliest_raw_time_fn=lambda _b=boundary: _b,
            latest_downsample_time_fn=lambda _h=rolled_hwm: _h)
        return ltr.materialize(plan, qctx)

    def _rolled_leg(self, res: int, start_ms: int, look_ms: int):
        """The rolled side of the stitch — with a THIRD boundary when
        the tier's age-out floor cuts the query range: data ending
        before the floor is guaranteed archived (rolled-cold), newer
        rolled data is still local sqlite (rolled-local).  Both legs
        read the SAME tier dataset through the TieredColumnStore merge,
        so the boundary is pure attribution: a stale watermark can
        mislabel a leg but never change bytes.  A year-long panel thus
        plans raw -> rolled-local -> rolled-cold and never touches the
        raw dataset below the profit boundary."""
        tier = self.tiers[res]
        local_leg = _TierNotePlanner(tier, "rolled-local", self.dataset,
                                     self._tier_served)
        cold_wm = self.cold_floor(res) if self.cold_floor is not None else 0
        if cold_wm <= start_ms:
            return local_leg
        cold_leg = _TierNotePlanner(tier, "rolled-cold", self.dataset,
                                    self._tier_served)
        # same gap-avoid offset as the outer stitch: the one step whose
        # lookback window spans the floor is served by the local leg
        cold_boundary = cold_wm + 1 - look_ms
        return LongTimeRangePlanner(
            local_leg, cold_leg,
            earliest_raw_time_fn=lambda _b=cold_boundary: _b,
            latest_downsample_time_fn=lambda _h=cold_wm: _h)
