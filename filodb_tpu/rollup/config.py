"""Rollup subsystem configuration: the resolution ladder + cadence.

Parsed from the per-dataset ``"rollup"`` block of the standalone config
(doc/rollup.md):

    "rollup": {
      "enabled": true,
      "resolutions": ["1m", "15m", "1h"],   # ascending ladder; each a
                                            # multiple of the previous
      "tick-interval-s": 30,                # scheduler cadence
      "raw-retention": "0",                 # 0/omit = raw keeps all;
                                            # else queries older than
                                            # this MUST serve rolled
      "idle-close": "2h",                   # force-close a silent
                                            # series' open periods
                                            # after this wall time
                                            # (0 disables)
      "stall-after-s": 120                  # tier stall gauge trips
                                            # after this many seconds
                                            # without progress while
                                            # work is pending
    }

A broken rollup block refuses startup (like a broken rule config):
silently rolling a subset of the configured tiers is worse than not
starting.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


class RollupConfigError(ValueError):
    pass


DEFAULT_RESOLUTIONS_MS = (60_000, 900_000, 3_600_000)  # 1m / 15m / 1h


@dataclasses.dataclass(frozen=True)
class RollupConfig:
    """One dataset's rollup ladder + scheduler knobs."""

    resolutions_ms: tuple = DEFAULT_RESOLUTIONS_MS
    tick_interval_s: float = 30.0
    # raw data older than this is considered unservable from the raw
    # dataset (the retention boundary LongTimeRangePlanner stitches
    # at); None = raw serves its whole history and rolled tiers are
    # used purely for scan-volume profit
    raw_retention_ms: Optional[int] = None
    # a series that stops ingesting holds its final (open) periods in
    # the buffer forever under pure closure semantics; after this wall
    # time without new samples its open periods are force-emitted and
    # the state dropped (None disables — the generative equivalence
    # sweeps run with it off)
    idle_close_s: Optional[float] = 7200.0
    # tier stall detection: the filodb_rollup_stalled level gauge trips
    # when a tier makes no progress for this long while work is pending
    stall_after_s: float = 120.0

    def __post_init__(self):
        res = tuple(int(r) for r in self.resolutions_ms)
        if not res:
            raise RollupConfigError("rollup needs >= 1 resolution")
        if sorted(res) != list(res) or len(set(res)) != len(res):
            raise RollupConfigError(
                f"rollup resolutions must be strictly ascending: {res}")
        if res[0] < 1000:
            raise RollupConfigError(
                f"rollup resolutions must be >= 1s: {res}")
        for fine, coarse in zip(res, res[1:]):
            if coarse % fine != 0:
                raise RollupConfigError(
                    f"each rollup resolution must be a multiple of the "
                    f"previous (cascade + period alignment): {coarse} "
                    f"% {fine} != 0")
        object.__setattr__(self, "resolutions_ms", res)
        if self.tick_interval_s <= 0:
            raise RollupConfigError("tick-interval-s must be > 0")

    @property
    def finest_ms(self) -> int:
        return self.resolutions_ms[0]

    @property
    def coarsest_ms(self) -> int:
        return self.resolutions_ms[-1]

    @staticmethod
    def from_config(conf: dict) -> "RollupConfig":
        """Parse the standalone ``"rollup"`` block (durations in the
        Prometheus spelling, e.g. ``"15m"``)."""
        from filodb_tpu.http.model import parse_duration_ms
        conf = dict(conf or {})
        known = {"enabled", "resolutions", "tick-interval-s",
                 "raw-retention", "idle-close", "stall-after-s",
                 "store", "query"}
        unknown = sorted(set(conf) - known)
        if unknown:
            # a misspelled knob silently applying the default is the
            # broken-rule-config failure mode: refuse startup instead
            raise RollupConfigError(
                f"unknown rollup config key(s) {unknown} "
                f"(known: {sorted(known)})")
        kwargs: dict = {}
        if "resolutions" in conf:
            try:
                kwargs["resolutions_ms"] = tuple(
                    parse_duration_ms(str(r)) for r in conf["resolutions"])
            except (ValueError, TypeError) as e:
                raise RollupConfigError(
                    f"bad rollup resolutions {conf['resolutions']!r}: "
                    f"{e}") from e
        if "tick-interval-s" in conf:
            kwargs["tick_interval_s"] = float(conf["tick-interval-s"])
        if conf.get("raw-retention") not in (None, 0, "0"):
            kwargs["raw_retention_ms"] = parse_duration_ms(
                str(conf["raw-retention"]))
        if "idle-close" in conf:
            idle = parse_duration_ms(str(conf["idle-close"])) \
                if conf["idle-close"] not in (0, "0", None) else None
            kwargs["idle_close_s"] = idle / 1000.0 \
                if idle is not None else None
        if "stall-after-s" in conf:
            kwargs["stall_after_s"] = float(conf["stall-after-s"])
        try:
            cfg = RollupConfig(**kwargs)
        except RollupConfigError:
            raise
        except (TypeError, ValueError) as e:
            raise RollupConfigError(f"bad rollup config: {e}") from e
        if cfg.idle_close_s is not None \
                and cfg.idle_close_s * 1000 < cfg.coarsest_ms:
            # an idle window shorter than the coarsest period would
            # force-close EVERY open coarse period mid-way for any
            # series scraped slower than the window — partial records
            # the complete ones could then never replace (tests use
            # the bare constructor for accelerated idle-close)
            raise RollupConfigError(
                f"idle-close ({cfg.idle_close_s}s) must cover the "
                f"coarsest resolution ({cfg.coarsest_ms // 1000}s)")
        return cfg
