"""Tiered-resolution serving: continuous on-device rollup inside the
live server (ROADMAP item 2; reference: the offline spark-jobs
downsampler + DownsampledTimeSeriesStore pair, run continuously).

- :mod:`filodb_tpu.rollup.config` — the per-dataset rollup ladder
  (raw -> 1m -> 15m -> 1h by default), tick cadence, routing policy.
- :mod:`filodb_tpu.rollup.engine` — the RollupEngine: per-shard
  incremental chunk consumption (only newly-flushed chunks per tick),
  per-series period closure, tier emission through the dataset's
  replicated publish path, persisted high-water marks.
- :mod:`filodb_tpu.rollup.planner` — RollupRouterPlanner: picks the
  coarsest tier whose resolution fits the query's step/window, stitches
  raw and rolled results at the tier boundary (LongTimeRangePlanner),
  and reports the chosen resolution in QueryStats.
"""

from filodb_tpu.rollup.config import RollupConfig  # noqa: F401
from filodb_tpu.rollup.engine import (ROLLUP_PRIORITY,  # noqa: F401
                                      ROLLUP_TENANT, RollupEngine)
from filodb_tpu.rollup.planner import RollupRouterPlanner  # noqa: F401
