"""The live rollup engine: continuous raw -> 1m -> 15m -> 1h tiering.

The offline scaffolding (``jobs.py``, ``downsample/``) could build
``<ds>_ds_<res>`` datasets, but nothing ever ran it inside a server —
a month-long dashboard query still scanned every raw sample.  This
engine runs the SAME downsample kernels (``downsample/griddown.py``
staged grids reduced under jit — the serving kernels driven as a batch
downsampler — with the per-series host path as the always-correct
fallback) continuously over freshly-flushed chunks:

- **incremental, chunk-aligned** (the PR 14 ``rules/incremental.py``
  idea, arXiv:2603.09555): each tick consumes ONLY the chunksets the
  flush pipeline produced since the last tick (a flush listener on
  :class:`TimeSeriesShard`; cold restarts catch up from the column
  store by ingestion time, resuming at persisted high-water marks);
- **per-series period closure**: a series' rollup period ``(P-res, P]``
  is emitted only once a flushed sample with ``ts > P`` exists for THAT
  series — per-series ingest is monotone, so a closed period can never
  change.  This is what makes the warm output **bit-equal** to the
  offline ``downsample/`` oracle over closed periods: the emitted
  records are computed by the same marker/downsampler code over the
  same rows, never a partial re-aggregation (two partial records for
  one period would collide on the period stamp and silently drop);
- **low-priority workload class**: each tick's consume+reduce runs
  under a ``"rollup"`` admission permit (share BELOW ``"low"`` in
  ``workload/admission.py``) with a minted deadline, so rollup defers
  under overload and can never starve user queries;
- **replicated, durable output**: emitted records publish through the
  tier dataset's normal publish path (in-proc queue, PR 12
  ``ReplicaFanout`` dual-write at rf>1), so rolled chunks are sharded,
  replicated, flushed through the integrity-checksummed store
  (CRC + quarantine semantics intact), and queryable like any dataset.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional

import numpy as np

from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.downsample.dsstore import ds_dataset_name
from filodb_tpu.downsample.sharddown import ShardDownsampler
from filodb_tpu.query.model import QueryContext
from filodb_tpu.utils.observability import (TRACER, PeriodicThread,
                                            rollup_metrics)
from filodb_tpu.workload import deadline as wdl

# the engine's admission identity: a dedicated class priced BELOW the
# rules engine (workload/admission.py DEFAULT_PRIORITY_SHARES) and a
# reserved tenant so rollup work is attributable in /admin/workload
ROLLUP_PRIORITY = "rollup"
ROLLUP_TENANT = "_rollup"

_NEG = -(1 << 62)
_QUEUE_CAP = 50_000        # flush batches buffered per shard before loss
# idle-closed series keep their emitted stamps in the restart-seed map
# so a resumed series cannot re-emit a force-closed period; the map is
# soft-capped (drop oldest-inserted half) against unbounded churn
_RESTORED_CAP = 500_000


def _ck_name(dataset: str) -> str:
    """Metastore checkpoint key for rollup high-water marks (namespaced
    so it can never collide with a real dataset's ingest checkpoints)."""
    return f"__rollup__:{dataset}"


def _cat_col(a, b):
    """Concatenate two decoded column parts: plain arrays, or histogram
    ``(buckets, rows)`` tuples (widening-aware, ISSUE 14)."""
    if isinstance(a, tuple) or isinstance(b, tuple):
        from filodb_tpu.core.histogram import concat_hist_parts
        return concat_hist_parts([a, b])
    return np.concatenate([a, b])


def _take_col(c, order, keep):
    """Row-select a (possibly histogram-tuple) concatenated column."""
    if isinstance(c, tuple):
        return c[0], c[1][order][keep]
    return c[order][keep]


def _emit_col(c, mask):
    """Downsampled output column -> per-row record values.  Histogram
    downsamplers (hSum/hLast) emit ``(buckets, rows)``; each masked row
    encodes back to the wire histogram value the tier schema's hist
    column ingests (same encode as the flush path's per-row emit,
    downsample/sharddown.py _emit)."""
    if isinstance(c, tuple):
        from filodb_tpu.codecs import histcodec
        buckets, rows = c
        return [histcodec.encode_hist_value(buckets, r)
                for r in np.asarray(rows)[mask]]
    return np.asarray(c)[mask].tolist()


class _SeriesState:
    """One raw series' resident tail: rows newer than the oldest tier's
    emitted boundary, plus per-tier emitted stamps."""

    __slots__ = ("partkey", "tags", "schema_hash", "ts", "cols",
                 "emitted", "last_seen_s")

    def __init__(self, partkey: bytes, tags: dict, schema_hash: int,
                 seed_emitted: Optional[dict] = None):
        self.partkey = partkey
        self.tags = tags
        self.schema_hash = schema_hash
        self.ts: Optional[np.ndarray] = None
        self.cols: list = []
        # res -> newest emitted period stamp (restored from the tier
        # dataset's persisted chunks on cold restart)
        self.emitted: dict[int, int] = dict(seed_emitted or {})
        self.last_seen_s = 0.0

    def append(self, ts: np.ndarray, cols: list) -> None:
        """Append decoded rows.  Per-series ingest is monotone so new
        chunks normally extend the tail; the defensive merge handles
        restart catch-up re-reading a chunk the live listener already
        delivered (exact-duplicate timestamps keep the first copy).
        Histogram columns arrive as ``(buckets, rows)`` tuples and
        merge bucket-scheme-aware (mid-stream widening edge-pads, see
        core.histogram.concat_hist_parts)."""
        if self.ts is None or len(self.ts) == 0:
            self.ts = ts
            self.cols = list(cols)
            return
        if len(ts) == 0:
            return
        if int(ts[0]) > int(self.ts[-1]):
            self.ts = np.concatenate([self.ts, ts])
            self.cols = [_cat_col(a, b)
                         for a, b in zip(self.cols, cols)]
            return
        merged_ts = np.concatenate([self.ts, ts])
        order = np.argsort(merged_ts, kind="stable")
        merged_ts = merged_ts[order]
        keep = np.ones(len(merged_ts), bool)
        keep[1:] = merged_ts[1:] != merged_ts[:-1]
        self.ts = merged_ts[keep]
        self.cols = [_take_col(_cat_col(a, b), order, keep)
                     for a, b in zip(self.cols, cols)]

    def prune(self, resolutions) -> None:
        """Drop rows EVERY configured tier has emitted (ts <= min
        emitted stamp, a tier with no cursor yet counting as minus
        infinity — a tier that failed to publish still needs its
        rows).  Rows in open periods always survive — closure needs
        them."""
        if self.ts is None or len(self.ts) == 0:
            return
        floor = min(self.emitted.get(r, _NEG) for r in resolutions)
        if floor <= _NEG:
            return
        i = int(np.searchsorted(self.ts, floor, side="right"))
        if i > 0:
            self.ts = self.ts[i:]
            self.cols = [(c[0], c[1][i:]) if isinstance(c, tuple)
                         else c[i:] for c in self.cols]

    @property
    def buffered(self) -> int:
        return 0 if self.ts is None else len(self.ts)


class _ShardRollup:
    """Per-raw-shard rollup state (one flush listener feeds it)."""

    def __init__(self, shard_num: int):
        self.shard_num = shard_num
        # flush listener -> tick handoff: [(itime, {schema: [(tags, cs)]})]
        self.queue: list = []
        self.lost = False              # queue overflowed: continuity broken
        self.series: dict[bytes, _SeriesState] = {}
        # restart seeds: partkey -> {res: emitted stamp} from the tier
        # datasets' persisted chunks, consumed as series reappear
        self.restored: dict[bytes, dict] = {}
        self.it_hwm = -1               # newest consumed ingestion time
        # chunks whose rows are not yet emitted by every tier:
        # [itime, end_ts, partkey] — min itime is the restart replay floor
        self.pending: list = []
        self.samplers: dict[int, Optional[ShardDownsampler]] = {}
        self.active = False            # this node currently rolls this shard
        # a tier errored (emission or publish): the next tick must
        # re-attempt emission over EVERY buffered series even with no
        # fresh chunks — the failed rows are already consumed from the
        # queue and live only in the buffers
        self.retry = False


class _DatasetRollup:
    def __init__(self, dataset, memstore, schemas, config, publish_for,
                 column_store, meta_store, owner_fn, admission):
        self.dataset = dataset
        self.memstore = memstore
        self.schemas = schemas
        self.config = config
        self.publish_for = publish_for      # res -> publish(shard, container)
        self.column_store = column_store
        self.meta_store = meta_store
        self.owner_fn = owner_fn            # shard -> bool (primary here?)
        self.admission = admission
        self.lock = threading.Lock()
        self.shards: dict[int, _ShardRollup] = {}
        self.loop: Optional[PeriodicThread] = None
        # telemetry the admin view + router read
        self.samples_written: dict[int, int] = {r: 0 for r
                                                in config.resolutions_ms}
        self.passes = 0
        self.deferred = 0
        self.last_pass_s = 0.0
        self.last_pass_at_s = 0.0
        self.tier_errors: dict[int, str] = {}
        self.tier_last_advance: dict[int, float] = {}
        self.rolled_cache: dict[int, int] = {}   # res -> stitch boundary
        # the two halves the cluster gossip composes separately
        # (ROADMAP 2b): what THIS node's owned shards have closed (the
        # value it gossips), and what the local tier replicas have had
        # delivered (the serve-locally clamp)
        self.owned_cache: dict[int, int] = {}
        self.delivered_cache: dict[int, int] = {}


class RollupEngine:
    """Owns every watched dataset's rollup ladder: scheduling, cursor
    state, emission, telemetry."""

    def __init__(self, node: str = ""):
        self.node = node
        self._m = rollup_metrics()
        self._datasets: dict[str, _DatasetRollup] = {}
        self._started = False
        self._gauge_rows: set = set()   # (metric, labels...) rows to remove

    # ------------------------------------------------------------- lifecycle

    def watch(self, dataset: str, memstore, schemas, config,
              publish_for: dict, column_store=None, meta_store=None,
              owner_fn: Optional[Callable[[int], bool]] = None,
              admission=None) -> None:
        """Register one raw dataset: attach a flush listener to each of
        its local shards and (for owned shards) restore cursors from the
        persisted high-water marks + tier datasets."""
        d = _DatasetRollup(dataset, memstore, schemas, config, publish_for,
                           column_store, meta_store, owner_fn, admission)
        self._datasets[dataset] = d
        for sh in memstore.shards(dataset):
            self.attach_shard(dataset, sh)

    def attach_shard(self, dataset: str, shard) -> None:
        """Wire one raw shard's flush stream into the engine (listener
        payload mirrors the flush path's downsample pairs: chunksets
        grouped by schema, tagged with the flush ingestion time)."""
        d = self._datasets[dataset]
        sr = _ShardRollup(shard.shard_num)
        with d.lock:
            d.shards[shard.shard_num] = sr
        self._install_listener(d, sr, shard)

    def _install_listener(self, d, sr, shard) -> None:
        shard.rollup_listener = \
            lambda pairs, itime, _d=d, _sr=sr: self._on_flush(_d, _sr,
                                                              pairs, itime)

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for d in self._datasets.values():
            # re-attach flush listeners after a previous stop() (which
            # detaches them); existing shard state — cursors, buffers —
            # is reused, and anything flushed while stopped replays
            # from the store on the next owned tick
            for sh in d.memstore.shards(d.dataset):
                sr = d.shards.get(sh.shard_num)
                if sr is None:
                    self.attach_shard(d.dataset, sh)
                elif sh.rollup_listener is None:
                    sr.active = False   # replay the stopped gap
                    self._install_listener(d, sr, sh)
            d.loop = PeriodicThread(
                lambda _d=d: self._tick(_d),
                d.config.tick_interval_s, f"rollup-{d.dataset}")
            d.loop.start()

    def stop(self) -> None:
        self._started = False
        for d in self._datasets.values():
            if d.loop is not None:
                d.loop.stop()
                d.loop = None
            # detach the flush listeners (the PR 13 lifecycle
            # discipline: every registration needs a remove path) — a
            # stopped engine must not keep accumulating chunksets into
            # queues no tick will ever drain, nor stay pinned by the
            # listener closures
            for sh in d.memstore.shards(d.dataset):
                sh.rollup_listener = None
            with d.lock:
                for sr in d.shards.values():
                    sr.queue = []
        # Gauge.remove contract: a stopped engine must not keep
        # exporting lag/stall rows (a dead node's stalled=1 would feed
        # the self-monitoring alerts forever — the PR 14 ledger lesson)
        for metric, labels in list(self._gauge_rows):
            self._m[metric].remove(**dict(labels))
        self._gauge_rows.clear()

    # ------------------------------------------------------------- ingest

    def _on_flush(self, d: _DatasetRollup, sr: _ShardRollup,
                  pairs_by_schema: dict, itime: int) -> None:
        """Flush-executor hook: enqueue freshly-flushed chunksets for
        the next tick.  Must never block or raise into the flush path."""
        with d.lock:
            if len(sr.queue) >= _QUEUE_CAP:
                # backlog cap: drop the handoff LOUDLY and fall back to
                # the store-replay path — the dropped chunks are
                # already persisted, so flipping the shard inactive
                # makes the next owned tick restore from the
                # ingestion-time floor instead of losing them (an
                # in-memory-only store keeps the loss, flagged)
                if not sr.lost:
                    for res in d.config.resolutions_ms:
                        d.tier_errors[res] = (
                            "flush-queue overflow: backlog dropped, "
                            "replaying from the store")
                sr.lost = True
                sr.active = False
                return
            sr.queue.append((itime, pairs_by_schema))

    # --------------------------------------------------------------- tick

    def run_once(self, dataset: str) -> None:
        """One synchronous pass over a dataset (tests, warm-up)."""
        self._tick(self._datasets[dataset])

    def _tick(self, d: _DatasetRollup) -> None:
        t0 = time.perf_counter()
        now_s = time.time()
        with TRACER.span("rollup.pass", dataset=d.dataset):
            # shards materialized after watch() (failover gain, late
            # resync) pick up their flush listener here
            for sh in d.memstore.shards(d.dataset):
                if sh.shard_num not in d.shards:
                    self.attach_shard(d.dataset, sh)
            with d.lock:
                shard_nums = list(d.shards)
            for s in shard_nums:
                sr = d.shards.get(s)
                if sr is None:
                    continue
                withheld: set = set()   # tiers in trouble this tick
                self._tick_shard(d, sr, now_s, withheld)
                # PER-SHARD stall clocks: one healthy shard must not
                # mask a permanently wedged one — the gauge below
                # reports the WORST shard per tier.  WITHHELD vetoes
                # advanced: a tier where one schema emitted but
                # another failed is still in trouble
                for res in d.config.resolutions_ms:
                    key = (s, res)
                    if res not in withheld:
                        d.tier_last_advance[key] = now_s
                    else:
                        # first withheld tick anchors the stall clock
                        d.tier_last_advance.setdefault(key, now_s)
            self._refresh_rolled_cache(d)
        dur = time.perf_counter() - t0
        d.last_pass_s = dur
        d.last_pass_at_s = now_s
        d.passes += 1
        self._m["passes"].inc(dataset=d.dataset)
        self._m["pass_seconds"].observe(dur, dataset=d.dataset)
        for res in d.config.resolutions_ms:
            stale = any(
                now_s - d.tier_last_advance.get((s, res), now_s)
                > d.config.stall_after_s for s in shard_nums)
            self._set_gauge("stalled", 1.0 if stale else 0.0,
                            dataset=d.dataset, resolution=str(res))

    def _tick_shard(self, d: _DatasetRollup, sr: _ShardRollup, now_s: float,
                    withheld: set) -> None:
        with d.lock:
            batches = sr.queue
            sr.queue = []
        owner = d.owner_fn is None or d.owner_fn(sr.shard_num)
        if not owner:
            # not the rolling replica for this shard: drop the backlog
            # (the owner consumes its own flush stream) and forget any
            # buffered state — a later ownership gain restores from the
            # persisted high-water marks instead of half-stale buffers.
            # The exported lag/buffered rows go too: a frozen lag value
            # from before the failover must not keep an alert latched
            # while the NEW owner is caught up
            if sr.active:
                with d.lock:
                    sr.series.clear()
                    sr.pending.clear()
                    sr.active = False
                self._clear_shard_gauges(d, sr)
            return
        if not sr.active:
            try:
                batches = self._restore_shard(d, sr) + batches
            except Exception as e:  # noqa: BLE001 — store unreadable:
                # requeue the drained flush batches and retry the
                # restore next tick (sr.active stays False)
                with d.lock:
                    sr.queue = batches + sr.queue
                for res in d.config.resolutions_ms:
                    d.tier_errors[res] = repr(e)
                withheld.update(d.config.resolutions_ms)
                return
            sr.active = True
        nchunks = sum(len(css) for _it, by_schema in batches
                      for css in by_schema.values())
        idle = self._idle_states(d, sr, now_s)
        if nchunks == 0 and not idle and not sr.retry:
            self._set_shard_gauges(d, sr)
            return
        permit = contextlib.nullcontext()
        if d.admission is not None and getattr(d.admission, "enabled", False) \
                and nchunks:
            from filodb_tpu.workload.admission import AdmissionRejected
            qctx = wdl.mint(QueryContext(
                submit_time_ms=int(now_s * 1000),
                timeout_ms=int(d.config.tick_interval_s * 1000),
                tenant=ROLLUP_TENANT,
                priority=ROLLUP_PRIORITY))
            try:
                permit = d.admission.admit(qctx, float(nchunks))
            except AdmissionRejected:
                # overloaded: rollup yields — requeue and retry next tick
                with d.lock:
                    sr.queue = batches + sr.queue
                d.deferred += 1
                self._m["deferred"].inc(dataset=d.dataset)
                withheld.update(d.config.resolutions_ms)
                return
        with permit:
            try:
                self._consume_and_emit(d, sr, batches, idle, now_s,
                                       withheld)
            except Exception as e:  # noqa: BLE001 — a consume failure
                # (decode, staging) must not LOSE the drained batches:
                # requeue them whole and retry next tick.  Re-consumed
                # rows dedupe at append and re-emission masks on the
                # cursors, so the retry is idempotent; a permanently
                # poisoned chunk wedges THIS shard's rollup loudly
                # (stall gauge -> self-monitoring alert) instead of
                # silently diverging from raw.
                with d.lock:
                    sr.queue = batches + sr.queue
                for res in d.config.resolutions_ms:
                    d.tier_errors[res] = repr(e)
                    self._m["errors"].inc(dataset=d.dataset,
                                          resolution=str(res))
                withheld.update(d.config.resolutions_ms)
                sr.retry = True
        self._set_shard_gauges(d, sr)

    # ------------------------------------------------------ consume + emit

    def _consume_and_emit(self, d, sr, batches, idle, now_s,
                          withheld) -> None:
        touched: dict[int, dict] = {}        # schema -> {id(tags): state}
        per_schema: dict[int, list] = {}
        ledger_add: list = []
        for itime, by_schema in batches:
            sr.it_hwm = max(sr.it_hwm, int(itime))
            for shash, pairs in by_schema.items():
                per_schema.setdefault(shash, []).extend(pairs)
                for _tags, cs in pairs:
                    ledger_add.append([int(itime), int(cs.info.end_time),
                                       cs.partkey])
        for shash, pairs in per_schema.items():
            sampler = self._sampler(d, sr, shash)
            if sampler is None:
                continue
            from filodb_tpu.downsample.sharddown import \
                decode_concat_with_keys
            decoded_new = decode_concat_with_keys(sampler.schema, pairs)
            with d.lock:
                for pk, tags, ts, cols in decoded_new:
                    st = sr.series.get(pk)
                    if st is None:
                        st = sr.series[pk] = _SeriesState(
                            pk, tags, shash,
                            seed_emitted=sr.restored.pop(pk, None))
                    st.append(np.asarray(ts, dtype=np.int64), cols)
                    st.last_seen_s = now_s
                    touched.setdefault(shash, {})[id(st.tags)] = st
        # a series that RESUMED in this very tick is no longer idle —
        # force-closing it now would emit its open period mid-way and
        # the later rows could never replace the partial record
        fresh = {sid for m in touched.values() for sid in m}
        idle = [st for st in idle if id(st.tags) not in fresh]
        for st in idle:
            # force-close a silent series: emit its open periods too
            touched.setdefault(st.schema_hash, {}).setdefault(
                id(st.tags), st)
        if sr.retry:
            # a previous tier failure left closed-but-unemitted rows in
            # the buffers: re-attempt every buffered series (already-
            # emitted periods mask out, so the pass is idempotent)
            with d.lock:
                for st in sr.series.values():
                    touched.setdefault(st.schema_hash, {}).setdefault(
                        id(st.tags), st)
        failed = False
        emitted: list = []      # (res, n, [containers], cursor updates)
        for shash, stmap in touched.items():
            sampler = self._sampler(d, sr, shash)
            if sampler is None:
                continue
            states = [st for st in stmap.values() if st.buffered]
            if not states:
                continue
            decoded = [(st.tags, st.ts, st.cols) for st in states]
            prepared = sampler.prepare_decoded(decoded)
            by_id = {id(st.tags): st for st in states}
            force_close = {id(st.tags) for st in idle}
            for res in d.config.resolutions_ms:
                try:
                    n, containers, updates = self._emit_tier(
                        sampler, prepared, by_id, force_close, res)
                except Exception as e:  # noqa: BLE001 — one tier's failure
                    # must not block the others (or the next tick)
                    d.tier_errors[res] = repr(e)
                    self._m["errors"].inc(dataset=d.dataset,
                                          resolution=str(res))
                    withheld.add(res)
                    failed = True
                    continue
                if n:
                    emitted.append((res, n, containers, updates))
        # publish OUTSIDE the state lock (the fanout/broker edge may
        # block), and advance the cursors only AFTER the tier's
        # containers left this process: a failed publish retries the
        # whole emission next tick — re-sent duplicates of a partially
        # delivered pass are dropped by the tier partition's equal-
        # timestamp dedupe, while an advanced-but-unsent cursor would
        # lose the rows forever
        all_published = True
        for res, n, containers, updates in emitted:
            publish = d.publish_for.get(res)
            try:
                if publish is not None:
                    for container in containers:
                        publish(sr.shard_num, container)
            except Exception as e:  # noqa: BLE001 — transport failure:
                # leave the cursor, retry next tick
                d.tier_errors[res] = repr(e)
                self._m["errors"].inc(dataset=d.dataset,
                                      resolution=str(res))
                withheld.add(res)
                all_published = False
                failed = True
                continue
            if res not in withheld:
                # only a FULLY healthy tier clears its error: another
                # schema's emission failure for this res in this same
                # tick must stay visible (and keep the stall clock
                # withheld) — a healthy schema must not mask it
                d.tier_errors.pop(res, None)
            with d.lock:
                for st, stamp in updates:
                    st.emitted[res] = stamp
            d.samples_written[res] += n
            self._m["samples"].inc(n, dataset=d.dataset,
                                   resolution=str(res))
        with d.lock:
            if all_published and not failed:
                # idle (force-closed) states drop only once EVERY tier
                # emitted AND delivered — otherwise their rows must
                # survive for the retry.  Their emitted stamps PERSIST
                # in the restart-
                # seed map: if the series resumes inside a force-closed
                # period, a fresh state would otherwise re-emit that
                # period's stamp from the new rows alone and the tier's
                # first-copy dedupe would keep the PARTIAL record
                for st in idle:
                    if st.emitted:
                        sr.restored[st.partkey] = dict(st.emitted)
                    sr.series.pop(st.partkey, None)
                if len(sr.restored) > _RESTORED_CAP:
                    for pk in list(sr.restored)[:_RESTORED_CAP // 2]:
                        sr.restored.pop(pk, None)
            for st in sr.series.values():
                st.prune(d.config.resolutions_ms)
            sr.pending.extend(ledger_add)
            keep = []
            for entry in sr.pending:
                st = sr.series.get(entry[2])
                if st is None:
                    continue
                floor = min(st.emitted.get(r, _NEG)
                            for r in d.config.resolutions_ms)
                if entry[1] > floor:
                    keep.append(entry)
            sr.pending = keep
            floor_itime = min((e[0] for e in sr.pending),
                              default=sr.it_hwm + 1)
            sr.retry = failed
        self._persist(d, sr, floor_itime)

    def _emit_tier(self, sampler, prepared, by_id, force_close,
                   res: int):
        """One (schema, resolution) emission pass: downsample the
        resident buffers with the shared grid/host kernels, keep only
        newly-CLOSED periods per series, build record containers.
        Returns (records, containers, cursor updates) — the caller
        applies the updates only after the containers are delivered."""
        outs = sampler.downsample_arrays(prepared, res)
        builder = None
        updates: list = []
        n = 0
        for tags, pe, cols in outs:
            st = by_id.get(id(tags))
            if st is None or st.ts is None or len(st.ts) == 0:
                continue
            if id(tags) in force_close:
                closed = 1 << 62        # emit open periods too (idle close)
            else:
                # period (P-res, P] closes only once a sample PAST it
                # exists for this series — monotone per-series ingest
                # means the period can then never change
                closed = ((int(st.ts[-1]) - 1) // res) * res
            pe = np.asarray(pe, dtype=np.int64)
            mask = pe <= closed
            prev = st.emitted.get(res)
            if prev is not None:
                mask &= pe > prev
            if not mask.any():
                continue
            if builder is None:
                builder = RecordBuilder(sampler.ds_schema)
            pe_m = pe[mask]
            builder.add_series([int(x) for x in pe_m],
                               [_emit_col(c, mask) for c in cols], tags)
            updates.append((st, int(pe_m[-1])))
            n += len(pe_m)
        return n, (builder.containers() if builder is not None else []), \
            updates

    def _idle_states(self, d, sr, now_s: float) -> list:
        if d.config.idle_close_s is None:
            return []
        cutoff = now_s - d.config.idle_close_s
        with d.lock:
            return [st for st in sr.series.values()
                    if st.buffered and st.last_seen_s
                    and st.last_seen_s < cutoff]

    def _sampler(self, d, sr, schema_hash: int):
        """ShardDownsampler for one raw schema, memoized; None when the
        schema can't roll (no downsamplers / no downsample schema).
        Histogram schemas roll through their hSum/hLast period oracles
        (downsample/chunkdown.py) since ISSUE 14 — the grid staging
        declines them (griddown.grid_supported), so they reduce on the
        always-correct per-series host path."""
        if schema_hash in sr.samplers:
            return sr.samplers[schema_hash]
        sampler = None
        try:
            schema = d.schemas.by_hash(schema_hash)
        except KeyError:
            schema = None
        if schema is not None:
            s = ShardDownsampler(d.dataset, sr.shard_num, schema, None,
                                 d.config.resolutions_ms)
            if s.enabled:
                sampler = s
        sr.samplers[schema_hash] = sampler
        return sampler

    # ------------------------------------------------------------- restart

    def _restore_shard(self, d: _DatasetRollup, sr: _ShardRollup) -> list:
        """Cold restart / ownership gain: seed per-series emitted stamps
        from the tier datasets' persisted chunks (a rolled record's
        stamp IS the cursor) and replay raw chunks from the persisted
        ingestion-time floor.  Returns listener-shaped batches."""
        from filodb_tpu.store.columnstore import NullColumnStore
        store = d.column_store
        if store is None or isinstance(store, NullColumnStore):
            return []
        for res in d.config.resolutions_ms:
            name = ds_dataset_name(d.dataset, res)
            try:
                for _it, cs in store.chunksets_with_ingestion_time(
                        name, sr.shard_num, 0, 1 << 62):
                    seeds = sr.restored.setdefault(cs.partkey, {})
                    seeds[res] = max(seeds.get(res, _NEG),
                                     int(cs.info.end_time))
            except Exception:  # noqa: BLE001 — tier dataset not created yet
                continue
        floor = None
        if d.meta_store is not None:
            try:
                cps = d.meta_store.read_checkpoints(_ck_name(d.dataset),
                                                    sr.shard_num)
            except Exception:  # noqa: BLE001 — meta store not ready
                cps = {}
            floor = cps.get(0)
            sr.it_hwm = max(sr.it_hwm, cps.get(1, -1))
        if floor is None:
            return []
        from filodb_tpu.core.record import parse_partkey
        tags_memo: dict[bytes, dict] = {}
        batches: dict[int, dict] = {}
        for itime, cs in store.chunksets_with_ingestion_time(
                d.dataset, sr.shard_num, floor, 1 << 62):
            schema = self._schema_of(d, cs)
            if schema is None:
                continue
            tags = tags_memo.get(cs.partkey)
            if tags is None:
                tags = tags_memo[cs.partkey] = parse_partkey(cs.partkey)
            batches.setdefault(int(itime), {}).setdefault(
                schema.schema_hash, []).append((tags, cs))
        return [(it, batches[it]) for it in sorted(batches)]

    @staticmethod
    def _schema_of(d, cs):
        if cs.schema_hash:
            try:
                return d.schemas.by_hash(cs.schema_hash)
            except KeyError:
                return None
        for s in d.schemas.all:
            if len(s.data.columns) == len(cs.vectors) \
                    and s.downsample is not None:
                return s
        return None

    def _persist(self, d, sr, floor_itime: int) -> None:
        """Write the restart high-water marks: group 0 = the replay
        floor (oldest ingestion time still holding unemitted rows),
        group 1 = the consumed ingestion-time high-water."""
        from filodb_tpu.store.columnstore import NullColumnStore
        if d.meta_store is None or d.column_store is None \
                or isinstance(d.column_store, NullColumnStore):
            return
        try:
            d.meta_store.write_checkpoint(_ck_name(d.dataset),
                                          sr.shard_num, 0, int(floor_itime))
            d.meta_store.write_checkpoint(_ck_name(d.dataset),
                                          sr.shard_num, 1, int(sr.it_hwm))
        except Exception:  # noqa: BLE001 — cursor persistence is advisory;
            # the next successful tick rewrites it
            pass

    # ------------------------------------------------------------ telemetry

    def _set_gauge(self, metric: str, value: float, **labels) -> None:
        self._m[metric].set(value, **labels)
        self._gauge_rows.add((metric, tuple(sorted(labels.items()))))

    def _clear_shard_gauges(self, d, sr) -> None:
        """Remove one shard's exported lag/buffered rows (ownership
        loss): frozen values must not outlive the state behind them."""
        rows = [("buffered", {"dataset": d.dataset,
                              "shard": str(sr.shard_num)})]
        for res in d.config.resolutions_ms:
            rows.append(("lag", {"dataset": d.dataset,
                                 "shard": str(sr.shard_num),
                                 "resolution": str(res)}))
        for metric, labels in rows:
            self._m[metric].remove(**labels)
            self._gauge_rows.discard(
                (metric, tuple(sorted(labels.items()))))

    def _set_shard_gauges(self, d, sr) -> None:
        with d.lock:
            states = list(sr.series.values())
        buffered = sum(st.buffered for st in states)
        self._set_gauge("buffered", float(buffered), dataset=d.dataset,
                        shard=str(sr.shard_num))
        data_hwm = max((int(st.ts[-1]) for st in states
                        if st.ts is not None and len(st.ts)), default=None)
        data_floor = min((int(st.ts[0]) for st in states
                          if st.ts is not None and len(st.ts)), default=None)
        for res in d.config.resolutions_ms:
            if data_hwm is None:
                lag = 0.0
            else:
                emitted = max((st.emitted.get(res, _NEG)
                               for st in states), default=_NEG)
                if emitted > _NEG:
                    lag = max(0.0, (data_hwm - emitted) / 1000.0)
                else:
                    # nothing emitted yet: the whole buffer is unrolled
                    lag = max(0.0, (data_hwm - data_floor) / 1000.0)
            self._set_gauge("lag", lag, dataset=d.dataset,
                            shard=str(sr.shard_num), resolution=str(res))

    def _refresh_rolled_cache(self, d) -> None:
        """Per-tier stitch boundary: the newest stamp up to which EVERY
        live series of every owned shard has been rolled — the router
        serves rolled data only below it, raw above (no gaps).

        Shards OTHER nodes roll contribute through the tier dataset's
        local replica instead: the newest rolled stamp actually
        DELIVERED here floors the boundary, so a peer whose rollup
        lags (deferrals, tier errors, a dead fanout lane) pulls the
        stitch down rather than leaving silent holes in its shards'
        rolled range.  (Intra-shard series skew on peer shards still
        needs tier-watermark gossip — ROADMAP follow-up.)"""
        out: dict[int, int] = {}
        owned_out: dict[int, int] = {}
        delivered_out: dict[int, int] = {}
        with d.lock:
            for res in d.config.resolutions_ms:
                vals: list[int] = []
                for sr in d.shards.values():
                    if not sr.active:
                        continue
                    for st in sr.series.values():
                        e = st.emitted.get(res)
                        if e is None:
                            if st.ts is None or not len(st.ts):
                                continue
                            # nothing closed yet: data before this
                            # series' first sample is not MISSING, so
                            # its floor is the period before it
                            e = ((int(st.ts[0]) - 1) // res) * res
                        vals.append(e)
                local = min(vals) if vals else None
                delivered = [sh.latest_ingest_ts for sh in
                             d.memstore.shards(ds_dataset_name(d.dataset,
                                                               res))
                             if sh.latest_ingest_ts >= 0]
                clamp = min(delivered) if delivered else None
                if local is not None:
                    owned_out[res] = local
                if clamp is not None:
                    delivered_out[res] = clamp
                if local is not None and clamp is not None:
                    out[res] = min(local, clamp)
                elif clamp is not None:
                    # a pure-replica node (owns no primaries) can still
                    # route from the tier data delivered to it
                    out[res] = clamp
                elif local is not None:
                    out[res] = local
            d.rolled_cache = out
            d.owned_cache = owned_out
            d.delivered_cache = delivered_out

    # ---------------------------------------------------------------- views

    def rolled_through(self, dataset: str, res: int) -> int:
        """Newest sample time the tier serves without gaps (very
        negative when nothing is rolled yet)."""
        d = self._datasets.get(dataset)
        if d is None:
            return _NEG
        with d.lock:
            return d.rolled_cache.get(res, _NEG)

    def owned_rolled_through(self, dataset: str, res: int) -> Optional[int]:
        """Closure boundary over the shards THIS node rolls (None when
        it owns none) — the authoritative value this node gossips."""
        d = self._datasets.get(dataset)
        if d is None:
            return None
        with d.lock:
            return d.owned_cache.get(res)

    def delivered_through(self, dataset: str, res: int) -> Optional[int]:
        """Newest rolled stamp delivered to every local tier replica
        (None when this node holds no tier data) — the serve-locally
        clamp the cluster-wide boundary still must respect."""
        d = self._datasets.get(dataset)
        if d is None:
            return None
        with d.lock:
            return d.delivered_cache.get(res)

    def rolled_snapshot(self) -> dict:
        """Per-dataset owned-closure watermarks for the ``/__health``
        gossip payload (ROADMAP 2b): only shards this node actually
        rolls — peers compose their own delivered clamps."""
        out: dict = {}
        for ds, d in self._datasets.items():
            with d.lock:
                tiers = {str(r): v for r, v in d.owned_cache.items()}
            if tiers:
                out[ds] = tiers
        return out

    def datasets(self) -> list[str]:
        return list(self._datasets)

    def config_for(self, dataset: str):
        d = self._datasets.get(dataset)
        return d.config if d is not None else None

    def admin_state(self) -> dict:
        """``GET /admin/rollup``: cursor positions, lag vs the flush
        watermark, pass timing, rows written, per-tier health."""
        out = []
        for ds, d in self._datasets.items():
            with d.lock:
                shards = []
                for sr in sorted(d.shards.values(),
                                 key=lambda s: s.shard_num):
                    states = list(sr.series.values())
                    data_hwm = max((int(st.ts[-1]) for st in states
                                    if st.ts is not None and len(st.ts)),
                                   default=None)
                    tiers = {}
                    for res in d.config.resolutions_ms:
                        em = [st.emitted[res] for st in states
                              if res in st.emitted]
                        tiers[str(res)] = {
                            "emitted_through_ms": max(em) if em else None,
                            "emitted_min_ms": min(em) if em else None,
                            "lag_s": round(
                                (data_hwm - max(em)) / 1000.0, 3)
                            if em and data_hwm is not None else None,
                        }
                    shards.append({
                        "shard": sr.shard_num,
                        "active": sr.active,
                        "queue_depth": len(sr.queue),
                        "ingestion_time_hwm": sr.it_hwm,
                        "buffered_series": len(states),
                        "buffered_samples": sum(st.buffered
                                                for st in states),
                        "data_hwm_ms": data_hwm,
                        "overflow_lost": sr.lost,
                        "tiers": tiers,
                    })
                rolled = {str(r): v for r, v in d.rolled_cache.items()}
                # atomic snapshots: the tick thread inserts/pops keys
                # concurrently and iterating the live dicts could raise
                # mid-request
                errors = dict(d.tier_errors)
                written = dict(d.samples_written)
            out.append({
                "dataset": ds,
                "resolutions_ms": list(d.config.resolutions_ms),
                "tick_interval_s": d.config.tick_interval_s,
                "passes": d.passes,
                "deferred": d.deferred,
                "last_pass_s": round(d.last_pass_s, 6),
                "samples_written": {str(r): n for r, n
                                    in written.items()},
                "tier_errors": {str(r): e for r, e
                                in errors.items()},
                "rolled_through_ms": rolled,
                "shards": shards,
            })
        return {"priority_class": ROLLUP_PRIORITY, "tenant": ROLLUP_TENANT,
                "datasets": out}
