"""Persistence-plane APIs: ChunkSink/ColumnStore + MetaStore
(reference: core/src/main/scala/filodb.core/store/)."""

from filodb_tpu.store.columnstore import (ColumnStore, InMemoryColumnStore,
                                          NullColumnStore, PartKeyRecord)
from filodb_tpu.store.metastore import InMemoryMetaStore, MetaStore

__all__ = ["ColumnStore", "NullColumnStore", "InMemoryColumnStore",
           "PartKeyRecord", "MetaStore", "InMemoryMetaStore"]
