"""ChunkSink / ColumnStore: where flushed chunks and part keys persist.

Capability match for the reference's ChunkSink/ColumnStore API plus its
NullColumnStore test double (reference: core/src/main/scala/filodb.core/
store/ChunkSink.scala:21,116, ColumnStore.scala:59) and the Cassandra table
model it persists into — chunks by (partkey, chunk_id), an ingestion-time
index for batch jobs, and partkeys with start/end times per shard
(reference: cassandra/.../TimeSeriesChunksTable.scala:22,
IngestionTimeIndexTable.scala:22, PartitionKeysTable.scala:15).  Concrete
backends: in-memory (tests), local disk (persistence.py).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, Optional, Sequence

from filodb_tpu.core.chunk import ChunkSet


class ScanBytesExceeded(Exception):
    """A capped raw-row read crossed its byte budget (the ODP bulk
    page-in streams the cap INSIDE the chunk read instead of paying a
    separate metadata pre-pass; the caller decides whether the precise
    range-overlap accounting still permits the query)."""


@dataclasses.dataclass
class PartKeyRecord:
    partkey: bytes
    start_time: int
    end_time: int
    shard: int
    schema_hash: int = 0  # 16-bit schema id so readers recover exact schemas


class ColumnStore:
    """Sink + source of persisted chunks.  All times are epoch millis."""

    def initialize(self, dataset: str, num_shards: int) -> None:
        pass

    # -- sink (flush path) --------------------------------------------------

    def write_chunks(self, dataset: str, shard: int,
                     chunksets: Sequence[ChunkSet],
                     ingestion_time: int = 0) -> int:
        raise NotImplementedError

    def write_part_keys(self, dataset: str, shard: int,
                        records: Sequence[PartKeyRecord]) -> int:
        raise NotImplementedError

    def merge_part_keys(self, dataset: str, shard: int,
                        records: Sequence["PartKeyRecord"]) -> int:
        """Upsert partkeys WIDENING the stored lifetime (min start, max
        end) instead of replacing it — the batch downsampler writes one
        ingestion window at a time, and a later window must not narrow a
        partkey's visible range (write_part_keys replaces, which is
        right for the memstore flush path that recomputes full
        lifetimes).  Default: read-modify-write via scan_part_keys."""
        existing = {r.partkey: r for r in self.scan_part_keys(dataset,
                                                              shard)}
        merged = []
        for r in records:
            old = existing.get(r.partkey)
            if old is not None:
                r = PartKeyRecord(r.partkey,
                                  min(old.start_time, r.start_time),
                                  max(old.end_time, r.end_time),
                                  r.shard, r.schema_hash)
            merged.append(r)
        return self.write_part_keys(dataset, shard, merged)

    def deferred_commits(self):
        """Context manager batching the durability point of the write
        calls inside it into ONE commit at exit (the batch downsampler's
        many small per-resolution writes).  Default: no-op — stores
        whose writes are already atomic per call need nothing."""
        import contextlib
        return contextlib.nullcontext()

    # -- source (ODP / recovery path) ---------------------------------------

    def read_raw_partitions(self, dataset: str, shard: int,
                            partkeys: Sequence[bytes],
                            start_time: int, end_time: int
                            ) -> Iterator[tuple[bytes, list[ChunkSet]]]:
        raise NotImplementedError

    def read_raw_rows(self, dataset: str, shard: int,
                      partkeys: Sequence[bytes], start_time: int,
                      end_time: int,
                      byte_cap: int | None = None,
                      defer_verify: bool = False) -> Optional[list[tuple]]:
        """Raw FRAMED chunk rows for the ODP bulk page-in (see
        persistence.DiskColumnStore.read_raw_rows for the row layout,
        cap and integrity contracts; rows may carry a trailing stored
        crc that callers index positionally or ignore).  None =
        unsupported; callers fall back to the per-partition
        :meth:`read_raw_partitions` path."""
        return None

    def scan_part_keys(self, dataset: str, shard: int) -> Iterator[PartKeyRecord]:
        raise NotImplementedError

    def scan_bytes(self, dataset: str, shard: int, partkeys: Sequence[bytes],
                   start_time: int, end_time: int) -> int:
        """Encoded bytes of chunks overlapping [start_time, end_time] for the
        given partkeys, WITHOUT reading the vectors — lets the ODP path
        enforce max-data-per-shard-query before paying the page-in cost
        (reference: capDataScannedPerShardCheck runs before paging)."""
        total = 0
        for _pk, chunks in self.read_raw_partitions(dataset, shard, partkeys,
                                                    start_time, end_time):
            total += sum(cs.nbytes for cs in chunks)
        return total

    def chunksets_by_ingestion_time(self, dataset: str, shard: int,
                                    start: int, end: int) -> Iterator[ChunkSet]:
        """Scan-by-ingestion-time for the batch downsampler (reference:
        getChunksByIngestionTimeRange)."""
        for _itime, cs in self.chunksets_with_ingestion_time(dataset, shard,
                                                             start, end):
            yield cs

    def chunksets_with_ingestion_time(self, dataset: str, shard: int,
                                      start: int, end: int
                                      ) -> Iterator[tuple[int, ChunkSet]]:
        """Like chunksets_by_ingestion_time but yields (ingestion_time,
        chunkset) so copies preserve the timeline (ChunkCopier)."""
        raise NotImplementedError

    def delete_part_keys(self, dataset: str, shard: int,
                         partkeys: Sequence[bytes]) -> int:
        """Delete partkeys and their chunks (reference:
        PerShardCardinalityBuster)."""
        raise NotImplementedError

    def clone_shard(self, dataset: str, src_shard: int, dst_shard: int,
                    keep_pk) -> int:
        """Copy ``src_shard``'s persisted chunks + partkeys whose
        partkey passes ``keep_pk`` into ``dst_shard`` (ISSUE 13 split
        catch-up backfill: the child inherits the parent's persisted
        history for its half of the hash space).  IDEMPOTENT — keys are
        upserts on (dataset, shard, partkey[, chunk_id]), so a crashed
        clone simply reruns.  Returns chunk rows copied."""
        recs = [r for r in self.scan_part_keys(dataset, src_shard)
                if keep_pk(r.partkey)]
        if recs:
            self.write_part_keys(dataset, dst_shard, [
                PartKeyRecord(r.partkey, r.start_time, r.end_time,
                              dst_shard, r.schema_hash) for r in recs])
        n = 0
        batch: dict[int, list] = {}
        for itime, cs in self.chunksets_with_ingestion_time(
                dataset, src_shard, 0, (1 << 62)):
            if not keep_pk(cs.partkey):
                continue
            batch.setdefault(itime, []).append(cs)
            n += 1
        for itime, css in batch.items():
            self.write_chunks(dataset, dst_shard, css, itime)
        return n

    def delete_shard(self, dataset: str, shard: int) -> int:
        """Drop EVERY persisted row of one shard (split abort discards
        the children's cloned/backfilled data wholesale)."""
        pks = [r.partkey for r in self.scan_part_keys(dataset, shard)]
        seen = set(pks)
        # chunks can exist for partkeys never flushed into the partkeys
        # table (evicted before their first dirty-key flush) — sweep the
        # chunk side too so an aborted child leaves nothing behind
        for _itime, cs in self.chunksets_with_ingestion_time(
                dataset, shard, 0, (1 << 62)):
            if cs.partkey not in seen:
                seen.add(cs.partkey)
                pks.append(cs.partkey)
        if pks:
            self.delete_part_keys(dataset, shard, pks)
        return len(pks)

    def shutdown(self) -> None:
        pass


class NullColumnStore(ColumnStore):
    """Swallows writes; serves nothing (reference: NullColumnStore,
    ChunkSink.scala:116).  Used by in-memory-only deployments and tests."""

    def __init__(self) -> None:
        self.chunksets_written = 0
        self.partkeys_written = 0

    def write_chunks(self, dataset, shard, chunksets, ingestion_time=0) -> int:
        n = len(chunksets)
        self.chunksets_written += n
        return n

    def write_part_keys(self, dataset, shard, records) -> int:
        self.partkeys_written += len(records)
        return len(records)

    def read_raw_partitions(self, dataset, shard, partkeys, start_time, end_time):
        return iter(())

    def scan_part_keys(self, dataset, shard):
        return iter(())

    def chunksets_with_ingestion_time(self, dataset, shard, start, end):
        return iter(())

    def delete_part_keys(self, dataset, shard, partkeys) -> int:
        return 0


class InMemoryColumnStore(ColumnStore):
    """Everything in host dicts; the test/recovery double with real reads."""

    def __init__(self) -> None:
        # (dataset, shard) -> partkey -> list[(ingestion_time, ChunkSet)]
        self._chunks: dict[tuple, dict[bytes, list]] = {}
        # (dataset, shard) -> partkey -> PartKeyRecord
        self._partkeys: dict[tuple, dict[bytes, PartKeyRecord]] = {}

    def write_chunks(self, dataset, shard, chunksets, ingestion_time=0) -> int:
        store = self._chunks.setdefault((dataset, shard), {})
        for cs in chunksets:
            store.setdefault(cs.partkey, []).append((ingestion_time, cs))
        return len(chunksets)

    def write_part_keys(self, dataset, shard, records) -> int:
        store = self._partkeys.setdefault((dataset, shard), {})
        for r in records:
            store[r.partkey] = r
        return len(records)

    def read_raw_partitions(self, dataset, shard, partkeys, start_time, end_time):
        store = self._chunks.get((dataset, shard), {})
        for pk in partkeys:
            rows = [cs for _, cs in store.get(pk, ())
                    if cs.info.end_time >= start_time
                    and cs.info.start_time <= end_time]
            if rows:
                yield pk, sorted(rows, key=lambda c: c.info.chunk_id)

    def scan_part_keys(self, dataset, shard):
        yield from self._partkeys.get((dataset, shard), {}).values()

    def chunksets_with_ingestion_time(self, dataset, shard, start, end):
        for rows in self._chunks.get((dataset, shard), {}).values():
            for itime, cs in rows:
                if start <= itime <= end:
                    yield itime, cs

    def delete_part_keys(self, dataset, shard, partkeys) -> int:
        pk_store = self._partkeys.get((dataset, shard), {})
        ch_store = self._chunks.get((dataset, shard), {})
        n = 0
        for pk in partkeys:
            if pk_store.pop(pk, None) is not None:
                n += 1
            ch_store.pop(pk, None)
        return n
