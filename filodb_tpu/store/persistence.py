"""Durable local-disk persistence: sqlite-backed ColumnStore + MetaStore.

Plays the role of the reference's Cassandra layer with the same table
model (reference: cassandra/src/main/scala/filodb.cassandra/columnstore/
TimeSeriesChunksTable.scala:22 — chunks by (partkey, chunkId),
IngestionTimeIndexTable.scala:22 — scan-by-ingestion-time for batch jobs,
PartitionKeysTable.scala:15 — partkeys with start/end per shard,
metastore/CheckpointTable.scala:17 — checkpoints per (dataset, shard,
group)).  sqlite3 is the embedded stand-in for CQL: one database file per
store, WAL mode so concurrent readers never block the single writer —
mirroring FiloDB's single-writer-per-shard discipline
(SURVEY.md §2.7 item 4).

Chunk vectors are stored as one blob per chunkset: u16 vector count, then
(u32 length, bytes) per encoded vector.  The encoded vectors themselves
are the wire-compatible codec outputs (filodb_tpu/codecs), so a chunk
read back from disk decodes through the exact same native fast paths.

Integrity: every chunk row carries the CRC32C of its framed blob
(``crc`` column), computed at write (flush/downsample) time and
re-verified on every read-back (ODP page-in, backfill, batch
downsampler).  A mismatching row is quarantined
(filodb_tpu/integrity/) and DROPPED from the result — readers serve
partial data with a warning, never bytes that fail their checksum.
Rows with ``crc=0`` predate checksums and skip verification.
"""

from __future__ import annotations

import os
import sqlite3
import struct
import threading
from typing import Iterator, Sequence

from filodb_tpu import integrity
from filodb_tpu.core.chunk import ChunkSet, ChunkSetInfo
from filodb_tpu.integrity import CorruptVectorError
from filodb_tpu.store.columnstore import ColumnStore, PartKeyRecord
from filodb_tpu.store.metastore import MetaStore

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")


def pack_vectors(vectors: Sequence[bytes]) -> bytes:
    out = bytearray(_U16.pack(len(vectors)))
    for v in vectors:
        out += _U32.pack(len(v))
        out += v
    return bytes(out)


def unpack_vectors(blob: bytes) -> list:
    """Zero-copy split: memoryview slices over the row blob (the batch
    downsampler unpacks thousands of rows per run; byte-slice copies of
    every vector were a measurable share of its budget).  All decode
    paths accept any buffer object."""
    (n,) = _U16.unpack_from(blob, 0)
    pos = _U16.size
    mv = memoryview(blob)
    vectors = []
    for _ in range(n):
        (ln,) = _U32.unpack_from(blob, pos)
        pos += _U32.size
        vectors.append(mv[pos:pos + ln])
        pos += ln
    return vectors


class _EagerCursor:
    """Pre-fetched cursor: rows are materialized while the connection lock
    is held, so no live sqlite cursor ever escapes the serialized section."""

    def __init__(self, rows: list, lastrowid, rowcount: int):
        self._rows = rows
        self._pos = 0
        self.lastrowid = lastrowid
        self.rowcount = rowcount

    def fetchall(self) -> list:
        rows = self._rows[self._pos:]
        self._rows, self._pos = [], 0
        return rows

    def fetchone(self):
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: int = 1) -> list:
        rows = self._rows[self._pos:self._pos + size]
        self._pos += len(rows)
        return rows

    def __iter__(self):
        while self._pos < len(self._rows):
            row = self._rows[self._pos]
            self._pos += 1
            yield row


class _SerializedConn:
    """One sqlite connection shared by every thread, one operation at a
    time.  Used for ':memory:' stores, where per-thread connections would
    each get their own private empty database."""

    def __init__(self, conn: sqlite3.Connection):
        self._conn = conn
        self._lock = threading.RLock()

    def execute(self, sql: str, params: Sequence = ()) -> _EagerCursor:
        with self._lock:
            cur = self._conn.execute(sql, params)
            rows = cur.fetchall() if cur.description else []
            return _EagerCursor(rows, cur.lastrowid, cur.rowcount)

    def executemany(self, sql: str, seq) -> _EagerCursor:
        with self._lock:
            cur = self._conn.executemany(sql, list(seq))
            return _EagerCursor([], cur.lastrowid, cur.rowcount)

    def executescript(self, script: str) -> None:
        with self._lock:
            self._conn.executescript(script)

    def commit(self) -> None:
        with self._lock:
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class _SqliteBase:
    """Shared connection handling: one connection per thread, WAL mode."""

    def __init__(self, path: str):
        self.path = path
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._local = threading.local()
        self._ddl_done = False  # guarded-by: _ddl_lock
        self._ddl_lock = threading.Lock()
        self._in_batch_size = None  # resolved from the sqlite var limit

    def _conn(self):
        if self.path == ":memory:":
            # plain :memory: is a fresh empty database PER CONNECTION, so a
            # second thread would see "no such table".  Every thread shares
            # ONE connection instead, serialized op-by-op (shared-cache URIs
            # were rejected: their table locks raise SQLITE_LOCKED, which
            # the busy timeout does not retry).
            with self._ddl_lock:
                conn = getattr(self, "_mem_conn", None)
                if conn is None:
                    conn = _SerializedConn(sqlite3.connect(
                        ":memory:", check_same_thread=False))
                    self._mem_conn = conn
                if not self._ddl_done:
                    self._ddl(conn)
                    self._ddl_done = True
            return conn
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=30.0)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            # blob reads via mmap skip one kernel->user copy (the ODP
            # bulk page-in pulls megabytes of chunk blobs per query)
            conn.execute("PRAGMA mmap_size=1073741824")
            self._local.conn = conn
        if not self._ddl_done:  # filolint: disable=lock-discipline — double-checked locking: the racy read only skips the lock on the hot path; the write side re-checks under _ddl_lock
            with self._ddl_lock:
                if not self._ddl_done:
                    self._ddl(conn)
                    self._ddl_done = True
        return conn

    def _ddl(self, conn: sqlite3.Connection) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        # teardown under _ddl_lock: an unlocked reset here could
        # interleave with a concurrent _conn()'s locked create path and
        # leave a fresh connection marked DDL-less (the lock-discipline
        # lint now holds this to the same rule as _conn)
        with self._ddl_lock:
            mem = getattr(self, "_mem_conn", None)
            if mem is not None:
                mem.close()
                self._mem_conn = None
                self._ddl_done = False  # a later use gets a fresh empty db
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


class DiskColumnStore(_SqliteBase, ColumnStore):
    """ColumnStore over a local sqlite database file."""

    def _ddl(self, conn) -> None:
        conn.executescript("""
        CREATE TABLE IF NOT EXISTS chunks (
            dataset TEXT NOT NULL, shard INTEGER NOT NULL,
            partkey BLOB NOT NULL, chunk_id INTEGER NOT NULL,
            num_rows INTEGER NOT NULL,
            start_time INTEGER NOT NULL, end_time INTEGER NOT NULL,
            ingestion_time INTEGER NOT NULL DEFAULT 0,
            schema_hash INTEGER NOT NULL DEFAULT 0,
            vectors BLOB NOT NULL,
            crc INTEGER NOT NULL DEFAULT 0,
            PRIMARY KEY (dataset, shard, partkey, chunk_id)
        ) WITHOUT ROWID;
        CREATE INDEX IF NOT EXISTS chunks_by_itime
            ON chunks (dataset, shard, ingestion_time);
        CREATE TABLE IF NOT EXISTS partkeys (
            dataset TEXT NOT NULL, shard INTEGER NOT NULL,
            partkey BLOB NOT NULL,
            start_time INTEGER NOT NULL, end_time INTEGER NOT NULL,
            schema_hash INTEGER NOT NULL DEFAULT 0,
            PRIMARY KEY (dataset, shard, partkey)
        ) WITHOUT ROWID;
        """)
        try:  # migrate pre-checksum databases in place (crc=0 skips verify)
            conn.execute(
                "ALTER TABLE chunks ADD COLUMN crc INTEGER NOT NULL DEFAULT 0")
        except sqlite3.OperationalError:
            pass  # column already exists (fresh DDL above, or migrated)
        conn.commit()

    # ------------------------------------------------------------------ sink

    def write_chunks(self, dataset, shard, chunksets, ingestion_time=0) -> int:
        conn = self._conn()
        rows = []
        for cs in chunksets:
            # checksum at encode/flush time: the blob is in cache right
            # after packing, so the CRC pass is effectively free here
            # compared to recomputing it at read time forever after
            blob = pack_vectors(cs.vectors)
            rows.append((dataset, shard, cs.partkey, cs.info.chunk_id,
                         cs.info.num_rows, cs.info.start_time,
                         cs.info.end_time, ingestion_time, cs.schema_hash,
                         blob, integrity.chunk_crc(blob)))
        conn.executemany(
            "INSERT OR REPLACE INTO chunks VALUES (?,?,?,?,?,?,?,?,?,?,?)",
            rows)
        self._commit(conn)
        return len(chunksets)

    def write_part_keys(self, dataset, shard, records) -> int:
        conn = self._conn()
        conn.executemany(
            "INSERT OR REPLACE INTO partkeys VALUES (?,?,?,?,?,?)",
            [(dataset, shard, r.partkey, r.start_time, r.end_time,
              r.schema_hash) for r in records])
        self._commit(conn)
        return len(records)

    def merge_part_keys(self, dataset, shard, records) -> int:
        conn = self._conn()
        conn.executemany(
            "INSERT INTO partkeys VALUES (?,?,?,?,?,?) "
            "ON CONFLICT(dataset, shard, partkey) DO UPDATE SET "
            "start_time=MIN(start_time, excluded.start_time), "
            "end_time=MAX(end_time, excluded.end_time), "
            "schema_hash=excluded.schema_hash",
            [(dataset, shard, r.partkey, r.start_time, r.end_time,
              r.schema_hash) for r in records])
        self._commit(conn)
        return len(records)

    def _commit(self, conn) -> None:
        if not getattr(self._local, "defer_commits", False):
            conn.commit()

    def deferred_commits(self):
        """One durability point for a batch of write calls (thread-local:
        the flag never leaks to other threads' connections)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._local.defer_commits = True
            try:
                yield
            except BaseException:
                # the batch failed mid-way: roll the partial writes
                # back — ONE durability point means all-or-nothing
                self._local.defer_commits = False
                self._conn().rollback()
                raise
            else:
                self._local.defer_commits = False
                self._conn().commit()
        return ctx()

    # ---------------------------------------------------------------- source

    def _in_batch(self, conn) -> int:
        """Largest usable IN-list size (sqlite's host-variable limit
        minus the fixed params).  One statement per ~32k keys instead of
        one per 500 — the ODP bulk page-in reads thousands of partkeys
        per query and per-statement overhead was measurable."""
        got = self._in_batch_size
        if got is None:
            try:
                inner = conn._conn if isinstance(conn, _SerializedConn) \
                    else conn
                got = max(inner.getlimit(
                    sqlite3.SQLITE_LIMIT_VARIABLE_NUMBER) - 8, 500)
            except Exception:
                got = 500
            self._in_batch_size = got
        return got

    def _verify_rows(self, dataset, shard, rows: list) -> list[tuple]:
        """Checksum-verify 8-tuple rows (…, vectors BLOB, stored crc)
        from sqlite; returns the surviving rows UNSLICED (consumers
        read positionally and ignore the trailing crc).  A mismatch
        quarantines the chunk and DROPS the row (the reader serves
        partial data, never unverified bytes); already-quarantined
        chunks are excluded the same way on every re-read.

        Hot path: ONE batched native CRC pass over the joined blobs —
        the per-row formulation cost ~30% of an ODP cold scan, this one
        costs <3% (BASELINE.md)."""
        quarantine = integrity.QUARANTINE
        if quarantine:
            rows = [r for r in rows
                    if not quarantine.is_quarantined(r[0], r[1])]
        if not rows or not integrity.verify_enabled():
            return rows
        import operator

        from filodb_tpu import native
        exps = list(map(operator.itemgetter(7), rows))   # C-speed map
        got = None
        if min(exps):                        # crc=0 legacy rows: slow path
            got = native.crc32c_verify(list(map(operator.itemgetter(6),
                                                rows)), exps)
        if got is None:
            return self._verify_rows_slow(dataset, shard, rows)
        bad, ok = got
        from filodb_tpu.utils.observability import integrity_metrics
        integrity_metrics()["chunks_verified"].inc(len(rows))
        if not bad:
            return rows
        out = []
        for i, r in enumerate(rows):
            if ok[i]:
                out.append(r)
            else:
                integrity.report_corrupt(CorruptVectorError(
                    f"chunk checksum mismatch on read-back "
                    f"(stored={r[7]:#010x})", partkey=r[0], chunk_id=r[1],
                    dataset=dataset, shard=shard, blob=r[6],
                    kind="checksum", start_time=r[3], end_time=r[4]))
        return out

    def _verify_rows_slow(self, dataset, shard, rows: list) -> list[tuple]:
        """Per-row verify: the no-native fallback, and the path for row
        sets containing legacy crc=0 (unverifiable) rows."""
        out: list[tuple] = []
        crc_fn = integrity.chunk_crc
        verified = 0
        for r in rows:
            crc = r[7]
            if crc:
                verified += 1
                if crc_fn(r[6]) != crc:
                    integrity.report_corrupt(CorruptVectorError(
                        f"chunk checksum mismatch on read-back "
                        f"(stored={crc:#010x})", partkey=r[0],
                        chunk_id=r[1], dataset=dataset, shard=shard,
                        blob=r[6], kind="checksum", start_time=r[3],
                        end_time=r[4]))
                    continue
            out.append(r)
        if verified:
            from filodb_tpu.utils.observability import integrity_metrics
            integrity_metrics()["chunks_verified"].inc(verified)
        return out

    def _filter_quarantined(self, rows: list) -> list:
        """Drop quarantined rows only (the deferred-verify path: the
        native bulk decoder checksums the blobs on its own join)."""
        quarantine = integrity.QUARANTINE
        if not quarantine:
            return rows
        return [r for r in rows
                if not quarantine.is_quarantined(r[0], r[1])]

    def read_raw_rows(self, dataset, shard, partkeys, start_time,
                      end_time, byte_cap: int | None = None,
                      defer_verify: bool = False) -> list[tuple]:
        """Raw chunk rows (partkey, chunk_id, num_rows, start_time,
        end_time, schema_hash, framed-vectors blob, stored crc) for a
        partkey set, ordered by (partkey, chunk_id), with NO blob
        unpacking — the ODP bulk page-in hands the framed blobs straight
        to the native page decoder (one C pass for the whole set).
        Every blob is checksum-verified against its stored CRC32C;
        corrupt and quarantined rows are dropped (see
        :meth:`_verify_rows`); consumers index positionally and may
        ignore the trailing crc.

        ``byte_cap``: stream-enforced blob-byte budget; crossing it
        raises :class:`ScanBytesExceeded` (bounded overshoot of one
        fetch batch).  Folding the cap into the read replaces the ODP
        path's separate LENGTH() metadata pre-pass.

        ``partkeys=None`` scans the WHOLE (dataset, shard) in primary
        key order — no per-key binding or b-tree point lookups.  The ODP
        path picks this when paging in most of a shard (the cold-
        dashboard shape); callers skip rows they did not ask for.

        ``defer_verify=True``: skip the checksum pass (quarantined rows
        are still dropped) — ONLY for callers that verify the stored
        crc themselves before trusting a blob, i.e. the ODP bulk
        page-in, whose native decoder checksums every span on the join
        it already builds (native page_decode ``crcs=``)."""
        from filodb_tpu.store.columnstore import ScanBytesExceeded

        check = self._filter_quarantined if defer_verify else \
            (lambda rows: self._verify_rows(dataset, shard, rows))
        conn = self._conn()
        rows: list[tuple] = []
        seen = 0
        if partkeys is None:
            batches = [None]
        else:
            partkeys = list(partkeys)
            lim = self._in_batch(conn)
            batches = [partkeys[i:i + lim]
                       for i in range(0, len(partkeys), lim)]
        for batch in batches:
            if batch is None:
                cur = conn.execute(
                    "SELECT partkey, chunk_id, num_rows, start_time, "
                    "end_time, schema_hash, vectors, crc FROM chunks "
                    "WHERE dataset=? AND shard=? "
                    "AND end_time>=? AND start_time<=? "
                    "ORDER BY partkey, chunk_id",
                    (dataset, shard, start_time, end_time))
            else:
                ph = ",".join("?" * len(batch))
                cur = conn.execute(
                    "SELECT partkey, chunk_id, num_rows, start_time, "
                    "end_time, schema_hash, vectors, crc FROM chunks "
                    f"WHERE dataset=? AND shard=? AND partkey IN ({ph}) "
                    "AND end_time>=? AND start_time<=? "
                    "ORDER BY partkey, chunk_id",
                    (dataset, shard, *batch, start_time, end_time))
            if byte_cap is None:
                rows.extend(check(cur.fetchall()))
                continue
            while True:
                got = cur.fetchmany(512)
                if not got:
                    break
                seen += sum(len(r[6]) for r in got)
                if seen > byte_cap:
                    raise ScanBytesExceeded(
                        f"raw-row read exceeded {byte_cap} bytes")
                rows.extend(check(got))
        return rows

    def read_raw_partitions(self, dataset, shard, partkeys, start_time,
                            end_time) -> Iterator[tuple[bytes, list[ChunkSet]]]:
        """Yields (partkey, chunk-ordered chunksets) in the CALLER's key
        order.  Reads are batched with chunked IN lists — the ODP cold
        path pages thousands of partitions per query, and one sqlite
        round-trip per partkey dominated its page-in time."""
        conn = self._conn()
        partkeys = list(partkeys)
        by_pk: dict[bytes, list] = {}
        lim = self._in_batch(conn)
        for i in range(0, len(partkeys), lim):
            batch = partkeys[i:i + lim]
            ph = ",".join("?" * len(batch))
            rows = conn.execute(
                "SELECT partkey, chunk_id, num_rows, start_time, "
                "end_time, schema_hash, vectors, crc FROM chunks "
                f"WHERE dataset=? AND shard=? AND partkey IN ({ph}) "
                "AND end_time>=? AND start_time<=? "
                "ORDER BY partkey, chunk_id",
                (dataset, shard, *batch, start_time, end_time)).fetchall()
            for pk, cid, nr, st, et, sh, blob, _crc in \
                    self._verify_rows(dataset, shard, rows):
                try:
                    vectors = unpack_vectors(blob)
                except Exception as e:  # noqa: BLE001 — corrupt framing
                    # a checksum-evading corruption (e.g. bit rot after
                    # the CRC was recomputed) must quarantine, not crash
                    # the whole page-in
                    integrity.report_corrupt(CorruptVectorError(
                        f"bad chunk framing: {e}", partkey=pk,
                        chunk_id=cid, dataset=dataset, shard=shard,
                        blob=blob, kind="decode", start_time=st,
                        end_time=et))
                    continue
                by_pk.setdefault(pk, []).append(
                    ChunkSet(ChunkSetInfo(cid, nr, st, et), pk,
                             vectors, schema_hash=sh))
        for pk in partkeys:
            css = by_pk.get(pk)
            if css:
                yield pk, css

    def scan_part_keys(self, dataset, shard) -> Iterator[PartKeyRecord]:
        conn = self._conn()
        for pk, st, et, sh in conn.execute(
                "SELECT partkey, start_time, end_time, schema_hash "
                "FROM partkeys WHERE dataset=? AND shard=?", (dataset, shard)):
            yield PartKeyRecord(pk, st, et, shard, schema_hash=sh)

    def chunksets_with_ingestion_time(self, dataset, shard, start, end
                                      ) -> Iterator[tuple[int, ChunkSet]]:
        conn = self._conn()
        # columns arranged so blob/crc sit at the indexes _verify_rows
        # reads (6/7); itime rides behind at 8 — rows verify in
        # fetchmany-sized batches through the same batched native CRC
        # pass as every other read path, streaming the batch job
        cur = conn.execute(
            "SELECT partkey, chunk_id, num_rows, start_time, end_time, "
            "schema_hash, vectors, crc, ingestion_time FROM chunks "
            "WHERE dataset=? AND shard=? "
            "AND ingestion_time BETWEEN ? AND ? ORDER BY partkey, chunk_id",
            (dataset, shard, start, end))
        while True:
            got = cur.fetchmany(512)
            if not got:
                return
            for pk, cid, nr, st, et, sh, blob, _crc, itime in \
                    self._verify_rows(dataset, shard, got):
                yield itime, ChunkSet(ChunkSetInfo(cid, nr, st, et), pk,
                                      unpack_vectors(blob), schema_hash=sh)

    def scan_bytes(self, dataset, shard, partkeys, start_time, end_time) -> int:
        """Metadata-only byte estimate: no vector blobs leave sqlite.
        LENGTH(vectors) is O(1) on a blob column; keys are batched with
        chunked IN lists (the ODP cap check costs one pass, not one
        round-trip per partition)."""
        conn = self._conn()
        partkeys = list(partkeys)
        total = 0
        lim = self._in_batch(conn)
        for i in range(0, len(partkeys), lim):
            batch = partkeys[i:i + lim]
            ph = ",".join("?" * len(batch))
            row = conn.execute(
                "SELECT COALESCE(SUM(LENGTH(vectors)),0) FROM chunks "
                f"WHERE dataset=? AND shard=? AND partkey IN ({ph}) "
                "AND end_time>=? AND start_time<=?",
                (dataset, shard, *batch, start_time, end_time)).fetchone()
            total += row[0]
        return total

    # ----------------------------------------------------------------- admin

    def num_chunks(self, dataset: str, shard: int) -> int:
        return self._conn().execute(
            "SELECT COUNT(*) FROM chunks WHERE dataset=? AND shard=?",
            (dataset, shard)).fetchone()[0]

    def list_shards(self, dataset: str) -> list[int]:
        """Shards holding chunks for a dataset (offline verify scan)."""
        return [int(r[0]) for r in self._conn().execute(
            "SELECT DISTINCT shard FROM chunks WHERE dataset=? "
            "ORDER BY shard", (dataset,))]

    def scan_chunk_rows(self, dataset: str, shard: int
                        ) -> Iterator[tuple[bytes, int, bytes, int]]:
        """Every persisted (partkey, chunk_id, framed blob, stored crc)
        of one shard, UNVERIFIED — the raw feed for the offline
        ``verify-chunks`` scanner (integrity/scan.py), which must see
        corrupt rows rather than have them dropped."""
        for pk, cid, blob, crc in self._conn().execute(
                "SELECT partkey, chunk_id, vectors, crc FROM chunks "
                "WHERE dataset=? AND shard=? ORDER BY partkey, chunk_id",
                (dataset, shard)):
            yield pk, int(cid), blob, int(crc)

    def delete_part_keys(self, dataset: str, shard: int,
                         partkeys: Sequence[bytes]) -> int:
        """Cardinality-buster path (reference: PerShardCardinalityBuster)."""
        conn = self._conn()
        cur = conn.executemany(
            "DELETE FROM partkeys WHERE dataset=? AND shard=? AND partkey=?",
            [(dataset, shard, pk) for pk in partkeys])
        conn.executemany(
            "DELETE FROM chunks WHERE dataset=? AND shard=? AND partkey=?",
            [(dataset, shard, pk) for pk in partkeys])
        conn.commit()
        return cur.rowcount

    # ------------------------------------------------------- cold-tier age-out

    def count_chunks_aged(self, dataset: str, shard: int,
                          end_before: int) -> tuple[int, int]:
        """(rows, blob bytes) wholly older than ``end_before`` — the
        age-out dry-run plan, metadata-only."""
        row = self._conn().execute(
            "SELECT COUNT(*), COALESCE(SUM(LENGTH(vectors)),0) "
            "FROM chunks WHERE dataset=? AND shard=? AND end_time<?",
            (dataset, shard, end_before)).fetchone()
        return int(row[0]), int(row[1])

    def scan_chunk_rows_aged(self, dataset: str, shard: int,
                             end_before: int) -> Iterator[tuple]:
        """Full VERIFIED rows (partkey, chunk_id, num_rows, start_time,
        end_time, schema_hash, blob, crc, ingestion_time) whose
        end_time < ``end_before`` — the age-out migration feed.  Rows
        failing their checksum are quarantined and SKIPPED: corruption
        stays local and loud instead of being archived as truth."""
        cur = self._conn().execute(
            "SELECT partkey, chunk_id, num_rows, start_time, end_time, "
            "schema_hash, vectors, crc, ingestion_time FROM chunks "
            "WHERE dataset=? AND shard=? AND end_time<? "
            "ORDER BY partkey, chunk_id", (dataset, shard, end_before))
        while True:
            got = cur.fetchmany(256)
            if not got:
                return
            yield from self._verify_rows(dataset, shard, got)

    def delete_chunk_rows(self, dataset: str, shard: int,
                          ids: Sequence[tuple[bytes, int]]) -> int:
        """Delete specific (partkey, chunk_id) rows — the local half of
        a verified tier migration.  Part keys are untouched: the series
        still exists; its old chunks just live in the cold tier now."""
        conn = self._conn()
        cur = conn.executemany(
            "DELETE FROM chunks WHERE dataset=? AND shard=? "
            "AND partkey=? AND chunk_id=?",
            [(dataset, shard, pk, cid) for pk, cid in ids])
        conn.commit()
        return cur.rowcount


class DiskMetaStore(_SqliteBase, MetaStore):
    """MetaStore (checkpoints + dataset metadata) over sqlite."""

    def _ddl(self, conn) -> None:
        conn.executescript("""
        CREATE TABLE IF NOT EXISTS checkpoints (
            dataset TEXT NOT NULL, shard INTEGER NOT NULL,
            grp INTEGER NOT NULL, offset INTEGER NOT NULL,
            PRIMARY KEY (dataset, shard, grp)
        ) WITHOUT ROWID;
        CREATE TABLE IF NOT EXISTS datasets (
            name TEXT PRIMARY KEY, config TEXT NOT NULL
        );
        CREATE TABLE IF NOT EXISTS kv (
            key TEXT PRIMARY KEY, value TEXT NOT NULL
        );
        """)
        conn.commit()

    def write_checkpoint(self, dataset, shard, group, offset) -> None:
        conn = self._conn()
        conn.execute("INSERT OR REPLACE INTO checkpoints VALUES (?,?,?,?)",
                     (dataset, shard, group, offset))
        conn.commit()

    def read_checkpoints(self, dataset, shard) -> dict[int, int]:
        return dict(self._conn().execute(
            "SELECT grp, offset FROM checkpoints WHERE dataset=? AND shard=?",
            (dataset, shard)))

    def delete_checkpoints(self, dataset, shard) -> None:
        conn = self._conn()
        conn.execute("DELETE FROM checkpoints WHERE dataset=? AND shard=?",
                     (dataset, shard))
        conn.commit()

    # durable KV (ISSUE 13: split phase records + clone/retire markers)

    def write_kv(self, key: str, value: str) -> None:
        conn = self._conn()
        conn.execute("INSERT OR REPLACE INTO kv VALUES (?,?)", (key, value))
        conn.commit()

    def read_kv(self, key: str) -> str | None:
        row = self._conn().execute(
            "SELECT value FROM kv WHERE key=?", (key,)).fetchone()
        return row[0] if row else None

    def delete_kv(self, key: str) -> None:
        conn = self._conn()
        conn.execute("DELETE FROM kv WHERE key=?", (key,))
        conn.commit()

    def list_kv(self, prefix: str) -> dict[str, str]:
        return dict(self._conn().execute(
            "SELECT key, value FROM kv WHERE key LIKE ? ESCAPE '\\'",
            (prefix.replace("\\", "\\\\").replace("%", "\\%")
             .replace("_", "\\_") + "%",)))

    def write_dataset(self, name: str, config: str) -> None:
        conn = self._conn()
        conn.execute("INSERT OR REPLACE INTO datasets VALUES (?,?)",
                     (name, config))
        conn.commit()

    def read_dataset(self, name: str) -> str | None:
        row = self._conn().execute(
            "SELECT config FROM datasets WHERE name=?", (name,)).fetchone()
        return row[0] if row else None

    def list_datasets(self) -> list[str]:
        return [r[0] for r in self._conn().execute(
            "SELECT name FROM datasets ORDER BY name")]
