"""MetaStore: dataset metadata + ingestion checkpoints.

Capability match for the reference's MetaStore incl. the checkpoint API
written per (dataset, shard, flush-group) only after chunks+partkeys
persist, and read back as min/max for recovery (reference:
core/src/main/scala/filodb.core/store/MetaStore.scala:14,48,67,
InMemoryMetaStore.scala:89, cassandra/.../CheckpointTable.scala:17).
"""

from __future__ import annotations

from typing import Mapping, Optional


class MetaStore:
    def initialize(self) -> None:
        pass

    def write_checkpoint(self, dataset: str, shard: int, group: int,
                         offset: int) -> None:
        raise NotImplementedError

    def read_checkpoints(self, dataset: str, shard: int) -> dict[int, int]:
        raise NotImplementedError

    # -- small durable KV (ISSUE 13): split phase/cursor records + the
    # per-node clone/retire markers that make resharding crash-safe ----

    def write_kv(self, key: str, value: str) -> None:
        raise NotImplementedError

    def read_kv(self, key: str) -> Optional[str]:
        raise NotImplementedError

    def delete_kv(self, key: str) -> None:
        raise NotImplementedError

    def list_kv(self, prefix: str) -> dict[str, str]:
        raise NotImplementedError

    def delete_checkpoints(self, dataset: str, shard: int) -> None:
        """Drop one shard's checkpoint rows (split abort discards the
        children's cloned recovery state)."""
        raise NotImplementedError

    def read_earliest_checkpoint(self, dataset: str, shard: int) -> int:
        cps = self.read_checkpoints(dataset, shard)
        return min(cps.values()) if cps else -1

    def read_highest_checkpoint(self, dataset: str, shard: int) -> int:
        cps = self.read_checkpoints(dataset, shard)
        return max(cps.values()) if cps else -1

    def shutdown(self) -> None:
        pass


class InMemoryMetaStore(MetaStore):
    def __init__(self) -> None:
        self._checkpoints: dict[tuple, dict[int, int]] = {}
        self._kv: dict[str, str] = {}

    def write_checkpoint(self, dataset, shard, group, offset) -> None:
        self._checkpoints.setdefault((dataset, shard), {})[group] = offset

    def read_checkpoints(self, dataset, shard) -> dict[int, int]:
        return dict(self._checkpoints.get((dataset, shard), {}))

    def delete_checkpoints(self, dataset, shard) -> None:
        self._checkpoints.pop((dataset, shard), None)

    def write_kv(self, key: str, value: str) -> None:
        self._kv[key] = value

    def read_kv(self, key: str) -> Optional[str]:
        return self._kv.get(key)

    def delete_kv(self, key: str) -> None:
        self._kv.pop(key, None)

    def list_kv(self, prefix: str) -> dict[str, str]:
        return {k: v for k, v in self._kv.items() if k.startswith(prefix)}
