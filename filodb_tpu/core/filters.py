"""Column filters for tag/label matching.

Equivalent of the reference's ``ColumnFilter`` + ``Filter`` ADT
(reference: core/src/main/scala/filodb.core/query/KeyFilter.scala) used by
the part-key index lookups and by the query planners for shard pruning.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence


class Filter:
    def matches(self, value: str) -> bool:  # pragma: no cover - interface
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Equals(Filter):
    value: str

    def matches(self, value: str) -> bool:
        return value == self.value


@dataclasses.dataclass(frozen=True)
class NotEquals(Filter):
    value: str

    def matches(self, value: str) -> bool:
        return value != self.value


@dataclasses.dataclass(frozen=True)
class In(Filter):
    values: frozenset

    def matches(self, value: str) -> bool:
        return value in self.values


@dataclasses.dataclass(frozen=True)
class NotIn(Filter):
    values: frozenset

    def matches(self, value: str) -> bool:
        return value not in self.values


@dataclasses.dataclass(frozen=True)
class EqualsRegex(Filter):
    pattern: str

    def matches(self, value: str) -> bool:
        return _full_match(self.pattern, value)


@dataclasses.dataclass(frozen=True)
class NotEqualsRegex(Filter):
    pattern: str

    def matches(self, value: str) -> bool:
        return not _full_match(self.pattern, value)


_regex_cache: dict[str, re.Pattern] = {}


def _full_match(pattern: str, value: str) -> bool:
    rx = _regex_cache.get(pattern)
    if rx is None:
        rx = re.compile(pattern)
        if len(_regex_cache) > 4096:
            _regex_cache.clear()
        _regex_cache[pattern] = rx
    return rx.fullmatch(value) is not None


@dataclasses.dataclass(frozen=True)
class ColumnFilter:
    """A (label, filter) pair, e.g. ColumnFilter("job", Equals("api"))."""

    column: str
    filter: Filter

    def matches(self, tags: dict) -> bool:
        return self.filter.matches(tags.get(self.column, ""))


def equals_value(filters: Sequence[ColumnFilter], column: str) -> Optional[str]:
    """The Equals value for ``column`` if one exists (used for shard-key
    extraction during shard pruning, reference SingleClusterPlanner
    shardsFromFilters)."""
    for f in filters:
        if f.column == column and isinstance(f.filter, Equals):
            return f.filter.value
    return None
