"""Chunk metadata and encoded chunk sets.

Equivalent of the reference's ChunkSetInfo + BinaryVector chunk payloads
(reference: core/src/main/scala/filodb.core/store/ChunkSetInfo.scala:59,122).
A ``ChunkSet`` is the frozen, compressed form of one partition's write buffer
(what gets flushed to the column store); ``ChunkBatch`` is the decoded,
padded, device-ready SoA form the query kernels consume — the TPU-native
replacement for per-row VectorDataReader iteration.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from filodb_tpu.codecs import deltadelta, doublecodec, histcodec, strcodec
from filodb_tpu.core.histogram import HistogramBuckets
from filodb_tpu.core.schemas import ColumnType, Schema


def chunk_id(start_time_ms: int, ingestion_seq: int = 0) -> int:
    """Chunk ids are timestamp-based so they sort by time (reference:
    ChunkSetInfo chunkID = timestamp-based, store/ChunkSetInfo.scala)."""
    return (start_time_ms << 12) | (ingestion_seq & 0xFFF)


@dataclasses.dataclass
class ChunkSetInfo:
    chunk_id: int
    num_rows: int
    start_time: int
    end_time: int


@dataclasses.dataclass
class ChunkSet:
    """Compressed columns of one chunk of one partition."""

    info: ChunkSetInfo
    partkey: bytes
    vectors: list[bytes]  # one encoded blob per data column (col 0 = timestamps)
    schema_hash: int = 0  # 16-bit schema id, persisted so readers (ODP,
    #                       batch downsampler) recover the exact schema

    @property
    def nbytes(self) -> int:
        return sum(len(v) for v in self.vectors)


def encode_chunkset(schema: Schema, partkey: bytes, timestamps: np.ndarray,
                    columns: Sequence, ingestion_seq: int = 0) -> ChunkSet:
    """Freeze raw append buffers into the smallest encoding per column —
    the optimize() step of the reference's BinaryAppendableVector
    (reference: memory/format/BinaryVector.scala optimize,
    TimeSeriesPartition.encodeOneChunkset TimeSeriesPartition.scala:203-249).

    ``columns`` are the non-timestamp data columns in schema order; histogram
    columns take ``(HistogramBuckets, int64[rows, buckets])`` tuples.
    """
    return encode_chunksets_batch(
        schema, [(partkey, timestamps, columns, ingestion_seq)])[0]


def encode_chunksets_batch(schema: Schema, items: Sequence[tuple]
                           ) -> list[ChunkSet]:
    """Encode MANY chunksets with two native batch-encode calls total
    (one per numeric family) — the offline downsampler's write side,
    where per-chunkset call overhead dominates small rollup chunks
    (reference: BatchDownsampler.downsampleBatch re-encode loop).

    ``items``: (partkey, timestamps, columns, ingestion_seq) tuples with
    the same column contract as :func:`encode_chunkset`."""
    data_cols = schema.data.columns[1:]
    ll_arrays, dbl_arrays = [], []
    # identical ll arrays (the grid downsampler hands EVERY series the
    # same period-end timestamp object) encode once and share the blob
    ll_index: dict[int, int] = {}

    def ll_slot(arr) -> int:
        i = ll_index.get(id(arr))
        if i is None:
            i = ll_index[id(arr)] = len(ll_arrays)
            ll_arrays.append(arr)
        return i

    plans = []          # per item: list of ("ll"/"dbl"/"done", idx/blob)
    items = [(pk, np.ascontiguousarray(ts, dtype=np.int64), cols, seq)
             for pk, ts, cols, seq in items]
    for partkey, ts, columns, seq in items:
        n = len(ts)
        if len(columns) != len(data_cols):
            raise ValueError(
                f"schema {schema.name} expects {len(data_cols)} data "
                f"columns, got {len(columns)}")
        plan = [("ll", ll_slot(ts))]
        for col, data in zip(data_cols, columns):
            rows = data[1] if col.ctype == ColumnType.HISTOGRAM else data
            if len(rows) != n:
                raise ValueError(f"column {col.name}: {len(rows)} rows "
                                 f"!= {n} timestamps")
            if col.ctype == ColumnType.DOUBLE:
                plan.append(("dbl", len(dbl_arrays)))
                dbl_arrays.append(np.asarray(data, dtype=np.float64))
            elif col.ctype in (ColumnType.LONG, ColumnType.TIMESTAMP,
                               ColumnType.INT):
                plan.append(("ll", ll_slot(np.asarray(data,
                                                      dtype=np.int64))))
            elif col.ctype == ColumnType.HISTOGRAM:
                buckets, hrows = data
                plan.append(("done",
                             histcodec.encode(buckets, np.asarray(hrows))))
            elif col.ctype == ColumnType.STRING:
                plan.append(("done", strcodec.encode_utf8(list(data))))
            else:
                raise ValueError(f"unsupported column type {col.ctype}")
        plans.append(plan)
    ll_blobs = deltadelta.encode_batch(ll_arrays)
    dbl_blobs = doublecodec.encode_batch(dbl_arrays) if dbl_arrays else []
    out = []
    for (partkey, ts, _columns, seq), plan in zip(items, plans):
        vectors = [ll_blobs[p[1]] if p[0] == "ll"
                   else dbl_blobs[p[1]] if p[0] == "dbl" else p[1]
                   for p in plan]
        n = len(ts)
        t0 = int(ts[0]) if n else 0
        info = ChunkSetInfo(chunk_id(t0, seq), n, t0,
                            int(ts[-1]) if n else 0)
        out.append(ChunkSet(info, partkey, vectors,
                            schema_hash=schema.schema_hash))
    return out


def decode_partitions_batch(schema: Schema, groups: Sequence[Sequence[ChunkSet]]
                            ) -> list[tuple[np.ndarray, list]]:
    """Decode partitions of chunk-ordered ChunkSets, returning ONE
    contiguous (ts, cols) per partition.  Blobs are batched COLUMN-major
    into the native decoder, so each partition's chunks land in adjacent
    output spans and the cross-chunk concatenation is a zero-copy view —
    the batch downsampler's read side (reference: BatchDownsampler
    chunkset iteration, spark-jobs BatchDownsampler.scala:36)."""
    from filodb_tpu import native
    nb = native.batch_decoder()
    numeric = (ColumnType.TIMESTAMP, ColumnType.LONG, ColumnType.INT,
               ColumnType.DOUBLE)
    if nb is None or any(c.ctype not in numeric
                         for c in schema.data.columns[1:]):
        out = []
        for css in groups:
            parts = [decode_chunkset(schema, cs) for cs in css]
            ts = np.concatenate([p[0] for p in parts]) if parts \
                else np.empty(0, np.int64)
            cols = []
            for ci in range(len(schema.data.columns) - 1):
                vals = [p[1][ci] for p in parts]
                if vals and isinstance(vals[0], tuple):
                    # widening-aware (16 -> 20 buckets mid-partition):
                    # the widest scheme wins, narrower rows edge-pad
                    from filodb_tpu.core.histogram import \
                        concat_hist_parts
                    cols.append(concat_hist_parts(vals))
                elif vals and isinstance(vals[0], list):
                    cols.append(sum(vals, []))
                else:
                    cols.append(np.concatenate(vals) if vals
                                else np.empty(0))
            out.append((ts, cols))
        return out
    data_cols = schema.data.columns[1:]
    counts = [cs.info.num_rows for css in groups for cs in css]
    spans = np.zeros(len(groups) + 1, np.int64)
    np.cumsum([sum(cs.info.num_rows for cs in css) for css in groups],
              out=spans[1:])

    def column(j: int, dbl: bool):
        blobs = [cs.vectors[j] for css in groups for cs in css]
        flat = (nb.dbl_decode_batch if dbl
                else nb.ll_decode_batch)(blobs, counts)
        base = flat[0].base if flat else None  # one buffer; spans view it
        if base is None:
            return [np.empty(0) for _ in groups]
        whole = base[:spans[-1]]
        return [whole[spans[i]:spans[i + 1]] for i in range(len(groups))]

    ts_views = column(0, dbl=False)
    col_views = [column(j, dbl=(col.ctype == ColumnType.DOUBLE))
                 for j, col in enumerate(data_cols, start=1)]
    return [(ts_views[g], [cv[g] for cv in col_views])
            for g in range(len(groups))]


def decode_column(blob: bytes, ctype: ColumnType):
    if ctype in (ColumnType.TIMESTAMP, ColumnType.LONG, ColumnType.INT):
        return deltadelta.decode(blob)
    if ctype == ColumnType.DOUBLE:
        return doublecodec.decode(blob)
    if ctype == ColumnType.HISTOGRAM:
        return histcodec.decode(blob)
    if ctype == ColumnType.STRING:
        return strcodec.decode_utf8(blob)
    raise ValueError(f"unsupported column type {ctype}")


def decode_chunkset(schema: Schema, cs: ChunkSet) -> tuple[np.ndarray, list]:
    ts = deltadelta.decode(cs.vectors[0])
    cols = [decode_column(blob, col.ctype)
            for col, blob in zip(schema.data.columns[1:], cs.vectors[1:])]
    return ts, cols


# --------------------------------------------------------------------------
# Device-ready batches
# --------------------------------------------------------------------------

TS_PAD = np.iinfo(np.int64).max  # padding timestamp: sorts after everything


@dataclasses.dataclass
class ChunkBatch:
    """Padded dense SoA over a set of series: the unit the kernels consume.

    ``timestamps[s, r]`` is padded with TS_PAD and ``values`` with NaN past
    ``row_counts[s]`` so searchsorted/window kernels need no masks beyond the
    value NaN convention.  ``hist`` columns become [S, R, B] matrices.

    Arrays are READ-ONLY by convention: scan paths may hand out views of
    shared decoded caches (partition read_range output, the fused ODP cold
    batch), so consumers must never mutate a batch in place.
    """

    timestamps: np.ndarray          # [S, R] int64
    values: np.ndarray              # [S, R] float64 (the designated value column)
    row_counts: np.ndarray          # [S] int32
    hist: Optional[np.ndarray] = None       # [S, R, B] float64 when value col is hist
    bucket_tops: Optional[np.ndarray] = None  # [B]
    extra_cols: Optional[dict] = None       # name -> [S, R] for multi-column scans

    @property
    def num_series(self) -> int:
        return self.timestamps.shape[0]

    @property
    def max_rows(self) -> int:
        return self.timestamps.shape[1]


def pad_rows(max_rows: int, pad_to: Optional[int]) -> int:
    """The padded row dimension R for a batch whose longest series has
    ``max_rows`` rows: rounded up to ``pad_to``, then geometric buckets
    above it — row counts that grow with live ingest would otherwise
    mint a fresh XLA compile every pad_to rows; powers of two keep the
    shape set logarithmic (SURVEY.md §7 ragged-data strategy).  Every
    batch-building path MUST use this one rule: shape-keyed memos and
    XLA compile caches assume cold/warm/generic batches of the same
    data agree on R."""
    R = max_rows
    if pad_to:
        if R <= pad_to:
            R = pad_to
        else:
            R = pad_to * (1 << int(np.ceil(np.log2(R / pad_to))))
    return max(R, 1)


def fill_batch_pads(ts2d: np.ndarray, val2d: np.ndarray,
                    cnts: np.ndarray, S: int) -> bool:
    """Write TS_PAD / NaN into every PADDING cell of an [S_pad, R]
    batch whose data cells are written separately — the shared tail of
    the flat-assembly paths (the ODP fused decode-into and bulk scan).
    One copy of the fill/geometry logic keeps every batch-building path
    agreeing on pad semantics (see :func:`pad_rows`).  Returns True
    when the first S row counts are uniform — data may then be placed
    with one reshaped block copy instead of a mask scatter."""
    S_pad, R = ts2d.shape
    counts = cnts[:S]
    r0 = int(counts[0]) if S else 0
    if S and bool((counts == r0).all()):
        ts2d[:, r0:] = TS_PAD
        val2d[:, r0:] = np.nan
        ts2d[S:, :r0] = TS_PAD
        val2d[S:, :r0] = np.nan
        return True
    padmask = np.arange(R)[None, :] >= cnts[:, None]
    ts2d[padmask] = TS_PAD
    val2d[padmask] = np.nan
    return False


def build_batch(series_ts: Sequence[np.ndarray], series_vals: Sequence,
                pad_to: Optional[int] = None, hist: bool = False,
                bucket_tops: Optional[np.ndarray] = None,
                extra_cols: Optional[dict] = None,
                pad_series_to: Optional[int] = None) -> ChunkBatch:
    """Stack ragged per-series arrays into a padded [S, R] batch.

    Padding strategy (SURVEY.md §7 "Ragged data"): R = max rows rounded up to
    ``pad_to`` via :func:`pad_rows` (a small set of bucket sizes keeps XLA
    recompiles bounded); timestamps pad with TS_PAD, values with NaN so
    windowed kernels naturally exclude them.
    """
    S = len(series_ts)
    counts = np.array([len(t) for t in series_ts], dtype=np.int32)
    R = pad_rows(int(counts.max()) if S else 0, pad_to)
    if pad_series_to:
        S_pad = max(S, pad_series_to)
    else:
        S_pad = max(S, 1)
    ts = np.full((S_pad, R), TS_PAD, dtype=np.int64)
    for i, t in enumerate(series_ts):
        ts[i, :len(t)] = t
    if hist:
        B = len(bucket_tops)
        vals = np.full((S_pad, R, B), np.nan, dtype=np.float64)
        for i, v in enumerate(series_vals):
            vals[i, :len(v)] = v
        return ChunkBatch(ts, np.full((S_pad, R), np.nan), counts_pad(counts, S_pad),
                          hist=vals, bucket_tops=np.asarray(bucket_tops, dtype=np.float64),
                          extra_cols=extra_cols)
    vals = np.full((S_pad, R), np.nan, dtype=np.float64)
    for i, v in enumerate(series_vals):
        vals[i, :len(v)] = v
    return ChunkBatch(ts, vals, counts_pad(counts, S_pad), extra_cols=extra_cols)


def counts_pad(counts: np.ndarray, s_pad: int) -> np.ndarray:
    if len(counts) == s_pad:
        return counts
    out = np.zeros(s_pad, dtype=np.int32)
    out[:len(counts)] = counts
    return out
