"""Ingestion record format: the BinaryRecord v2 equivalent.

The reference serializes each sample into an off-heap BinaryRecord inside a
reusable RecordContainer — the unit that flows over Kafka and into shards,
carrying the 16-bit schema hash, the partition-key hash and the shard-key
hash so downstream code never re-parses tags (reference:
core/src/main/scala/filodb.core/binaryrecord2/RecordBuilder.scala:32,
RecordSchema.scala:40, RecordContainer.scala:27, doc/binaryrecord-spec.md).

Here a record is a compact binary struct with the same embedded hashes, and a
``RecordContainer`` is a length-prefixed batch of them.  Hashes use
blake2b-64 (stable across processes/hosts, unlike Python ``hash``); the
shard-key hash covers only the shard-key tags so the shard mapper can
bit-splice it with the partition hash (reference: RecordBuilder.shardKeyHash
/ partitionKeyHash, RecordBuilder.scala:578+).
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
from typing import Iterator, Mapping, Optional, Sequence

import numpy as np

from filodb_tpu.core.schemas import ColumnType, DatasetOptions, Schema


def stable_hash64(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


def stable_hash32(data: bytes) -> int:
    return stable_hash64(data) & 0xFFFFFFFF


def canonical_partkey(tags: Mapping[str, str]) -> bytes:
    """Canonical partition-key bytes: sorted tag pairs.  Serves the role of
    the reference's partKey BinaryRecord (equality + hashing + persistence)."""
    out = bytearray()
    for k in sorted(tags):
        kb, vb = k.encode(), tags[k].encode()
        out += struct.pack("<HH", len(kb), len(vb)) + kb + vb
    return bytes(out)


def parse_partkey(buf: bytes) -> dict[str, str]:
    tags: dict[str, str] = {}
    pos = 0
    while pos < len(buf):
        klen, vlen = struct.unpack_from("<HH", buf, pos)
        pos += 4
        k = buf[pos:pos + klen].decode(); pos += klen
        v = buf[pos:pos + vlen].decode(); pos += vlen
        tags[k] = v
    return tags


def shard_key_hash(tags: Mapping[str, str], options: DatasetOptions) -> int:
    """32-bit hash over shard-key tag values only, with the reference's
    metric-suffix stripping (``_bucket``/``_count``/``_sum`` hash like their
    base metric so they land on the same shards; reference:
    RecordBuilder.trimShardColumn + shardKeyHash)."""
    parts = []
    for col in options.shard_key_columns:
        v = tags.get(col, "")
        for suffix in options.ignore_shard_key_column_suffixes.get(col, ()):
            if v.endswith(suffix):
                v = v[: -len(suffix)]
                break
        parts.append(v)
    return stable_hash32("\x00".join(parts).encode())


def partition_hash(tags: Mapping[str, str], options: Optional[DatasetOptions] = None) -> int:
    """32-bit hash over the full tag set minus ignored tags (reference:
    DatasetOptions.ignoreTagsOnPartitionKeyHash, e.g. ``le``)."""
    ignored = options.ignore_tags_on_partition_key_hash if options else ()
    filtered = {k: v for k, v in tags.items() if k not in ignored}
    return stable_hash32(canonical_partkey(filtered))


@dataclasses.dataclass
class IngestRecord:
    """One decoded sample: schema hash + tags + timestamp + data values.

    ``values`` holds the non-timestamp data columns in schema order; histogram
    columns hold an encoded BinaryHistogram-equivalent blob (bytes).
    """

    schema_hash: int
    tags: dict[str, str]
    timestamp: int
    values: tuple
    shard_hash: int = 0
    part_hash: int = 0

    def partkey(self) -> bytes:
        return canonical_partkey(self.tags)


_REC_DTYPE_CACHE: dict = {}


def record_dtype(schema: Schema, pklen: int) -> "np.dtype":
    """The numpy structured dtype of one wire record for (schema, pklen)
    — cached: dtype construction is a surprising share of small
    per-series batch encodes."""
    key = (schema.schema_hash, pklen)
    dt = _REC_DTYPE_CACHE.get(key)
    if dt is None:
        fields = [("schema", "<u2"), ("shash", "<u4"), ("phash", "<u4"),
                  ("ts", "<i8")]
        for ci, col in enumerate(schema.data.columns[1:]):
            if col.ctype == ColumnType.DOUBLE:
                fields.append((f"c{ci}", "<f8"))
            elif col.ctype == ColumnType.INT:
                fields.append((f"c{ci}", "<i4"))
            else:
                fields.append((f"c{ci}", "<i8"))
        fields.append(("pklen", "<u2"))
        if pklen:
            fields.append(("pk", f"V{pklen}"))
        dt = _REC_DTYPE_CACHE[key] = np.dtype(fields)
    return dt


class RecordBuilder:
    """Builds RecordContainers from samples (reference: RecordBuilder.scala:32).

    Not thread-safe; one builder per producer, like the reference's
    per-thread builders.
    """

    def __init__(self, schema: Schema, options: DatasetOptions | None = None,
                 container_size: int = 1024 * 1024):
        self.schema = schema
        self.options = options or DatasetOptions()
        self.container_size = container_size
        self._containers: list[bytearray] = []
        self._cur: bytearray = bytearray()

    def add(self, timestamp: int, values: Sequence, tags: Mapping[str, str]) -> None:
        # normalize the Prometheus __name__ label to the dataset's metric
        # column (reference: gateway InputRecord conversion writes the
        # metric into DatasetOptions.metricColumn)
        mcol = self.options.metric_column
        if mcol != "__name__" and "__name__" in tags:
            norm = dict(tags)
            norm[mcol] = norm.pop("__name__")
            tags = norm
        shash = shard_key_hash(tags, self.options)
        phash = partition_hash(tags, self.options)
        rec = _encode_record(self.schema, self.options, timestamp, values, tags,
                             shash, phash)
        if len(self._cur) + len(rec) > self.container_size and self._cur:
            self._flush_container()
        self._cur += rec

    def add_series(self, timestamps: Sequence, columns: Sequence[Sequence],
                   tags: Mapping[str, str]) -> int:
        """Vectorized add of one series' samples: hashes and the partkey
        are computed once, and all records are encoded with a numpy
        structured array in one pass.  Producers naturally hold
        per-series batches (reference: RecordBuilder reuse across a
        container, RecordBuilder.scala:32; the gateway's InputRecords
        carry one series each).  Falls back to per-row :meth:`add` for
        histogram/string schemas.  Returns records added."""
        n = len(timestamps)
        if n == 0:
            return 0
        data_cols = self.schema.data.columns[1:]
        if len(columns) != len(data_cols):
            raise ValueError(f"expected {len(data_cols)} columns, "
                             f"got {len(columns)}")
        if any(c.ctype not in (ColumnType.DOUBLE, ColumnType.LONG,
                               ColumnType.TIMESTAMP, ColumnType.INT)
               for c in data_cols):
            for i, t in enumerate(timestamps):
                self.add(int(t), [col[i] for col in columns], tags)
            return n
        mcol = self.options.metric_column
        if mcol != "__name__" and "__name__" in tags:
            norm = dict(tags)
            norm[mcol] = norm.pop("__name__")
            tags = norm
        return self.add_series_hashed(
            timestamps, columns, shard_key_hash(tags, self.options),
            partition_hash(tags, self.options), canonical_partkey(tags))

    def add_series_hashed(self, timestamps: Sequence,
                          columns: Sequence[Sequence], shash: int,
                          phash: int, pk: bytes) -> int:
        """:meth:`add_series` with the per-series hashes/partkey already
        computed — the gateway's columnar ingest memoizes them per
        series across batches, so recomputing them per call would be
        a third of its cost.  Numeric schemas only (the caller already
        normalized tags into ``pk``)."""
        n = len(timestamps)
        if n == 0:
            return 0
        rec = np.zeros(n, dtype=record_dtype(self.schema, len(pk)))
        rec["schema"] = self.schema.schema_hash
        rec["shash"] = shash
        rec["phash"] = phash
        rec["ts"] = np.asarray(timestamps, dtype=np.int64)
        self._fill_value_cols(rec, columns)
        rec["pklen"] = len(pk)
        if pk:
            rec["pk"] = np.frombuffer(pk, dtype=np.uint8).view(f"V{len(pk)}")
        self.append_encoded(rec.tobytes(), rec.dtype.itemsize, n)
        return n

    def _fill_value_cols(self, rec: np.ndarray, columns) -> None:
        for ci, col in enumerate(self.schema.data.columns[1:]):
            arr = np.asarray(columns[ci])
            rec[f"c{ci}"] = arr.astype(np.float64) \
                if col.ctype == ColumnType.DOUBLE else arr.astype(np.int64) \
                if col.ctype != ColumnType.INT else arr.astype(np.int32)

    def append_encoded(self, blob: bytes, rec_size: int, n: int) -> None:
        """Append ``n`` pre-encoded fixed-size wire records (built with
        :func:`record_dtype`) across container boundaries.  This is the
        PUBLIC seam for callers that batch-encode records themselves
        (the gateway's planned ingest) — container framing and size
        policy stay in this class."""
        per = max((self.container_size - len(self._cur)) // rec_size, 0)
        pos = 0
        while pos < n:
            if per == 0:
                if self._cur:
                    self._flush_container()
                per = max(self.container_size // rec_size, 1)
            take = min(per, n - pos)
            self._cur += blob[pos * rec_size:(pos + take) * rec_size]
            pos += take
            per = (self.container_size - len(self._cur)) // rec_size

    def _flush_container(self) -> None:
        self._containers.append(self._cur)
        self._cur = bytearray()

    def containers(self) -> list[bytes]:
        """Drain all full+partial containers as wire bytes."""
        if self._cur:
            self._flush_container()
        out = [struct.pack("<I", len(c)) + bytes(c) for c in self._containers]
        self._containers = []
        return out


def _encode_record(schema: Schema, options: DatasetOptions, timestamp: int,
                   values: Sequence, tags: Mapping[str, str],
                   shash: int, phash: int) -> bytes:
    out = bytearray()
    out += struct.pack("<HIIq", schema.schema_hash, shash, phash, timestamp)
    data_cols = schema.data.columns[1:]
    if len(values) != len(data_cols):
        raise ValueError(f"expected {len(data_cols)} values, got {len(values)}")
    for col, v in zip(data_cols, values):
        if col.ctype == ColumnType.DOUBLE:
            out += struct.pack("<d", float(v))
        elif col.ctype in (ColumnType.LONG, ColumnType.TIMESTAMP):
            out += struct.pack("<q", int(v))
        elif col.ctype == ColumnType.INT:
            out += struct.pack("<i", int(v))
        elif col.ctype == ColumnType.HISTOGRAM:
            blob = v if isinstance(v, (bytes, bytearray)) else bytes(v)
            out += struct.pack("<H", len(blob)) + blob
        elif col.ctype == ColumnType.STRING:
            blob = v.encode() if isinstance(v, str) else bytes(v)
            out += struct.pack("<H", len(blob)) + blob
        else:
            raise ValueError(f"unsupported column type {col.ctype}")
    pk = canonical_partkey(tags)
    out += struct.pack("<H", len(pk)) + pk
    return bytes(out)


def decode_container(buf: bytes, schemas) -> Iterator[IngestRecord]:
    """Iterate records in one container (reference: RecordContainer.iterate)."""
    (total,) = struct.unpack_from("<I", buf, 0)
    pos = 4
    end = 4 + total
    while pos < end:
        schema_hash, shash, phash, ts = struct.unpack_from("<HIIq", buf, pos)
        pos += 18
        schema = schemas.by_hash(schema_hash)
        vals = []
        for col in schema.data.columns[1:]:
            if col.ctype == ColumnType.DOUBLE:
                vals.append(struct.unpack_from("<d", buf, pos)[0]); pos += 8
            elif col.ctype in (ColumnType.LONG, ColumnType.TIMESTAMP):
                vals.append(struct.unpack_from("<q", buf, pos)[0]); pos += 8
            elif col.ctype == ColumnType.INT:
                vals.append(struct.unpack_from("<i", buf, pos)[0]); pos += 4
            elif col.ctype in (ColumnType.HISTOGRAM, ColumnType.STRING):
                (ln,) = struct.unpack_from("<H", buf, pos); pos += 2
                vals.append(bytes(buf[pos:pos + ln])); pos += ln
        (pklen,) = struct.unpack_from("<H", buf, pos)
        pos += 2
        tags = parse_partkey(buf[pos:pos + pklen])
        pos += pklen
        yield IngestRecord(schema_hash, tags, ts, tuple(vals), shash, phash)
