"""First-class histogram value type and bucket schemes.

Re-creates the capability of the reference's histogram model (reference:
memory/src/main/scala/filodb.memory/format/vectors/Histogram.scala:59-76):
histograms are single values with cumulative (Prometheus-style) buckets, a
bucket *scheme* shared across rows, and a ``quantile`` with Prometheus linear
interpolation.  Unlike the reference (per-value objects), bulk operations here
work on dense ``[rows, buckets]`` matrices so they can be shipped to device.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Sequence

import numpy as np


class HistogramBuckets:
    """Base for bucket schemes.  Subclasses define top-edge ("le") values."""

    scheme_id: int = 0

    @property
    def num_buckets(self) -> int:
        return len(self.bucket_tops())

    def bucket_tops(self) -> np.ndarray:
        raise NotImplementedError

    def serialize(self) -> bytes:
        raise NotImplementedError

    @staticmethod
    def deserialize(buf: bytes, offset: int = 0) -> tuple["HistogramBuckets", int]:
        scheme = buf[offset]
        if scheme == GeometricBuckets.scheme_id:
            first, mult, n, m1 = struct.unpack_from("<ddHB", buf, offset + 1)
            return GeometricBuckets(first, mult, n, bool(m1)), offset + 1 + 19
        if scheme == CustomBuckets.scheme_id:
            (n,) = struct.unpack_from("<H", buf, offset + 1)
            tops = np.frombuffer(buf, dtype="<f8", count=n, offset=offset + 3)
            return CustomBuckets(tops.copy()), offset + 3 + 8 * n
        raise ValueError(f"unknown bucket scheme {scheme}")

    def __eq__(self, other) -> bool:
        return (type(self) is type(other)
                and np.array_equal(self.bucket_tops(), other.bucket_tops()))

    def __hash__(self) -> int:
        return hash(self.bucket_tops().tobytes())


@dataclasses.dataclass(eq=False)
class GeometricBuckets(HistogramBuckets):
    """Exponential buckets: top_i = first * mult**i  (reference scheme
    ``geometric``; ``geometric_1`` prepends a bucket counting from 1)."""

    first_bucket: float
    multiplier: float
    count: int
    starts_at_one: bool = False  # geometric_1

    scheme_id = 1

    def bucket_tops(self) -> np.ndarray:
        tops = self.first_bucket * self.multiplier ** np.arange(self.count, dtype=np.float64)
        if self.starts_at_one:
            tops = np.concatenate([[1.0], tops])
        return tops

    def serialize(self) -> bytes:
        return bytes([self.scheme_id]) + struct.pack(
            "<ddHB", self.first_bucket, self.multiplier, self.count, int(self.starts_at_one))


@dataclasses.dataclass(eq=False)
class CustomBuckets(HistogramBuckets):
    """Explicit "le" upper bounds, Prometheus style; last is typically +Inf."""

    tops: np.ndarray

    scheme_id = 2

    def __post_init__(self):
        self.tops = np.asarray(self.tops, dtype=np.float64)

    def bucket_tops(self) -> np.ndarray:
        return self.tops

    def serialize(self) -> bytes:
        return bytes([self.scheme_id]) + struct.pack("<H", len(self.tops)) + self.tops.astype("<f8").tobytes()


@dataclasses.dataclass
class Histogram:
    """One histogram observation: cumulative bucket counts under a scheme."""

    buckets: HistogramBuckets
    values: np.ndarray  # cumulative counts, shape [num_buckets]

    def quantile(self, q: float) -> float:
        return float(quantile_bulk(self.buckets.bucket_tops(),
                                   self.values[np.newaxis, :], q)[0])

    def top_bucket_value(self) -> float:
        return float(self.values[-1])

    def __add__(self, other: "Histogram") -> "Histogram":
        if self.buckets != other.buckets:
            raise ValueError("bucket scheme mismatch")
        return Histogram(self.buckets, self.values + other.values)


def concat_hist_parts(parts: Sequence[tuple]) -> tuple:
    """Concatenate decoded ``(buckets, rows [n, b])`` histogram column
    parts along the row axis, tolerating a MID-STREAM bucket-scheme
    widening (16 -> 20 buckets): the widest scheme wins and narrower
    rows edge-pad with their top bucket — cumulative histograms carry
    their total in the top bucket, so the pad is semantically exact for
    every le the narrow scheme lacked (the same convention the serving
    paths use, memstore scan_batch / devicestore._build)."""
    parts = [(b, np.asarray(r)) for b, r in parts]
    if not parts:
        raise ValueError("no histogram parts to concatenate")
    widest = max(parts, key=lambda p: p[0].num_buckets)[0]
    nb = widest.num_buckets
    rows = []
    for bk, r in parts:
        if r.ndim != 2:
            r = r.reshape(len(r), -1)
        if r.shape[1] < nb:
            r = np.pad(r, ((0, 0), (0, nb - r.shape[1])), mode="edge")
        elif r.shape[1] > nb:        # cannot happen: widest wins
            raise ValueError("histogram part wider than the widest scheme")
        rows.append(r)
    return widest, np.concatenate(rows, axis=0)


def quantile_bulk(tops: np.ndarray, rows: np.ndarray, q: float) -> np.ndarray:
    """Prometheus histogram_quantile over a dense [rows, buckets] matrix.

    Same interpolation contract as the reference (reference:
    memory/.../vectors/Histogram.scala:59-76 and Prometheus's bucketQuantile):
    linear within the located bucket, lower bound 0 for the first bucket, and
    the last finite bucket top when the quantile lands in the +Inf bucket.
    """
    rows = np.asarray(rows, dtype=np.float64)
    if q < 0:
        return np.full(rows.shape[0], -np.inf)
    if q > 1:
        return np.full(rows.shape[0], np.inf)
    B = len(tops)
    if B < 2:
        return np.full(rows.shape[0], np.nan)
    total = rows[:, -1]
    rank = q * total
    # first bucket index whose cumulative count >= rank (exact, no epsilon —
    # reference: Histogram.firstBucketGTE)
    idx = np.sum(rows < rank[:, None], axis=1)
    idx = np.minimum(idx, B - 1)
    count_at = np.take_along_axis(rows, idx[:, None], axis=1)[:, 0]
    count_below = np.where(idx > 0,
                           np.take_along_axis(rows, np.maximum(idx - 1, 0)[:, None], axis=1)[:, 0],
                           0.0)
    top = tops[idx]
    bottom = np.where(idx > 0, tops[np.maximum(idx - 1, 0)], 0.0)
    with np.errstate(invalid="ignore", divide="ignore"):
        interp = bottom + (top - bottom) * (rank - count_below) / (count_at - count_below)
    # last bucket: cannot interpolate to +Inf -> second-to-last top
    out = np.where(idx == B - 1, tops[B - 2], interp)
    # first bucket with non-positive top: return the top itself
    out = np.where((idx == 0) & (tops[0] <= 0), tops[0], out)
    # all-NaN rows (padded / no-data series slots) must stay NaN
    out = np.where(np.isnan(total), np.nan, out)
    return out


def hist_max_quantile_bulk(tops: np.ndarray, rows: np.ndarray, maxes: np.ndarray,
                           q: float) -> np.ndarray:
    """histogram_max_quantile: clamp the top interpolation bound to the
    observed max column (reference hist-max schema handling,
    query/exec/rangefn and Histogram.scala `quantile` w/ max)."""
    base = quantile_bulk(tops, rows, q)
    return np.where(np.isfinite(maxes) & (base > maxes), maxes, base)
