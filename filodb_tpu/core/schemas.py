"""Dataset / schema metadata.

Capability match for the reference's config-driven schema system (reference:
core/src/main/scala/filodb.core/metadata/Schemas.scala:170,258,374,
Column.scala, Dataset.scala:36 and the ``filodb.schemas`` section of
core/src/main/resources/filodb-defaults.conf:52-107):

- ``DataSchema``: column 0 is the timestamp; one column is the designated
  value column; a 16-bit schema hash distinguishes multi-schema records;
  downsampler specs and a downsample-period marker ride along.
- ``PartitionSchema``: the tag map + predefined keys shared by every dataset.
- ``Schema``: a (partition, data) pair plus optional downsample schema.
- ``Dataset``/``DatasetOptions``: a named dataset bound to one schema with
  shard-key options (metric column, shard key columns, ...).

Built-in schemas replicate the reference defaults: gauge, untyped,
prom-counter, prom-histogram, ds-gauge.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import zlib
from typing import Mapping, Optional, Sequence


class ColumnType(enum.Enum):
    TIMESTAMP = "ts"      # int64 epoch millis
    LONG = "long"
    DOUBLE = "double"
    INT = "int"
    STRING = "string"
    HISTOGRAM = "hist"
    MAP = "map"           # partition-key tag map


@dataclasses.dataclass(frozen=True)
class Column:
    id: int
    name: str
    ctype: ColumnType
    # detectDrops=true marks Prometheus counter semantics (reset correction
    # applied at query time); mirrors the reference's column param
    # `detectDrops` (filodb-defaults.conf:80) and DoubleCounterAppender.
    detect_drops: bool = False
    counter: bool = False  # hist:counter=true

    @staticmethod
    def parse(col_id: int, spec: str) -> "Column":
        parts = spec.split(":")
        name, ctype = parts[0], ColumnType(parts[1])
        params = dict(p.split("=") for p in parts[2:])
        return Column(col_id, name, ctype,
                      detect_drops=params.get("detectDrops", "false") == "true",
                      counter=params.get("counter", "false") == "true")


def _hash16(text: str) -> int:
    return zlib.crc32(text.encode()) & 0xFFFF


@dataclasses.dataclass(frozen=True)
class DataSchema:
    """Columns of one time-series sample; column 0 must be the timestamp
    (reference: Schemas.scala DataSchema validation)."""

    name: str
    columns: tuple[Column, ...]
    value_column: str
    downsamplers: tuple[str, ...] = ()
    downsample_period_marker: str = "time(0)"
    downsample_schema: Optional[str] = None

    def __post_init__(self):
        if not self.columns or self.columns[0].ctype not in (ColumnType.TIMESTAMP, ColumnType.LONG):
            raise ValueError(f"schema {self.name}: first column must be ts/long")

    @functools.cached_property
    def schema_hash(self) -> int:
        """16-bit hash over name + column defs, embedded in ingest records so
        multi-schema streams are self-describing (reference: per-schema 16-bit
        hash, Schemas.scala:170).  Cached — the serving hot path compares
        it once per partition per query."""
        sig = self.name + "|" + ",".join(f"{c.name}:{c.ctype.value}" for c in self.columns)
        return _hash16(sig)

    @property
    def value_column_id(self) -> int:
        return next(c.id for c in self.columns if c.name == self.value_column)

    def column(self, name: str) -> Column:
        return next(c for c in self.columns if c.name == name)

    @property
    def timestamp_column(self) -> Column:
        return self.columns[0]


@dataclasses.dataclass(frozen=True)
class PartitionSchema:
    """Partition-key layout: a tag map plus predefined keys whose names are
    stored as small indexes (reference: PartitionSchema, Schemas.scala:258;
    predefined-keys in filodb-defaults.conf)."""

    predefined_keys: tuple[str, ...] = ("_ws_", "_ns_", "_metric_")

    def shard_key_tags(self, options: "DatasetOptions") -> tuple[str, ...]:
        return tuple(options.shard_key_columns)


@dataclasses.dataclass(frozen=True)
class Schema:
    partition: PartitionSchema
    data: DataSchema
    downsample: Optional["Schema"] = None

    @property
    def name(self) -> str:
        return self.data.name

    @property
    def schema_hash(self) -> int:
        return self.data.schema_hash


@dataclasses.dataclass(frozen=True)
class DatasetOptions:
    """Reference: Dataset.scala:108 DatasetOptions."""

    shard_key_columns: tuple[str, ...] = ("_ws_", "_ns_", "_metric_")
    metric_column: str = "_metric_"
    ignore_shard_key_column_suffixes: Mapping[str, tuple[str, ...]] = dataclasses.field(
        default_factory=lambda: {"_metric_": ("_bucket", "_count", "_sum")})
    ignore_tags_on_partition_key_hash: tuple[str, ...] = ("le",)


@dataclasses.dataclass(frozen=True)
class Dataset:
    name: str
    schema: Schema
    options: DatasetOptions = dataclasses.field(default_factory=DatasetOptions)


class Schemas:
    """Registry of all known schemas, looked up by name or 16-bit hash
    (reference: Schemas object, Schemas.scala:374 fromConfig)."""

    def __init__(self, partition: PartitionSchema, schemas: Mapping[str, Schema]):
        self.part = partition
        self._by_name = dict(schemas)
        self._by_hash = {s.schema_hash: s for s in schemas.values()}
        if len(self._by_hash) != len(self._by_name):
            raise ValueError("schema hash conflict")

    def __getitem__(self, name: str) -> Schema:
        return self._by_name[name]

    def get(self, name: str) -> Optional[Schema]:
        return self._by_name.get(name)

    def by_hash(self, h: int) -> Schema:
        return self._by_hash[h]

    @property
    def all(self) -> Sequence[Schema]:
        return list(self._by_name.values())

    @staticmethod
    def from_config(config: Mapping[str, Mapping]) -> "Schemas":
        """Build from a dict mirroring the ``filodb.schemas`` HOCON section."""
        part = PartitionSchema()
        datas: dict[str, DataSchema] = {}
        for name, sc in config.items():
            cols = tuple(Column.parse(i, spec) for i, spec in enumerate(sc["columns"]))
            datas[name] = DataSchema(
                name=name, columns=cols, value_column=sc["value-column"],
                downsamplers=tuple(sc.get("downsamplers", ())),
                downsample_period_marker=sc.get("downsample-period-marker", "time(0)"),
                downsample_schema=sc.get("downsample-schema"))
        schemas: dict[str, Schema] = {}
        for name, d in datas.items():
            ds = None
            if d.downsample_schema and d.downsample_schema != name:
                dd = datas[d.downsample_schema]
                ds = Schema(part, dd)
            elif d.downsample_schema == name:
                ds = None  # self-downsampling (counter/hist): same schema
            schemas[name] = Schema(part, d, downsample=ds)
        return Schemas(part, schemas)


# Built-in schema registry replicating filodb-defaults.conf:52-107.
DEFAULT_SCHEMA_CONFIG: dict[str, dict] = {
    "gauge": {
        "columns": ["timestamp:ts", "value:double:detectDrops=false"],
        "value-column": "value",
        "downsamplers": ["tTime(0)", "dMin(1)", "dMax(1)", "dSum(1)", "dCount(1)", "dAvg(1)"],
        "downsample-period-marker": "time(0)",
        "downsample-schema": "ds-gauge",
    },
    "untyped": {
        "columns": ["timestamp:ts", "number:double"],
        "value-column": "number",
        "downsamplers": [],
    },
    "prom-counter": {
        "columns": ["timestamp:ts", "count:double:detectDrops=true"],
        "value-column": "count",
        "downsamplers": ["tTime(0)", "dLast(1)"],
        "downsample-period-marker": "counter(1)",
        "downsample-schema": "prom-counter",
    },
    "prom-histogram": {
        "columns": ["timestamp:ts", "sum:double:detectDrops=true",
                    "count:double:detectDrops=true", "h:hist:counter=true"],
        "value-column": "h",
        "downsamplers": ["tTime(0)", "dLast(1)", "dLast(2)", "hLast(3)"],
        "downsample-period-marker": "counter(2)",
        "downsample-schema": "prom-histogram",
    },
    "ds-gauge": {
        "columns": ["timestamp:ts", "min:double", "max:double", "sum:double",
                    "count:double", "avg:double"],
        "value-column": "avg",
        "downsamplers": [],
    },
    # histogram with an extra max column: queries pair the hist kernel
    # with the max plane so histogram_max_quantile can cap the top bucket
    # (reference: SelectRawPartitionsExec.histMaxColumn + the hist-max
    # test schemas; rewrites in query/dsrewrite.py)
    "prom-hist-max": {
        "columns": ["timestamp:ts", "sum:double:detectDrops=true",
                    "count:double:detectDrops=true", "max:double",
                    "h:hist:counter=true"],
        "value-column": "h",
        "downsamplers": ["tTime(0)", "dLast(1)", "dLast(2)", "dMax(3)",
                         "hLast(4)"],
        "downsample-period-marker": "counter(2)",
        "downsample-schema": "prom-hist-max",
    },
}

DEFAULT_SCHEMAS = Schemas.from_config(DEFAULT_SCHEMA_CONFIG)
