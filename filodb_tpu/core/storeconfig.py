"""Per-dataset store/ingestion configuration.

Capability match for the reference's StoreConfig/IngestionConfig parsed from
per-dataset source config (reference: core/src/main/scala/filodb.core/store/
IngestionConfig.scala:202 and conf/timeseries-dev-source.conf:28-102).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional


@dataclasses.dataclass(frozen=True)
class StoreConfig:
    flush_interval_ms: int = 3_600_000        # flush-interval = 1h
    flush_task_parallelism: int = 2           # flush executor workers
    max_chunks_size: int = 400                # max rows per chunk
    groups_per_shard: int = 60
    shard_mem_size: int = 512 * 1024 * 1024   # shard-mem-size budget (bytes)
    max_buffer_pool_size: int = 10_000
    disk_ttl_seconds: int = 3 * 24 * 3600
    demand_paging_enabled: bool = True
    max_data_per_shard_query: int = 50 * 1024 * 1024
    evicted_pk_bloom_filter_capacity: int = 5_000_000
    # TPU additions: padding buckets for device batches (bounded XLA
    # recompiles — SURVEY.md §7 "Ragged data")
    batch_row_pad: int = 64
    batch_series_pad: int = 128
    # device-resident chunk store (HBM arena, reclaim-on-demand — the
    # BlockManager equivalent, reference: memory/BlockManager.scala:142)
    device_cache_bytes: int = 2 * 1024 * 1024 * 1024
    # host page cache for demand-paged partitions (decoded bytes are
    # accounted too); must cover the cold-dashboard working set or the
    # device grid cannot build from paged history (reference: ODP pages
    # into block memory whose size is config-driven,
    # DemandPagedChunkStore.scala:34 + num-block-pages)
    page_cache_bytes: int = 256 * 1024 * 1024
    grid_step_ms: Optional[int] = None   # bucket width; None = detect
    # keep grid blocks compressed in HBM (XOR-class value planes +
    # elided uniform-phase ts planes), decoded on device inside the
    # serving program; compression is taken per block only when it
    # saves >=25% (reference: compressed BinaryVectors served in place
    # from block memory, doc/compression.md)
    device_cache_compress: bool = True
    # proactive reclaim target: flush tasks trim each device cache to
    # (1-frac) of budget off the query path (reference: BlockManager
    # ensureHeadroomPercentAvailable headroom task)
    device_headroom_frac: float = 0.1
    # tag subset selecting series created as TracingTimeSeriesPartition
    # (reference: `trace-filters` config -> TimeSeriesPartition.scala:451)
    trace_filters: Optional[Mapping] = None

    @staticmethod
    def from_config(conf: Mapping) -> "StoreConfig":
        def ms(key: str, default: int) -> int:
            v = conf.get(key)
            return parse_duration_ms(v) if v is not None else default

        d = StoreConfig()
        return StoreConfig(
            flush_interval_ms=ms("flush-interval", d.flush_interval_ms),
            flush_task_parallelism=int(conf.get("flush-task-parallelism",
                                                d.flush_task_parallelism)),
            max_chunks_size=int(conf.get("max-chunks-size", d.max_chunks_size)),
            groups_per_shard=int(conf.get("groups-per-shard", d.groups_per_shard)),
            shard_mem_size=parse_size(conf.get("shard-mem-size", d.shard_mem_size)),
            max_buffer_pool_size=int(conf.get("max-buffer-pool-size",
                                              d.max_buffer_pool_size)),
            disk_ttl_seconds=ms("disk-time-to-live", d.disk_ttl_seconds * 1000) // 1000,
            demand_paging_enabled=parse_bool(conf.get("demand-paging-enabled",
                                                d.demand_paging_enabled)),
            max_data_per_shard_query=parse_size(conf.get("max-data-per-shard-query",
                                                         d.max_data_per_shard_query)),
            evicted_pk_bloom_filter_capacity=int(
                conf.get("evicted-pk-bloom-filter-capacity",
                         d.evicted_pk_bloom_filter_capacity)),
            batch_row_pad=int(conf.get("batch-row-pad", d.batch_row_pad)),
            batch_series_pad=int(conf.get("batch-series-pad", d.batch_series_pad)),
            device_cache_bytes=parse_size(conf.get("device-cache-size",
                                                   d.device_cache_bytes)),
            page_cache_bytes=parse_size(conf.get("page-cache-size",
                                                 d.page_cache_bytes)),
            grid_step_ms=(parse_duration_ms(conf["grid-step"])
                          if "grid-step" in conf else None),
            device_cache_compress=parse_bool(
                conf.get("device-cache-compress",
                         d.device_cache_compress)),
            device_headroom_frac=float(
                conf.get("device-headroom-frac", d.device_headroom_frac)),
            trace_filters=conf.get("trace-filters"),
        )


@dataclasses.dataclass(frozen=True)
class IngestionConfig:
    """Binds a dataset to a source (reference: IngestionConfig — dataset,
    num-shards, min-num-nodes, sourcefactory + sourceconfig)."""

    dataset: str
    num_shards: int
    min_num_nodes: int = 1
    source_factory: Optional[str] = None
    source_config: Mapping = dataclasses.field(default_factory=dict)
    store: StoreConfig = dataclasses.field(default_factory=StoreConfig)

    def __post_init__(self):
        if self.num_shards & (self.num_shards - 1):
            raise ValueError(f"num_shards {self.num_shards} must be a power of 2")

    @staticmethod
    def from_config(conf: Mapping) -> "IngestionConfig":
        src = conf.get("sourceconfig", {})
        return IngestionConfig(
            dataset=conf["dataset"],
            num_shards=int(conf["num-shards"]),
            min_num_nodes=int(conf.get("min-num-nodes", 1)),
            source_factory=conf.get("sourcefactory"),
            source_config=src,
            store=StoreConfig.from_config(src.get("store", {})),
        )


_UNITS_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000,
             "minute": 60_000, "minutes": 60_000, "hour": 3_600_000,
             "hours": 3_600_000, "day": 86_400_000, "days": 86_400_000,
             "second": 1000, "seconds": 1000}


def parse_bool(v) -> bool:
    """Config booleans arrive as real bools or as strings from config
    files; bool('false') == True would silently defeat every string-
    valued kill switch."""
    if isinstance(v, str):
        lv = v.strip().lower()
        if lv in ("true", "yes", "on", "1"):
            return True
        if lv in ("false", "no", "off", "0"):
            return False
        raise ValueError(f"not a boolean config value: {v!r}")
    return bool(v)


def parse_duration_ms(v) -> int:
    """'1 hour' / '5m' / '300ms' / int millis -> millis (HOCON-style)."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip()
    for unit in sorted(_UNITS_MS, key=len, reverse=True):
        if s.endswith(unit):
            return int(float(s[: -len(unit)].strip()) * _UNITS_MS[unit])
    return int(float(s))


_SIZE_UNITS = {"kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30, "k": 1 << 10,
               "m": 1 << 20, "g": 1 << 30, "b": 1}


def parse_size(v) -> int:
    """'512MB' / '2GB' / int bytes -> bytes."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    for unit in sorted(_SIZE_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            return int(float(s[: -len(unit)].strip()) * _SIZE_UNITS[unit])
    return int(float(s))
