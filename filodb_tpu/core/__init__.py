"""Core engine: schemas, record format, chunk store APIs, memstore.

Equivalent of the reference's ``core/`` module (SURVEY.md §2.2).
"""
