"""Standalone server: wire every subsystem into one process.

Capability match for the reference's FiloServer main (reference:
standalone/src/main/scala/filodb.standalone/FiloServer.scala:39,91 —
coordinatorActor -> metaStore.initialize -> cluster bootstrap -> cluster
singleton/shard assignment -> HTTP server -> SimpleProfiler.launch),
driven by a JSON config instead of HOCON:

    {
      "node": "node-0",
      "data-dir": "/var/filodb",          # omit for in-memory only
      "http-port": 8080,
      "gateway-port": 8009,               # omit to disable the Influx edge
      "broker": {"port": 9092, "data-dir": "/var/filodb/broker"},
                                          # embedded message broker (omit
                                          # to use an external one / none)
      "profiler": false,
      "workload": {"min-remote-budget-ms": 5},
                                          # node-wide workload knobs
      "result-cache": {                   # ISSUE 12 (doc/query-engine.md):
                                          # chunk-aligned partial
                                          # memoization + incremental
                                          # instant windows, every
                                          # dataset incl. rollup tiers
        "enabled": true, "max-bytes": 67108864,
        "segment": "1h",                  # default: the flush interval
        "instant": true
      },
      "coldstore": {                      # ISSUE 16 (doc/coldstore.md):
                                          # object-store cold tier —
                                          # flushed/rolled chunks age out
                                          # of local sqlite into the
                                          # bucket; queries page them
                                          # back on demand (CRC-verified)
        "enabled": true,
        "bucket-dir": "/var/filodb/coldstore",
                                          # default: {data-dir}/coldstore
        "retention": "30d",               # age-out cutoff; omit/0 =
                                          # manual only (cli age-out)
        "tick-interval-s": 3600,
        "fetch-timeout-s": 30,            # offline cap; queries use the
                                          # tighter deadline budget
        "datasets": ["prom_ds_3600000"]   # restrict; omit = all
      },
      "dataplane": {                      # ISSUE 6 (doc/observability.md)
        "watermark-sample-interval-s": 10,
        "ingest-stall-window-s": 30,
        "self-scrape": {"enabled": false, "interval-s": 10,
                        "dataset": "_system", "num-shards": 1}
      },
      "rules": {                          # ISSUE 9 (doc/rules.md)
        "groups": [...],                  # inline rule groups
        "files": ["/etc/filodb/rules.json"],
        "notifier": {"url": "http://alertmanager:9093/api/v2/alerts",
                     "timeout-s": 5, "retries": 3, "backoff-s": 0.25},
        "self-monitoring": {"enabled": true, "interval": "15s",
                            "for": "30s"}
                                          # the shipped pack over the
                                          # _system dataset; defaults on
                                          # whenever self-scrape is on
      },
      "datasets": [{
        "name": "prom", "num-shards": 4, "min-num-nodes": 1,
        "schema": "gauge", "spread": 1,
        "replication-factor": 1,          # ISSUE 7 (doc/ha.md): >1 puts
                                          # each shard on that many nodes
        "source": {"factory": "kafka", "host": "127.0.0.1",
                   "port": 9092, "topic": "prom"},
                                          # omit for the in-proc queue
        "store": {"flush-interval": "1h", "groups-per-shard": 8},
        "rollup": {                       # ISSUE 11 (doc/rollup.md):
                                          # continuous raw->1m->15m->1h
                                          # tiering + resolution-routed
                                          # queries; omit to disable
          "resolutions": ["1m", "15m", "1h"],
          "tick-interval-s": 30,
          "raw-retention": "0"            # 0 = raw keeps everything
        },
        "workload": {                     # ISSUE 5 (doc/workload.md);
                                          # every knob has a default —
                                          # the block is optional
          "admission": {"max-inflight-cost": 10000,
                        "tenant-max-concurrent": 32,
                        "priority-shares": {"low": 0.5, "default": 0.8,
                                            "high": 1.0}},
          "quota": {"tenant-label": "_ns_",
                    "default-max-series": 1000000,
                    "overrides": {"App-9": 1000}},
          "dispatch": {"timeout-cap-s": 60, "retries": 2,
                       "hedge": false}
        }
      }]
    }
"""

from __future__ import annotations

import json
import signal
import sys
import threading
from typing import Optional

from filodb_tpu.coordinator.cluster import (FailureDetector, ShardManager,
                                            StatusPoller)
from filodb_tpu.coordinator.node import NodeCoordinator
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.gateway.server import GatewayServer, ShardingPublisher
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.ingest.stream import QueueStreamFactory
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.utils.observability import REGISTRY, SimpleProfiler


class FiloServer:
    """One node: stores + shard manager + ingestion + HTTP (+ gateway)."""

    def __init__(self, config: dict):
        self.config = config
        self.node = config.get("node", "node-0")
        data_dir = config.get("data-dir")
        if data_dir:
            from filodb_tpu.store.persistence import (DiskColumnStore,
                                                      DiskMetaStore)
            self.colstore = DiskColumnStore(f"{data_dir}/chunks.db")
            self.metastore = DiskMetaStore(f"{data_dir}/meta.db")
        else:
            from filodb_tpu.store.columnstore import NullColumnStore
            from filodb_tpu.store.metastore import InMemoryMetaStore
            self.colstore = NullColumnStore()
            self.metastore = InMemoryMetaStore()
        # cold tier (ISSUE 16, doc/coldstore.md): object-bucket chunk
        # archive behind the local store.  The TieredColumnStore wrap
        # happens BEFORE the memstore exists, so ODP paging, flushes and
        # the split controller all see one merged ColumnStore; age-out
        # itself runs against the unwrapped local store.
        self.local_colstore = self.colstore
        self.cold_store = None
        self.ageout = None
        self._ageout_stop = threading.Event()
        self._ageout_thread: Optional[threading.Thread] = None
        cs_conf = config.get("coldstore") or {}
        if data_dir and cs_conf.get("enabled"):
            from filodb_tpu.coldstore import (AgeOutManager, ColdChunkStore,
                                              LocalFSBucket,
                                              TieredColumnStore)
            bucket_dir = cs_conf.get("bucket-dir") \
                or f"{data_dir}/coldstore"
            self.cold_store = ColdChunkStore(
                LocalFSBucket(bucket_dir),
                fetch_timeout_s=float(cs_conf.get("fetch-timeout-s",
                                                  30.0)))
            self.colstore = TieredColumnStore(self.local_colstore,
                                              self.cold_store)
            self.ageout = AgeOutManager(self.local_colstore,
                                        self.cold_store,
                                        metastore=self.metastore)
        self.memstore = TimeSeriesMemStore(self.colstore, self.metastore)
        self.manager = ShardManager(
            reassignment_min_interval_ms=int(
                config.get("reassignment-min-interval-ms", 0)))
        self.failure_detector = FailureDetector(
            self.manager,
            timeout_ms=int(config.get("failure-detector-timeout-ms",
                                      10_000)))
        self.coordinator = NodeCoordinator(self.node, self.memstore)
        self.stream_factory = QueueStreamFactory()
        self.http = FiloHttpServer(port=config.get("http-port", 0),
                                   node_name=self.node,
                                   shard_manager=self.manager,
                                   running_shards=self._running_shards)
        self.gateways: list[GatewayServer] = []
        self.broker = None  # embedded BrokerServer when configured
        self.query_schedulers: dict[str, object] = {}
        self.admission_controllers: dict[str, object] = {}
        self.status_poller: Optional[StatusPoller] = None
        self.profiler: Optional[SimpleProfiler] = None
        # data-plane observability (ISSUE 6): watermark ledger + sampler
        # + optional self-telemetry scraper; the remote-write publishers
        # per dataset double as the self-scrape ingest edge
        self.watermarks = None
        self.watermark_sampler = None
        self.selfscraper = None
        # fleet workload insights (ISSUE 19): per-fingerprint ledger +
        # tenant SLO tracker + fleet aggregator behind /admin/insights
        # and /admin/fleet; wired in _setup_insights()
        self.slo_tracker = None
        self.insights_fleet = None
        # rule engine (ISSUE 9): continuous recording/alerting rules
        # evaluated through the normal query path (doc/rules.md)
        self.rule_engine = None
        self.rule_notifier = None
        # rollup engine (ISSUE 11, doc/rollup.md): continuous
        # raw->1m->15m->1h tiering into <ds>_ds_<res> datasets +
        # resolution-routed serving; created on the first dataset with
        # a "rollup" block
        self.rollup_engine = None
        # cluster-wide rollup tier closure gossip (ROADMAP 2b): peers'
        # /__health rollup payloads land here via the StatusPoller so
        # the resolution router stitches at the CLUSTER boundary
        from filodb_tpu.memstore.watermarks import TierWatermarks
        self.tier_watermarks = TierWatermarks(node=self.node)
        # query-frontend result cache (ISSUE 12, doc/query-engine.md):
        # one ResultCache per dataset (tiers included), embedded in the
        # serving planner; the top-level "result-cache" block opts in
        self.result_caches: dict[str, object] = {}
        self.write_publishers: dict[str, ShardingPublisher] = {}
        # dataset -> raw container publish fn (queue push / broker
        # produce / ReplicaFanout): the rollup engine emits rolled
        # containers through the TIER dataset's publish path so they
        # ride the same replication as any ingest
        self._publish_fns: dict[str, object] = {}
        self._global_gateway_claimed = False
        # datasets fed by the in-proc queue: the only legal targets of
        # the replica container-push edge (POST /ingest, ISSUE 7)
        self._queue_push_datasets: set = set()
        # dual-write fanouts, retained so shutdown can stop their peer
        # delivery lanes (a dead node must not keep POSTing to peers)
        self._replica_fanouts: list = []
        # elastic resharding (ISSUE 13, coordinator/split.py): live
        # power-of-two shard splits.  Per-dataset transport/spread/tier
        # maps feed the controller; the memstore setup hook installs the
        # split half-filters on shards the instant they are created.
        self._transports: dict[str, str] = {}
        self._spreads: dict[str, int] = {}
        self._tiers: dict[str, list] = {}
        from filodb_tpu.coordinator.split import SplitController
        self.split_controller = SplitController(
            self.node, self.manager, self.memstore, self.colstore,
            self.metastore,
            peers=self.config.get("peers", {}),
            resync=self.resync_all,
            transport_for=lambda ds: self._transports.get(ds, "queue"),
            tiers_for=lambda ds: list(self._tiers.get(ds, ())),
            fresh_nodes=self.failure_detector.fresh_nodes,
            spread_for=lambda ds: self._spreads.get(ds, 1))
        self.memstore.shard_setup_hook = self._on_shard_setup
        self.http.split = self.split_controller
        self.http.split_progress = self.split_controller.split_progress
        # (dataset, shard) -> first legal push offset (above persisted
        # checkpoints), resolved once per shard on first peer push
        self._push_offset_floor: dict = {}
        self.http.ingest_sink = self._ingest_push
        self._started = threading.Event()

    def _ingest_push(self, dataset: str, shard: int,
                     container: bytes) -> int:
        """Receiver side of the replica dual-write fanout: a peer's
        container lands on this node's in-proc ingest queue.  The
        stream's offset numbering is fast-forwarded past this node's
        persisted checkpoints FIRST — a push landing before the
        restarted consumer's own ``create(offset=resume_from)`` would
        otherwise be numbered below the recovery watermark and silently
        skipped as already-persisted."""
        if dataset not in self._queue_push_datasets:
            raise ValueError(
                f"dataset {dataset!r} does not accept container pushes "
                f"(broker-sourced or unknown)")
        # total_shards: a peer that committed a split before this node
        # adopted it may already push child-shard containers (ISSUE 13)
        num_shards = self.manager.mapper(dataset).total_shards
        if not 0 <= shard < num_shards:
            # out-of-range pushes would ACK into a consumerless queue
            # (silent loss + unbounded memory).  A valid shard this
            # node does not CURRENTLY hold is accepted on purpose —
            # membership gossip may lag the sender's view, and the
            # queue is drained once the replica assignment lands.
            raise ValueError(
                f"shard {shard} out of range for {dataset!r} "
                f"({num_shards} shards)")
        stream = self.stream_factory.stream_for(dataset, shard)
        key = (dataset, shard)
        floor = self._push_offset_floor.get(key)
        if floor is None:
            try:
                cps = self.metastore.read_checkpoints(dataset, shard)
            except Exception:  # noqa: BLE001 — meta store not ready
                # transient failure: use 0 for THIS push but do not
                # cache it — a cached 0 would defeat the fast-forward
                # forever even after the metastore becomes readable
                cps = None
            if cps is None:
                floor = 0
            else:
                floor = self._push_offset_floor[key] = \
                    (max(cps.values()) + 1) if cps else 0
        if floor:
            stream.ensure_offset(floor)
        return stream.push(container)

    @staticmethod
    def _device_count() -> int:
        try:
            import jax
            return jax.local_device_count()
        except Exception:  # noqa: BLE001 — no backend: host-only serving
            return 1

    def _running_shards(self, dataset: str) -> list[int]:
        ic = self.coordinator.ingestion.get(dataset)
        return ic.running_shards() if ic is not None else []

    def _on_shard_setup(self, dataset: str, shard) -> None:
        """memstore hook: every freshly-created shard picks up its split
        policy (half filters) before any ingest, and raw-dataset shards
        born from a split attach to the live rollup engine so their
        flushes tier exactly like their parents'."""
        self.split_controller.on_shard_setup(dataset, shard)
        eng = self.rollup_engine
        if eng is not None and dataset in eng.datasets() \
                and shard.rollup_listener is None:
            try:
                eng.attach_shard(dataset, shard)
            except Exception:  # noqa: BLE001 — engine mid-shutdown
                pass

    def resync_all(self) -> None:
        """Reconcile every dataset's running shards with the mapper,
        holding back split children whose local clone has not landed
        (they would replay from nothing)."""
        for ds in self.manager.datasets():
            shards = self.manager.mapper(ds).runnable_shards_for_node(
                self.node)
            shards = self.split_controller.startable_shards(ds, shards)
            self.coordinator.resync(ds, shards)

    def start(self) -> int:
        """Bring the node up; returns the HTTP port."""
        broker_conf = self.config.get("broker")
        if broker_conf is not None:
            from filodb_tpu.ingest.broker import BrokerServer
            self.broker = BrokerServer(
                port=int(broker_conf.get("port", 0)),
                data_dir=broker_conf.get("data-dir"))
            self.broker.start()
        self.metastore.initialize()
        # in-flight split records load BEFORE datasets: each dataset's
        # mapper replays its persisted split topology at setup, so a
        # restarted coordinator resumes (or can abort) instead of
        # wedging mid-split (ISSUE 13)
        self.split_controller.load_persisted()
        self.failure_detector.heartbeat(self.node)
        up = REGISTRY.gauge("filodb_node_up")
        up.set(1.0, node=self.node)
        # slow-query forensics threshold (seconds); completed queries
        # slower than this keep their span tree in /admin/slowlog.
        # Runtime-adjustable afterwards via POST /admin/config.
        thr = self.config.get("slow-query-threshold-s")
        if thr is not None:
            from filodb_tpu.utils.forensics import TRACE_STORE
            TRACE_STORE.slow_threshold_s = float(thr)
        # device-resource observability (ISSUE 4): storm-detector tuning
        # + flight-recorder sizing from the "devicewatch" config block,
        # and the crash hooks that dump the black box on an unhandled
        # exception shutdown
        from filodb_tpu.utils import devicewatch
        devicewatch.configure(self.config.get("devicewatch"))
        devicewatch.install_crash_hooks()
        # kernel flight deck (ISSUE 15): regression-sentry baselines
        # persist in the metastore KV (ratcheted downward only), so a
        # restart does not relearn a regressed program's slow state as
        # its baseline — the persisted healthy floor wins the merge
        _meta = self.metastore
        devicewatch.KERNEL_TIMER.attach_baseline_store(
            load_fn=lambda: {
                k.split(":", 1)[1]: float(v)
                for k, v in _meta.list_kv("kernel_baseline:").items()},
            save_fn=lambda program, seconds: _meta.write_kv(
                f"kernel_baseline:{program}", repr(float(seconds))))
        # node-wide workload knob: the /execplan refusal floor guards
        # ONE HTTP server, so it lives at the config top level (a
        # per-dataset spelling would silently be last-bound-wins)
        wl_top = self.config.get("workload", {})
        if "min-remote-budget-ms" in wl_top:
            self.http.min_remote_budget_ms = int(
                wl_top["min-remote-budget-ms"])
        # data-plane observability (ISSUE 6, doc/observability.md):
        # the watermark ledger exists BEFORE datasets so _setup_dataset
        # can watch each one with its broker/queue end-offset source
        from filodb_tpu.memstore.watermarks import (WatermarkLedger,
                                                    WatermarkSampler)
        dp = self.config.get("dataplane", {})
        self.watermarks = WatermarkLedger(
            stall_window_s=float(dp.get("ingest-stall-window-s", 30.0)),
            node=self.node)
        self.http.watermarks = self.watermarks

        for ds_conf in self.config.get("datasets", []):
            self._setup_dataset(ds_conf)

        # self-telemetry (ISSUE 6 pillar 3): scrape this node's own
        # exposition into a Prometheus-schema dataset through the normal
        # gateway ingest path, so node health is PromQL-queryable
        ss = dp.get("self-scrape") or {}
        if ss.get("enabled"):
            sys_ds = ss.get("dataset", "_system")
            if sys_ds not in self.manager.datasets():
                # the synthesized dataset never claims the node's global
                # Influx gateway port — that edge belongs to user data
                claimed = self._global_gateway_claimed
                self._global_gateway_claimed = True
                try:
                    self._setup_dataset({
                        "name": sys_ds,
                        "num-shards": int(ss.get("num-shards", 1)),
                        "min-num-nodes": 1, "schema": "gauge", "spread": 0,
                        "store": ss.get("store", {})})
                finally:
                    self._global_gateway_claimed = claimed
            from filodb_tpu.gateway.selfscrape import SelfScraper
            self.selfscraper = SelfScraper(
                self.write_publishers[sys_ds],
                interval_s=float(ss.get("interval-s", 10.0)),
                default_tags={"_ws_": "filodb", "_ns_": self.node,
                              "instance": self.node})
            self.selfscraper.start()
        self.watermark_sampler = WatermarkSampler(
            self.watermarks,
            interval_s=float(dp.get("watermark-sample-interval-s", 10.0)))
        self.watermark_sampler.start()

        self._setup_insights()
        self._setup_rules(ss)
        if self.rollup_engine is not None:
            self.rollup_engine.start()

        # cold-tier age-out loop (ISSUE 16): periodic retention passes
        # move closed local chunks into the bucket.  Only when a
        # retention is configured — without one the tier is read/manual
        # only (cli.py age-out)
        cs_conf = self.config.get("coldstore") or {}
        if self.ageout is not None and cs_conf.get("retention") \
                and str(cs_conf["retention"]) not in ("0", ""):
            from filodb_tpu.http.model import parse_duration_ms
            retention_ms = parse_duration_ms(str(cs_conf["retention"]))
            if retention_ms > 0:
                self._ageout_thread = threading.Thread(
                    target=self._ageout_loop,
                    args=(retention_ms,
                          float(cs_conf.get("tick-interval-s", 3600.0))),
                    name="coldstore-ageout", daemon=True)
                self._ageout_thread.start()

        port = self.http.start()
        self.split_controller.start()
        peers = self.config.get("peers", {})
        if peers:
            # cross-node status gossip + automatic failover (reference:
            # StatusActor/ShardMapper snapshots + Akka failure detector)
            def resync_all():
                # split participant duties first: an adopted topology
                # may need child clones before the resync can start
                # their consumers (ISSUE 13)
                self.split_controller.reconcile()
                self.resync_all()

            def local_watermarks(ds: str) -> dict:
                return {sh.shard_num: sh.latest_offset
                        for sh in self.memstore.shards(ds)}

            self.status_poller = StatusPoller(
                self.manager, self.failure_detector, peers, self.node,
                interval_s=float(self.config.get(
                    "status-poll-interval-s", 2.0)),
                on_assignment_change=resync_all,
                local_running=self._running_shards,
                local_watermarks=local_watermarks,
                tier_watermarks=self.tier_watermarks)
            self.status_poller.start()
        if self.insights_fleet is not None:
            # AFTER http.start(): peers answer /admin/insights only
            # once their server is up, and start() no-ops peerless
            self.insights_fleet.start()
        if self.config.get("profiler"):
            self.profiler = SimpleProfiler()
            self.profiler.start()
        self._started.set()
        return port

    def _ageout_loop(self, retention_ms: int, tick_s: float) -> None:
        """Background retention passes over every dataset (tier
        datasets included — each tier dataset gets its OWN age-out
        watermark, the per-tier retention floor the resolution router
        stitches at).  A failed pass logs and retries next tick; the
        failed shard's watermark never advances past unarchived data."""
        import logging
        log = logging.getLogger("filodb.coldstore")
        only = set((self.config.get("coldstore") or {})
                   .get("datasets") or ())
        while not self._ageout_stop.wait(tick_s):
            for ds in list(self.manager.datasets()):
                if only and ds not in only:
                    continue
                if self._ageout_stop.is_set():
                    return
                try:
                    self.ageout.run(ds, retention_ms)
                except Exception:  # noqa: BLE001 — keep the loop alive
                    log.exception("cold-tier age-out pass failed for %s "
                                  "(will retry next tick)", ds)

    def _setup_insights(self) -> None:
        """Fleet workload insights (ISSUE 19, doc/observability.md):
        the per-fingerprint workload ledger, the declarative tenant SLO
        tracker, and the fleet aggregator that merges peers' raw
        snapshots into /admin/fleet.  Always on (the ledger is a few
        hundred KB of ints); ``insights.enabled: false`` or the runtime
        knob turns the per-query accounting off."""
        conf = self.config.get("insights") or {}
        from filodb_tpu.insights.ledger import WorkloadLedger
        from filodb_tpu.utils.observability import insights_metrics
        ledger = WorkloadLedger(
            node=self.node,
            max_entries=int(conf.get("max-entries", 512)),
            co_window_ms=float(conf.get("co-arrival-window-ms", 250.0)),
            enabled=bool(conf.get("enabled", True)))
        self.http.insights = ledger
        # resident-fingerprint gauge as a set_fn: the row exists (at 0)
        # from startup, so dashboards and rules see the ramp, not a
        # label set born mid-incident
        insights_metrics()["fingerprints"].set_fn(ledger.fingerprints,
                                                  node=self.node)
        slo_conf = conf.get("slo") or {}
        objectives = []
        from filodb_tpu.insights.slo import SloObjective, SloTracker
        for i, obj in enumerate(slo_conf.get("objectives") or []):
            objectives.append(SloObjective.from_config(obj, i))
        if objectives:
            self.slo_tracker = SloTracker(
                objectives, node=self.node,
                fast_window_s=float(slo_conf.get("fast-window-s", 300.0)),
                slow_window_s=float(slo_conf.get("slow-window-s",
                                                 3600.0)))
            self.http.slo = self.slo_tracker
        from filodb_tpu.insights.fleet import FleetAggregator
        # fleet-poll-interval-s <= 0 (the default) = on-demand: no
        # background peer chatter; each /admin/fleet read polls.  Set
        # it > 0 to keep the console cache warm between reads.
        self.insights_fleet = FleetAggregator(
            self.node, self.config.get("peers", {}),
            self.http._insights_raw,
            interval_s=float(conf.get("fleet-poll-interval-s", 0.0)),
            timeout_s=float(conf.get("fleet-poll-timeout-s", 2.0)),
            stale_after_s=float(conf.get("fleet-stale-after-s", 60.0)))
        self.http.fleet = self.insights_fleet

    def _setup_rules(self, selfscrape_conf: dict) -> None:
        """Rule engine (ISSUE 9, doc/rules.md): inline groups + rule
        files + the shipped self-monitoring pack (on whenever
        self-scrape is on).  A broken rule config refuses startup —
        silently running a subset of the configured rules is worse
        than not starting."""
        rules_conf = self.config.get("rules") or {}
        from filodb_tpu.rules.config import (load_rule_config,
                                             load_rule_file)
        groups: list = []
        if rules_conf.get("groups"):
            groups.extend(load_rule_config(
                {"groups": rules_conf["groups"]}, source="config"))
        for path in rules_conf.get("files", []):
            groups.extend(load_rule_file(path))
        sm = rules_conf.get("self-monitoring") or {}
        if selfscrape_conf.get("enabled") and sm.get("enabled", True):
            from filodb_tpu.rules.selfmon import selfmon_pack
            groups.extend(load_rule_config(
                selfmon_pack(
                    interval=str(sm.get("interval", "15s")),
                    for_=str(sm.get("for", "30s")),
                    dataset=selfscrape_conf.get("dataset", "_system"),
                    window=str(sm.get("window", "2m"))),
                source="builtin:self-monitoring"))
        # tenant SLO burn alerts (ISSUE 19): shipped whenever SLO
        # objectives are configured AND self-scrape feeds filodb_slo_*
        # into a queryable dataset (the burn gauges ride the same
        # exposition the selfmon pack evaluates against)
        slo_rules = rules_conf.get("slo-burn") or {}
        if selfscrape_conf.get("enabled") and self.http.slo is not None \
                and slo_rules.get("enabled", True):
            from filodb_tpu.rules.selfmon import slo_pack
            groups.extend(load_rule_config(
                slo_pack(
                    interval=str(slo_rules.get("interval", "15s")),
                    for_=str(slo_rules.get("for", "30s")),
                    dataset=selfscrape_conf.get("dataset", "_system")),
                source="builtin:slo-burn"))
        if not groups:
            return
        nconf = rules_conf.get("notifier") or {}
        if nconf.get("url"):
            from filodb_tpu.rules.notifier import WebhookNotifier
            self.rule_notifier = WebhookNotifier(
                nconf["url"],
                timeout_s=float(nconf.get("timeout-s", 5.0)),
                retries=int(nconf.get("retries", 3)),
                backoff_s=float(nconf.get("backoff-s", 0.25)))
        from filodb_tpu.rules.engine import RuleEngine
        ds_names = [d["name"] for d in self.config.get("datasets", [])]
        self.rule_engine = RuleEngine(
            groups,
            binding_for=self.http.datasets.get,
            publisher_for=self.write_publishers.get,
            default_dataset=ds_names[0] if ds_names else "",
            notifier=self.rule_notifier,
            node=self.node,
            incremental=bool(rules_conf.get("incremental", True)))
        self.http.rules = self.rule_engine
        self.rule_engine.start()

    def _setup_dataset(self, ds_conf: dict) -> None:
        name = ds_conf["name"]
        num_shards = int(ds_conf.get("num-shards", 4))
        spread = int(ds_conf.get("spread", 1))
        store_cfg = StoreConfig.from_config(ds_conf.get("store", {}))
        if hasattr(self.metastore, "write_dataset"):
            self.metastore.write_dataset(name, json.dumps(ds_conf))

        # per-dataset source: "broker"/"kafka" reads topic partitions from
        # a message broker (reference: sourcefactory =
        # KafkaIngestionStreamFactory); default is the in-proc queue
        source_conf = dict(ds_conf.get("source", {}))
        factory_name = source_conf.pop("factory", None)
        broker_producer = None
        if factory_name in ("broker", "kafka"):
            from filodb_tpu.ingest.broker import (BrokerClient,
                                                  BrokerIngestionStreamFactory,
                                                  BrokerProducer)
            if self.broker is not None:
                source_conf.setdefault("port", self.broker.port)
            ds_factory = BrokerIngestionStreamFactory(
                topic=source_conf.pop("topic", name), **source_conf)
            # shard -> partition folds modulo the topic's creation-time
            # partition count: a live split doubles SERVING shards while
            # child s+N keeps consuming partition s (ISSUE 13)
            ds_factory.base_partitions = num_shards
            client = BrokerClient(ds_factory.host, ds_factory.port)
            broker_producer = BrokerProducer(client, ds_factory.topic or name,
                                             num_shards)
        elif factory_name is not None:
            from filodb_tpu.ingest.stream import source_factory
            ds_factory = source_factory(factory_name, **source_conf)
        else:
            ds_factory = self.stream_factory

        rf = int(ds_conf.get("replication-factor", 1))
        self.manager.setup_dataset(name, num_shards,
                                   int(ds_conf.get("min-num-nodes", 1)),
                                   replication_factor=rf)
        mapper = self.manager.mapper(name)
        source_is_broker = factory_name in ("broker", "kafka")
        self._transports[name] = "broker" if source_is_broker else "queue"
        self._spreads[name] = spread
        # a persisted in-flight split re-applies its topology NOW, so
        # the resync below already sees children + split policy
        self.split_controller.restore_dataset(name)
        ic = self.coordinator.setup_dataset(
            name, DEFAULT_SCHEMAS, ds_factory, store_cfg,
            event_sink=self.manager.publish_event,
            # recovery promotion gate (ISSUE 7): a rejoining replica is
            # promoted only once it reaches the group's gossiped head.
            # BROKER sources only: replicas share one partition log, so
            # their offsets are comparable.  Queue-transport replicas
            # number their own independent queues (deliveries dropped
            # while a node was down leave a permanent gap), so gating
            # on a peer's offset would wedge a rejoined node in
            # Recovery forever — they promote at the local checkpoint
            # head instead (best-effort transport, doc/ha.md).
            group_head_fn=(lambda shard, _m=mapper: _m.group_head(shard))
            if rf > 1 and source_is_broker else None)
        shards = self.split_controller.startable_shards(
            name, mapper.runnable_shards_for_node(self.node))
        ic.resync(shards)
        # workload management (ISSUE 5): admission + quota + dispatch
        # tuning from the per-dataset "workload" block
        wl_conf = dict(ds_conf.get("workload", {}))
        # peers: node -> http endpoint; shards owned by peers dispatch
        # remotely (reference: ActorPlanDispatcher per shard owner)
        peers = self.config.get("peers", {})
        disp = None
        if peers:
            from filodb_tpu.coordinator.dispatch import dispatcher_factory
            disp = dispatcher_factory(mapper, peers, local_node=self.node,
                                      dispatch_config=wl_conf.get(
                                          "dispatch"))
        # ICI-collective serving: fuse local multi-shard aggregates into
        # one SPMD mesh program.  Auto-on when >1 device is visible
        # (multi-chip); override per dataset with "mesh": true/false.
        mesh_conf = ds_conf.get("mesh")
        mesh_provider = None
        if mesh_conf or (mesh_conf is None and self._device_count() > 1):
            from filodb_tpu.parallel.mesh import default_engine
            mesh_provider = default_engine
        # mesh query fabric (ISSUE 18): when every child shard of an
        # aggregate is mesh-resident here, the plan root is ONE fused
        # device program (scan -> window -> aggregate -> cross-shard
        # psum -> present).  "mesh-fused": false pins the PR 17 shape
        # (mesh partials + host reduce) without turning the mesh off.
        mesh_fused = bool(ds_conf.get("mesh-fused", True))
        # per-shard-key spread overrides (reference: filodb-defaults
        # `spread-assignment`): "spread-assignment":
        #   [{"keys": {"_ws_": "demo", "_ns_": "App-0"}, "spread": 3}]
        spread_provider = None
        if ds_conf.get("spread-assignment"):
            from filodb_tpu.coordinator.planner import \
                spread_provider_from_config
            spread_provider = spread_provider_from_config(
                ds_conf["spread-assignment"], spread)
        planner = SingleClusterPlanner(name, mapper, DatasetOptions(),
                                       spread_default=spread,
                                       spread_provider=spread_provider,
                                       dispatcher_for_shard=disp,
                                       mesh_engine_provider=mesh_provider,
                                       mesh_fused=mesh_fused)
        # query-frontend result cache (ISSUE 12): the wrapper is always
        # installed (a disabled cache is one boolean per materialize)
        # so POST /admin/config can enable it at runtime; it sits BELOW
        # the rollup router on purpose — tier selection stays upstream,
        # and each tier dataset's own wrapper memoizes its segments
        rc_conf = self.config.get("result-cache") or {}
        from filodb_tpu.http.model import parse_duration_ms
        from filodb_tpu.query.resultcache import (ResultCache,
                                                  ResultCachingPlanner)
        cache = ResultCache(
            name,
            max_bytes=int(rc_conf.get("max-bytes", 64 * 1024 * 1024)),
            enabled=bool(rc_conf.get("enabled", False)))
        seg_ms = parse_duration_ms(rc_conf["segment"]) \
            if "segment" in rc_conf else store_cfg.flush_interval_ms
        planner = ResultCachingPlanner(
            name, planner, self.memstore, cache, segment_ms=seg_ms,
            routing_token_fn=mapper.routing_token,
            instant=bool(rc_conf.get("instant", True)))
        self.result_caches[name] = cache
        schema = DEFAULT_SCHEMAS[ds_conf.get("schema", "gauge")]
        peers_conf = self.config.get("peers", {})
        if broker_producer is not None:
            # the broker's shared partition log IS the replicated
            # stream: one produce, every replica consumes at its own
            # offset (reference: Kafka replicated ingest)
            publish = broker_producer.publish
        elif rf > 1 and peers_conf:
            # queue transport + replicas: dual-write each container to
            # every replica — local queue for this node, the peers'
            # POST /ingest container edge for the rest (ISSUE 7)
            from filodb_tpu.gateway.server import (ReplicaFanout,
                                                   http_container_push)
            self._queue_push_datasets.add(name)
            per_node = {self.node:
                        (lambda s, c, _n=name:
                         self.stream_factory.stream_for(_n, s).push(c))}
            for peer, endpoint in peers_conf.items():
                if peer != self.node:
                    per_node[peer] = http_container_push(endpoint, name)
            publish = ReplicaFanout(name, mapper, per_node,
                                    local_node=self.node)
            self._replica_fanouts.append(publish)
        else:
            self._queue_push_datasets.add(name)
            publish = lambda s, c, _n=name: self.stream_factory.stream_for(  # noqa: E731
                _n, s).push(c)
        self._publish_fns[name] = publish
        # Prometheus remote-write edge shares the gateway sharding rules
        # (and doubles as the self-telemetry ingest edge, ISSUE 6)
        wpub = ShardingPublisher(schema, mapper, publish, spread=spread)
        self.write_publishers[name] = wpub
        # watermark ledger source: the broker head when this dataset
        # consumes from a broker, the in-proc queue head otherwise
        if self.watermarks is not None:
            if broker_producer is not None:
                # split children consume their parent's partition, so
                # their broker head is the parent partition's (ISSUE 13)
                end_fn = (lambda shard, _c=client, _n=num_shards,
                          _t=ds_factory.topic or name:
                          _c.end_offset(_t, shard % _n))
            elif ds_factory is self.stream_factory:
                end_fn = (lambda shard, _n=name:
                          self.stream_factory.stream_for(
                              _n, shard).end_offset())
            else:
                end_fn = None
            self.watermarks.watch(name, self.memstore, mapper=mapper,
                                  end_offset_fn=end_fn)

        def write_router(labels, ts, vals, _pub=wpub):
            metric = labels.get("__name__", "")
            tags = {k: v for k, v in labels.items() if k != "__name__"}
            for t, v in zip(ts, vals):
                _pub.add_sample(metric, tags, int(t), float(v))
            _pub.flush()

        # bounded query scheduler per dataset (reference: QueryActor's
        # priority mailbox + dedicated query pool)
        from filodb_tpu.query.scheduler import QueryScheduler
        qconf = ds_conf.get("query", {})
        qsched = QueryScheduler(
            num_workers=int(qconf.get("workers", 4)),
            max_queued=int(qconf.get("max-queued", 256)),
            name=f"query-{name}")
        # dispatched leaf plans get their own pool: coordinator queries
        # block on remote leaves, so a shared pool would deadlock
        leaf_sched = QueryScheduler(
            num_workers=int(qconf.get("leaf-workers",
                                      qconf.get("workers", 4))),
            max_queued=int(qconf.get("max-queued", 256)),
            name=f"leaf-{name}")
        self.query_schedulers[name] = qsched
        self.query_schedulers[f"{name}/leaf"] = leaf_sched
        # cost-based admission in front of the scheduler (ISSUE 5):
        # present by default — a node with no overload defense is the
        # failure mode this subsystem exists to close; "admission":
        # {"enabled": false} opts out
        adm_conf = dict(wl_conf.get("admission", {}))
        admission = None
        if adm_conf.get("enabled", True):
            from filodb_tpu.workload.admission import AdmissionController
            from filodb_tpu.workload.cost import CostModel
            admission = AdmissionController(
                CostModel(),
                dataset=name,
                max_inflight_cost=float(
                    adm_conf.get("max-inflight-cost", 10_000.0)),
                priority_shares=adm_conf.get("priority-shares"),
                tenant_max_concurrent=int(
                    adm_conf.get("tenant-max-concurrent", 32)),
                tenant_max_inflight_cost=adm_conf.get(
                    "tenant-max-cost"),
                workers=int(qconf.get("workers", 4)))
            self.admission_controllers[name] = admission
        # active-series cardinality quota, shared by every local shard
        # of this dataset and the gateway edge (workload/quota.py)
        quota = None
        q_conf = wl_conf.get("quota")
        if q_conf:
            from filodb_tpu.workload.quota import SeriesQuota
            quota = SeriesQuota(
                dataset=name,
                tenant_label=q_conf.get("tenant-label", "_ns_"),
                default_limit=q_conf.get("default-max-series"),
                overrides=q_conf.get("overrides"))
            for sh in self.memstore.shards(name):
                sh.series_quota = quota
            quota.refresh_from_index(
                *(sh.index for sh in self.memstore.shards(name)))
            wpub.quota = quota
        # fleet batching tier (ISSUE 20, filodb_tpu/batching): one
        # QueryBatcher per dataset, attached to every local shard —
        # the device stores offer eligible dispatches to it, so
        # concurrent shape-compatible queries share ONE vmapped launch.
        # On by default ("batching": {"enabled": false} opts out); the
        # ledger resolves lazily because _setup_insights runs after
        # datasets bind.
        bat_conf = dict(ds_conf.get("batching",
                                    self.config.get("batching", {})))
        from filodb_tpu.batching import QueryBatcher
        batcher = QueryBatcher(
            enabled=bool(bat_conf.get("enabled", True)),
            window_ms=float(bat_conf.get("window-ms", 3.0)),
            max_batch=int(bat_conf.get("max-batch", 8)),
            hot_ttl_s=float(bat_conf.get("hot-ttl-s", 10.0)),
            dataset=name,
            ledger=lambda: self.http.insights)
        for sh in self.memstore.shards(name):
            sh.query_batcher = batcher
        # tiered-resolution serving (ISSUE 11, doc/rollup.md): stand up
        # the <ds>_ds_<res> tier datasets as REAL datasets (replicated,
        # flushed through the checksummed store, queryable), wire the
        # rollup engine over this dataset's flush stream, and wrap the
        # serving planner in the resolution router
        planner = self._setup_rollup(ds_conf, name, num_shards, spread, rf,
                                     mapper, schema, planner, admission)
        self.http.bind_dataset(DatasetBinding(name, self.memstore, planner,
                                              write_router=write_router,
                                              scheduler=qsched,
                                              leaf_scheduler=leaf_sched,
                                              admission=admission,
                                              quota=quota,
                                              resultcache=cache,
                                              batcher=batcher))

        gw_port = ds_conf.get("gateway-port")
        if gw_port is None and not self._global_gateway_claimed:
            # the top-level port can serve exactly one dataset; additional
            # datasets need their own gateway-port
            gw_port = self.config.get("gateway-port")
            if gw_port is not None:
                self._global_gateway_claimed = True
        if gw_port is not None:
            pub = ShardingPublisher(schema, mapper, publish, spread=spread,
                                    quota=quota)
            gw = GatewayServer(pub, port=int(gw_port))
            gw.start()
            self.gateways.append(gw)

    def _setup_rollup(self, ds_conf: dict, name: str, num_shards: int,
                      spread: int, rf: int, mapper, schema, planner,
                      admission):
        """Per-dataset rollup wiring (ISSUE 11).  Returns the serving
        planner — the resolution router when rollup is enabled, the
        original planner otherwise.  A broken rollup block refuses
        startup, like a broken rule config."""
        ro_conf = ds_conf.get("rollup")
        if ro_conf is None or ds_conf.get("_rollup_tier") \
                or not ro_conf.get("enabled", True):
            return planner
        from filodb_tpu.rollup.config import (RollupConfig,
                                              RollupConfigError)
        # self-downsampling schemas (prom-counter / prom-histogram roll
        # into their own shape, schemas.py) carry downsample=None but a
        # downsample_schema NAME — they tier since ISSUE 14
        if schema.downsample is None \
                and not (schema.data.downsamplers
                         and schema.data.downsample_schema):
            raise RollupConfigError(
                f"dataset {name!r} (schema {ds_conf.get('schema')!r}) "
                f"has no downsample schema — rollup cannot tier it")
        cfg = RollupConfig.from_config(ro_conf)
        from filodb_tpu.downsample.dsstore import ds_dataset_name
        # tier datasets split in LOCKSTEP with their source (ISSUE 13):
        # the SplitController doubles them in the same phase machine
        self._tiers[name] = [ds_dataset_name(name, r)
                             for r in cfg.resolutions_ms]
        tier_planners: dict[int, object] = {}
        publish_for: dict[int, object] = {}
        tier_schema = schema.data.downsample_schema \
            or ds_conf.get("schema", "gauge")
        for res in cfg.resolutions_ms:
            tname = ds_dataset_name(name, res)
            if tname not in self.manager.datasets():
                # tier datasets never claim the node's global gateway
                # port (the _system-dataset discipline) and always use
                # the in-proc queue transport: at rf>1 the generic
                # queue+peers branch gives them the PR 12 ReplicaFanout
                # dual-write, broker or not
                claimed = self._global_gateway_claimed
                self._global_gateway_claimed = True
                try:
                    self._setup_dataset({
                        "name": tname, "num-shards": num_shards,
                        "min-num-nodes": int(
                            ds_conf.get("min-num-nodes", 1)),
                        "schema": tier_schema, "spread": spread,
                        "replication-factor": rf,
                        "store": ro_conf.get("store",
                                             ds_conf.get("store", {})),
                        "query": ro_conf.get("query", {"workers": 2}),
                        "_rollup_tier": True})
                finally:
                    self._global_gateway_claimed = claimed
            tier_planners[res] = self.http.datasets[tname].planner
            publish_for[res] = self._publish_fns[tname]
        if self.rollup_engine is None:
            from filodb_tpu.rollup.engine import RollupEngine
            self.rollup_engine = RollupEngine(node=self.node)
            self.http.rollup = self.rollup_engine
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        self.rollup_engine.watch(
            name, self.memstore, DEFAULT_SCHEMAS, cfg, publish_for,
            column_store=self.colstore, meta_store=self.metastore,
            # only the shard's primary replica rolls it (the raw data
            # is identical on every replica; the EMITTED containers
            # replicate through the tier publish path — two emitters
            # would double-publish every record)
            owner_fn=(lambda s, _m=mapper, _n=self.node:
                      _m.coord_for_shard(s) == _n),
            admission=admission)
        from filodb_tpu.rollup.planner import RollupRouterPlanner

        def cluster_rolled_through(res: int, _e=self.rollup_engine,
                                   _n=name, _m=mapper,
                                   _tw=self.tier_watermarks,
                                   _node=self.node) -> int:
            """Cluster-wide stitch boundary (ROADMAP 2b): min over the
            shard owners' GOSSIPED closure watermarks — each owner is
            authoritative for the shards it rolls, so intra-shard
            series skew on peer shards can no longer open silent holes
            the delivered-stamp proxy missed, and a coordinator that
            owns no primaries can route rolled at all.  Still clamped
            by what the LOCAL tier replicas have had delivered (a
            boundary past undelivered data would stitch into a hole);
            any owner without gossip yet degrades to the local
            engine's conservative boundary, exactly the pre-gossip
            behavior."""
            local = _e.rolled_through(_n, res)
            owners = {_m.coord_for_shard(s)
                      for s in range(_m.num_shards)}
            peer_owners = owners - {_node, None}
            if not peer_owners:
                return local
            peer_min = _tw.cluster_min(_n, res, peer_owners)
            if peer_min is None:
                return local
            owned = _e.owned_rolled_through(_n, res)
            if _node in owners and owned is None:
                # this node rolls shards but its engine has not
                # computed a closure yet (pre-first-pass / restart):
                # None means "unknown", not "owns nothing" — trusting
                # peer_min alone would stitch past the local shards'
                # actual closure
                return local
            vals = [peer_min] + ([owned] if owned is not None else [])
            delivered = _e.delivered_through(_n, res)
            if delivered is not None:
                vals.append(delivered)
            elif self.memstore.shards(ds_dataset_name(_n, res)):
                # this node HOLDS tier replicas but nothing has been
                # delivered yet (restart window): a boundary past the
                # empty local tier data would stitch into a hole
                return local
            return min(vals)

        cold_floor = None
        if self.ageout is not None:
            # rolled-local / rolled-cold stitch boundary (ISSUE 16):
            # the TIER dataset's age-out floor — 0 until a pass
            # completes on every shard, so the cold leg only appears
            # once data is guaranteed archived
            def cold_floor(res: int, _a=self.ageout, _n=name) -> int:
                return _a.floor_ms(ds_dataset_name(_n, res))

        return RollupRouterPlanner(
            name, planner, tier_planners,
            rolled_through_fn=cluster_rolled_through,
            raw_retention_ms=cfg.raw_retention_ms,
            cold_floor_fn=cold_floor)

    def flush_all(self) -> int:
        n = 0
        for ds in self.manager.datasets():
            for sh in self.memstore.shards(ds):
                n += sh.flush_all()
        return n

    def shutdown(self) -> None:
        # stop the age-out loop FIRST: a migration pass mid-flight must
        # finish its current shard before the stores close under it
        self._ageout_stop.set()
        if self._ageout_thread is not None:
            self._ageout_thread.join(timeout=30)
        self.split_controller.stop()
        if self.rule_engine is not None:
            # stops the group loops AND closes the notifier — a dead
            # node must not keep evaluating or POSTing webhooks
            self.rule_engine.stop()
        if self.rollup_engine is not None:
            # stops the tier loops and removes the exported lag/stall
            # gauge rows — a dead node's stalled=1 must not feed the
            # self-monitoring alerts forever
            self.rollup_engine.stop()
        if self.watermark_sampler is not None:
            self.watermark_sampler.stop()
        if self.insights_fleet is not None:
            self.insights_fleet.stop()
        if self.selfscraper is not None:
            self.selfscraper.stop()
        if self.status_poller is not None:
            self.status_poller.stop()
        for gw in self.gateways:
            gw.shutdown()
        for fanout in self._replica_fanouts:
            fanout.close()
        self.coordinator.shutdown()
        self.http.shutdown()
        if self.watermarks is not None:
            # drop this node's exported watermark/stall gauge rows — a
            # dead node's stalled=1 must not feed alerting rules
            # forever.  AFTER http.shutdown(): a late /admin/shards
            # request would otherwise re-watch the emptied ledger and
            # resurrect the just-removed rows permanently
            self.watermarks.close()
        # same discipline for the insights/SLO gauge rows: AFTER
        # http.shutdown(), so no late query can re-register them
        if self.slo_tracker is not None:
            self.slo_tracker.close()
        if self.http.insights is not None:
            from filodb_tpu.utils.observability import insights_metrics
            insights_metrics()["fingerprints"].remove(node=self.node)
        for qs in self.query_schedulers.values():
            qs.shutdown()
        for ac in self.admission_controllers.values():
            ac.shutdown()
        if self.broker is not None:
            self.broker.shutdown()
        if self.profiler is not None:
            self.profiler.stop()
        self.colstore.shutdown()
        self.metastore.shutdown()


def main(argv=None) -> int:
    # epoch-ms timestamps need int64 end to end; on CPU hosts x64 must be
    # enabled explicitly (TPU kernels rebase to int32 offsets internally)
    import jax
    jax.config.update("jax_enable_x64", True)

    args = argv if argv is not None else sys.argv[1:]
    if not args:
        print("usage: python -m filodb_tpu.standalone <config.json>",
              file=sys.stderr)
        return 2
    with open(args[0]) as f:
        config = json.load(f)
    server = FiloServer(config)
    port = server.start()
    print(f"FiloDB-TPU node {server.node} up: http={port} "
          f"datasets={server.manager.datasets()}")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
