"""Bloom filter over byte keys, numpy-bitmap backed.

Stands in for the reference's evicted-partkey bloom filter
(reference: core/.../TimeSeriesShard.scala:418-424 evictedPartKeys,
``bloomfilter.mutable.BloomFilter`` with configured capacity), used to
decide whether a newly seen part key might have been evicted (and so needs
an index/column-store lookup before re-creation).
"""

from __future__ import annotations

import hashlib

import numpy as np


class BloomFilter:
    def __init__(self, capacity: int, error_rate: float = 0.01) -> None:
        # standard sizing: m = -n ln(p) / (ln 2)^2, k = m/n ln 2
        n = max(capacity, 1)
        m = int(-n * np.log(error_rate) / (np.log(2) ** 2))
        self._bits = np.zeros((m + 63) // 64, dtype=np.uint64)
        self._m = max(m, 64)
        self._k = max(int(round(m / n * np.log(2))), 1)
        self.count = 0

    def _positions(self, key: bytes) -> np.ndarray:
        d = hashlib.blake2b(key, digest_size=16).digest()
        h1 = int.from_bytes(d[:8], "little")
        h2 = int.from_bytes(d[8:], "little") | 1
        return np.array([(h1 + i * h2) % self._m for i in range(self._k)],
                        dtype=np.uint64)

    def add(self, key: bytes) -> None:
        for p in self._positions(key):
            self._bits[int(p) >> 6] |= np.uint64(1) << np.uint64(int(p) & 63)
        self.count += 1

    def __contains__(self, key: bytes) -> bool:
        for p in self._positions(key):
            if not (self._bits[int(p) >> 6] >> np.uint64(int(p) & 63)) & np.uint64(1):
                return False
        return True
