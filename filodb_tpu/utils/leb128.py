"""Unsigned LEB128 varints — shared by the snappy block codec and the
protobuf wire codec (both formats use the same base-128 encoding)."""

from __future__ import annotations


def encode(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode(buf: bytes, pos: int, max_shift: int = 70) -> tuple[int, int]:
    """Returns (value, next_pos).  ``max_shift`` bounds the encoding at
    10 bytes (enough for any uint64)."""
    val = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > max_shift:
            raise ValueError("varint too long")
