"""Shared utilities: bloom filter, metrics registry, scheduling helpers."""
