"""Observability: metrics registry, tracing spans, sampling profiler.

Capability match for the reference's Kamon-based instrumentation
(reference: coordinator/.../KamonLogger.scala:146 metric/span log
reporters; Kamon.spanBuilder use throughout ExecPlan.execute
ExecPlan.scala:99-126 and flush TimeSeriesShard.scala:888-891;
core/.../Perftools.scala:53 timing spans; standalone/.../
SimpleProfiler.java sampling profiler launched at server start).

Everything is stdlib: counters/gauges/histograms with Prometheus text
exposition (replacing Kamon's embedded Prometheus server), thread-local
span stacks with a pluggable reporter, and a sys._current_frames-based
sampling profiler."""

from __future__ import annotations

import collections
import dataclasses
import sys
import threading
import time
import traceback
from typing import Callable, Mapping, Optional, Sequence

# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0)


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = collections.defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += amount

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        """Sum across every label set (admin summaries)."""
        with self._lock:
            return sum(self._values.values())

    def expose(self) -> list[str]:
        with self._lock:  # concurrent inc() may insert new label sets
            items = sorted(self._values.items())
        out = [f"# TYPE {self.name} counter"]
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_val(v)}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._fns: dict[tuple, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def set_fn(self, fn: Callable[[], float], **labels) -> None:
        """Lazily-sampled gauge (e.g. memory usage at scrape time)."""
        with self._lock:
            self._fns[tuple(sorted(labels.items()))] = fn

    def value(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        if key in self._fns:
            return float(self._fns[key]())
        return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum across every label set (admin summaries)."""
        with self._lock:
            return sum(self._values.values()) + \
                sum(fn() for fn in self._fns.values())

    def expose(self) -> list[str]:
        out = [f"# TYPE {self.name} gauge"]
        with self._lock:
            items = list(self._values.items()) + \
                [(k, fn()) for k, fn in self._fns.items()]
        for key, v in sorted(items):
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_val(v)}")
        return out


class Histogram:
    """Cumulative-bucket histogram (seconds by convention)."""

    def __init__(self, name: str, help_: str = "",
                 buckets: Sequence[float] = _BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = collections.defaultdict(float)
        self._totals: dict[tuple, int] = collections.defaultdict(int)
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def expose(self) -> list[str]:
        with self._lock:  # concurrent observe() may insert new label sets
            counts = {k: list(v) for k, v in self._counts.items()}
            sums = dict(self._sums)
            totals = dict(self._totals)
        out = [f"# TYPE {self.name} histogram"]
        for key in sorted(counts):
            for i, b in enumerate(self.buckets):
                lk = key + (("le", repr(b)),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} "
                           f"{counts[key][i]}")
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {totals[key]}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} "
                       f"{_fmt_val(sums[key])}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {totals[key]}")
        return out


def _escape_label(v) -> str:
    """Prometheus exposition escaping: backslash, quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_val(v: float) -> str:
    import math
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return str(int(v)) if v == int(v) else repr(v)


class MetricsRegistry:
    """Process-wide named metrics + Prometheus text exposition (replaces
    Kamon's metric registry + embedded Prometheus reporter)."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = _BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, buckets),
                         Histogram)

    def _get(self, name, ctor, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = ctor()
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as "
                                f"{type(m).__name__}")
            return m

    def expose_text(self) -> str:
        """Prometheus text format for a /metrics endpoint."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()


def integrity_metrics() -> dict:
    """Canonical integrity counters (filodb_tpu/integrity): one place
    defines the metric names so the corruption funnel, the /metrics
    exposition, and /admin/integrity can never drift apart.  Labels:
    ``dataset``/``shard`` when the detection site knows them."""
    return {
        "checksum_failures": REGISTRY.counter(
            "filodb_integrity_checksum_failures_total",
            "chunk blobs whose stored CRC32C did not match on read-back"),
        "decode_failures": REGISTRY.counter(
            "filodb_integrity_decode_failures_total",
            "chunk vectors whose native/numpy decode hit a -1 sentinel"),
        "chunks_verified": REGISTRY.counter(
            "filodb_integrity_chunks_verified_total",
            "chunk blobs checksum-verified on page-in/read-back"),
        "chunks_quarantined": REGISTRY.gauge(
            "filodb_integrity_quarantined_chunks",
            "chunks currently excluded from serving by the quarantine"),
        "invariant_failures": REGISTRY.counter(
            "filodb_integrity_invariant_failures_total",
            "eviction/reclaim bookkeeping invariant violations"),
        "partial_queries": REGISTRY.counter(
            "filodb_integrity_partial_query_results_total",
            "queries answered with a partial-data warning"),
    }


# ---------------------------------------------------------------------------
# Tracing spans
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SpanRecord:
    name: str
    start_s: float
    duration_s: float
    tags: dict
    parent: Optional[str]
    error: Optional[str] = None


class Tracer:
    """Thread-local span stack + pluggable reporters (replaces Kamon
    span propagation via Kamon.runWithSpan)."""

    def __init__(self) -> None:
        self._local = threading.local()
        self._reporters: list[Callable[[SpanRecord], None]] = []
        self._lock = threading.Lock()

    def add_reporter(self, fn: Callable[[SpanRecord], None]) -> None:
        with self._lock:
            self._reporters.append(fn)

    def clear_reporters(self) -> None:
        with self._lock:
            self._reporters = []

    def current_span(self) -> Optional[str]:
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    def span(self, name: str, **tags):
        return _Span(self, name, tags)

    def _report(self, rec: SpanRecord) -> None:
        with self._lock:
            reporters = list(self._reporters)
        for fn in reporters:
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — reporters must not break work
                traceback.print_exc()


class _Span:
    def __init__(self, tracer: Tracer, name: str, tags: dict):
        self.tracer = tracer
        self.name = name
        self.tags = tags
        self._t0 = 0.0

    def __enter__(self):
        stack = getattr(self.tracer._local, "stack", None)
        if stack is None:
            stack = self.tracer._local.stack = []
        self.parent = stack[-1] if stack else None
        stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def tag(self, **tags):
        self.tags.update(tags)
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        self.tracer._local.stack.pop()
        self.tracer._report(SpanRecord(
            self.name, time.time() - dur, dur, dict(self.tags), self.parent,
            error=repr(exc) if exc is not None else None))
        return False


TRACER = Tracer()


def span_log_reporter(log: Callable[[str], None] = print,
                      min_duration_s: float = 0.0):
    """Span -> log line reporter (reference: KamonSpanLogReporter)."""

    def report(rec: SpanRecord) -> None:
        if rec.duration_s >= min_duration_s:
            tags = " ".join(f"{k}={v}" for k, v in rec.tags.items())
            err = f" ERROR={rec.error}" if rec.error else ""
            log(f"span {rec.name} {rec.duration_s * 1000:.2f}ms "
                f"parent={rec.parent} {tags}{err}")
    return report


# ---------------------------------------------------------------------------
# Sampling profiler
# ---------------------------------------------------------------------------


class SimpleProfiler:
    """Background stack-sampling profiler (reference:
    standalone/src/main/java/filodb/standalone/SimpleProfiler.java —
    samples thread stacks periodically, aggregates hottest frames, and
    reports every interval)."""

    def __init__(self, sample_interval_s: float = 0.01,
                 report_interval_s: float = 60.0,
                 top_k: int = 20,
                 report_fn: Optional[Callable[[str], None]] = None):
        self.sample_interval_s = sample_interval_s
        self.report_interval_s = report_interval_s
        self.top_k = top_k
        self.report_fn = report_fn or print
        self._counts: collections.Counter = collections.Counter()
        self._samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="profiler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        own = threading.get_ident()
        next_report = time.monotonic() + self.report_interval_s
        while not self._stop.wait(self.sample_interval_s):
            frames = sys._current_frames()
            with self._lock:
                self._samples += 1
                for tid, frame in frames.items():
                    if tid == own:
                        continue
                    code = frame.f_code
                    self._counts[(code.co_filename, code.co_name)] += 1
            if time.monotonic() >= next_report:
                self.report_fn(self.report())
                next_report = time.monotonic() + self.report_interval_s

    def report(self) -> str:
        with self._lock:
            total = self._samples or 1
            top = self._counts.most_common(self.top_k)
        lines = [f"profiler: {self._samples} samples"]
        for (fname, func), n in top:
            short = fname.rsplit("/", 1)[-1]
            lines.append(f"  {100.0 * n / total:5.1f}% {short}:{func}")
        return "\n".join(lines)

    def snapshot(self) -> Mapping:
        with self._lock:
            return dict(self._counts)
