"""Observability: metrics registry, tracing spans, sampling profiler.

Capability match for the reference's Kamon-based instrumentation
(reference: coordinator/.../KamonLogger.scala:146 metric/span log
reporters; Kamon.spanBuilder use throughout ExecPlan.execute
ExecPlan.scala:99-126 and flush TimeSeriesShard.scala:888-891;
core/.../Perftools.scala:53 timing spans; standalone/.../
SimpleProfiler.java sampling profiler launched at server start).

Everything is stdlib: counters/gauges/histograms with Prometheus text
exposition (replacing Kamon's embedded Prometheus server), thread-local
span stacks with a pluggable reporter, and a sys._current_frames-based
sampling profiler."""

from __future__ import annotations

import bisect
import collections
import contextlib
import dataclasses
import random
import sys
import threading
import time
import traceback
from typing import Callable, Mapping, Optional, Sequence

# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0)


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = collections.defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += amount

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        """Sum across every label set (admin summaries)."""
        with self._lock:
            return sum(self._values.values())

    def expose(self) -> list[str]:
        with self._lock:  # concurrent inc() may insert new label sets
            items = sorted(self._values.items())
        out = [f"# TYPE {self.name} counter"]
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_val(v)}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = {}
        self._fns: dict[tuple, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def set_fn(self, fn: Callable[[], float], **labels) -> None:
        """Lazily-sampled gauge (e.g. memory usage at scrape time)."""
        with self._lock:
            self._fns[tuple(sorted(labels.items()))] = fn

    def value(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        if key in self._fns:
            return float(self._fns[key]())
        return self._values.get(key, 0.0)

    def remove(self, **labels) -> None:
        """Drop one label set (both value and set_fn).  Components that
        register bound-method callbacks MUST call this on shutdown or
        the registry keeps them (and everything they capture) alive and
        keeps exporting rows for dead instances."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._fns.pop(key, None)
            self._values.pop(key, None)

    def total(self) -> float:
        """Sum across every label set (admin summaries).  ``set_fn``
        callbacks run OUTSIDE the gauge lock: a callback that touches
        this same gauge (or blocks on something that does) must not
        deadlock the scrape."""
        with self._lock:
            vals = sum(self._values.values())
            fns = list(self._fns.values())
        return vals + sum(fn() for fn in fns)

    def expose(self) -> list[str]:
        out = [f"# TYPE {self.name} gauge"]
        with self._lock:  # snapshot under the lock, call fns outside it
            vals = list(self._values.items())
            fns = list(self._fns.items())
        items = vals + [(k, fn()) for k, fn in fns]
        for key, v in sorted(items):
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_val(v)}")
        return out


class Histogram:
    """Cumulative-bucket histogram (seconds by convention)."""

    def __init__(self, name: str, help_: str = "",
                 buckets: Sequence[float] = _BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        # per-bucket RAW counts (one extra slot for > last bucket);
        # observe() is on every hot path, so it does ONE bisect + ONE
        # increment — the cumulative le-counts are computed at expose()
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = collections.defaultdict(float)
        self._totals: dict[tuple, int] = collections.defaultdict(int)
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        # first bucket b with value <= b (buckets are sorted ascending);
        # len(buckets) = the +Inf overflow slot
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
            counts[i] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def expose(self) -> list[str]:
        with self._lock:  # concurrent observe() may insert new label sets
            counts = {k: list(v) for k, v in self._counts.items()}
            sums = dict(self._sums)
            totals = dict(self._totals)
        out = [f"# TYPE {self.name} histogram"]
        for key in sorted(counts):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[key][i]
                lk = key + (("le", repr(b)),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} {cum}")
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {totals[key]}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} "
                       f"{_fmt_val(sums[key])}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {totals[key]}")
        return out


def _escape_label(v) -> str:
    """Prometheus exposition escaping: backslash, quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_val(v: float) -> str:
    import math
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return str(int(v)) if v == int(v) else repr(v)


class MetricsRegistry:
    """Process-wide named metrics + Prometheus text exposition (replaces
    Kamon's metric registry + embedded Prometheus reporter)."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = _BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, buckets),
                         Histogram)

    def _get(self, name, ctor, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = ctor()
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as "
                                f"{type(m).__name__}")
            return m

    def expose_text(self) -> str:
        """Prometheus text format for a /metrics endpoint."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()


def integrity_metrics() -> dict:
    """Canonical integrity counters (filodb_tpu/integrity): one place
    defines the metric names so the corruption funnel, the /metrics
    exposition, and /admin/integrity can never drift apart.  Labels:
    ``dataset``/``shard`` when the detection site knows them."""
    return {
        "checksum_failures": REGISTRY.counter(
            "filodb_integrity_checksum_failures_total",
            "chunk blobs whose stored CRC32C did not match on read-back"),
        "decode_failures": REGISTRY.counter(
            "filodb_integrity_decode_failures_total",
            "chunk vectors whose native/numpy decode hit a -1 sentinel"),
        "chunks_verified": REGISTRY.counter(
            "filodb_integrity_chunks_verified_total",
            "chunk blobs checksum-verified on page-in/read-back"),
        "chunks_quarantined": REGISTRY.gauge(
            "filodb_integrity_quarantined_chunks",
            "chunks currently excluded from serving by the quarantine"),
        "invariant_failures": REGISTRY.counter(
            "filodb_integrity_invariant_failures_total",
            "eviction/reclaim bookkeeping invariant violations"),
        "partial_queries": REGISTRY.counter(
            "filodb_integrity_partial_query_results_total",
            "queries answered with a partial-data warning"),
    }


def query_metrics() -> dict:
    """Canonical query-pipeline metrics (ISSUE 2): one place defines the
    names so the HTTP layer, scheduler, and docs can never drift."""
    return {
        "request_seconds": REGISTRY.histogram(
            "filodb_query_request_seconds",
            "HTTP route handler latency by endpoint"),
        "requests": REGISTRY.counter(
            "filodb_query_requests_total",
            "HTTP requests by endpoint and status code"),
        "run_seconds": REGISTRY.histogram(
            "filodb_query_run_seconds",
            "query execution time on a scheduler worker (excl. queue)"),
        "slow_queries": REGISTRY.counter(
            "filodb_query_slow_total",
            "completed queries over the slow-query threshold"),
        "execplan_seconds": REGISTRY.histogram(
            "filodb_query_execplan_remote_seconds",
            "remote /execplan leaf execution latency"),
        "hbm_read_bytes": REGISTRY.counter(
            "filodb_query_hbm_read_bytes_total",
            "device-grid HBM bytes read serving queries, by resident "
            "format (label format=dense|compressed)"),
    }


def ingest_metrics() -> dict:
    """Canonical gateway-ingest metrics."""
    return {
        "samples": REGISTRY.counter(
            "filodb_ingest_samples_total",
            "samples accepted by the gateway sharding publisher"),
        "parse_errors": REGISTRY.counter(
            "filodb_ingest_parse_errors_total",
            "malformed influx lines rejected by the gateway"),
        "batch_seconds": REGISTRY.histogram(
            "filodb_ingest_batch_seconds",
            "gateway batch ingest latency (parse -> route -> build)"),
        "replica_publishes": REGISTRY.counter(
            "filodb_ingest_replica_publishes_total",
            "containers delivered per replica by the dual-write fanout"),
        "replica_publish_failures": REGISTRY.counter(
            "filodb_ingest_replica_publish_failures_total",
            "per-replica container deliveries that failed (the replica "
            "lags and must catch up from its checkpoint/broker)"),
    }


def flush_metrics() -> dict:
    """Canonical memstore-flush metrics."""
    return {
        "flush_seconds": REGISTRY.histogram(
            "filodb_flush_seconds",
            "run_flush_task latency (encode + IO + checkpoint)"),
        "chunks": REGISTRY.counter(
            "filodb_flush_chunks_total", "chunksets written by flushes"),
        "failures": REGISTRY.counter(
            "filodb_flush_failures_total",
            "flush tasks that raised (work requeued)"),
        # ISSUE 6 satellite: the pipeline's backlog was never observable
        "queue_depth": REGISTRY.gauge(
            "filodb_flush_queue_depth",
            "flush tasks submitted but not yet completed, per shard"),
        "last_age": REGISTRY.gauge(
            "filodb_flush_last_age_seconds",
            "seconds since the most recent completed flush on any group "
            "of the shard (since scheduler start when none completed)"),
    }


def index_metrics() -> dict:
    """Canonical part-key-index cardinality metrics (ISSUE 6): active
    series, per-tenant occupancy, and series churn — one place defines
    the names so the tracker, /admin/cardinality, and
    doc/observability.md can never drift."""
    return {
        "active_series": REGISTRY.gauge(
            "filodb_index_cardinality_active_series",
            "series currently alive in the part-key index, per shard"),
        "labels": REGISTRY.gauge(
            "filodb_index_cardinality_labels",
            "distinct label names carried by alive series, per shard"),
        "tenant_series": REGISTRY.gauge(
            "filodb_index_cardinality_tenant_series",
            "alive series per tenant (tenant-label value; untagged "
            "series pool under the empty tenant)"),
        "created": REGISTRY.counter(
            "filodb_index_churn_created_total",
            "new series assigned a part id, per shard"),
        "removed": REGISTRY.counter(
            "filodb_index_churn_removed_total",
            "series removed from the index, per shard and reason "
            "(evict | purge)"),
        "create_rate": REGISTRY.gauge(
            "filodb_index_churn_create_rate_per_s",
            "exponentially-decayed series-creation rate, per shard"),
        "remove_rate": REGISTRY.gauge(
            "filodb_index_churn_remove_rate_per_s",
            "exponentially-decayed series-removal rate, per shard"),
    }


def watermark_metrics() -> dict:
    """Canonical ingest-watermark metrics (ISSUE 6): the per-shard
    monotone offset chain broker_end -> ingested -> flushed ->
    checkpoint, its lag in rows and seconds, and stall detection."""
    return {
        "offset": REGISTRY.gauge(
            "filodb_ingest_watermark_offset",
            "per-shard ingest watermark chain by stage "
            "(broker_end | ingested | flushed | checkpoint)"),
        "lag_rows": REGISTRY.gauge(
            "filodb_ingest_lag_rows",
            "records the broker holds that this shard has not ingested"),
        "lag_seconds": REGISTRY.gauge(
            "filodb_ingest_lag_seconds",
            "seconds since the shard's newest ingested sample, while "
            "row lag is nonzero (0 when caught up)"),
        "stalls": REGISTRY.counter(
            "filodb_ingest_stalls_total",
            "stall episodes: a lagging shard whose ingested offset made "
            "no progress for the stall window"),
        "stalled": REGISTRY.gauge(
            "filodb_ingest_stalled",
            "1 while the shard counts as stalled, else 0 — the level "
            "the self-monitoring alert rules watch (a counter's label "
            "set is born at 1, invisible to increase())"),
    }


def split_metrics() -> dict:
    """Elastic-resharding metrics (ISSUE 13, coordinator/split.py):
    phase progression, child replay volume, cutover latency, aborts."""
    return {
        "phase": REGISTRY.gauge(
            "filodb_split_phase",
            "live shard-split phase as a code: 0=none 1=prepare "
            "2=catchup 3=serving(cutover done) 4=retire 5=complete "
            "6=aborted"),
        "replayed_rows": REGISTRY.gauge(
            "filodb_split_replayed_rows",
            "rows the split children have ingested so far (catch-up "
            "replay + dual-ingested live rows, summed across local "
            "children)"),
        "cutover_seconds": REGISTRY.gauge(
            "filodb_split_cutover_seconds",
            "wall seconds the last cutover took from gate-pass to the "
            "committed topology flip"),
        "aborts": REGISTRY.counter(
            "filodb_split_aborts_total",
            "split aborts (lossless rollbacks to the parent topology)"),
        "generation": REGISTRY.gauge(
            "filodb_split_generation",
            "the dataset's current topology generation (bumps on "
            "prepare / cutover / retire-complete / abort)"),
    }


def shard_health_metrics() -> dict:
    """Canonical shard-status metrics (ISSUE 6): numeric status code,
    recovery progress, and transition counts, emitted by
    ShardMapper.update_status on every real change."""
    return {
        "status_code": REGISTRY.gauge(
            "filodb_shard_status_code",
            "shard status as a code: 0=Unassigned 1=Assigned 2=Recovery "
            "3=Active 4=Error 5=Stopped 6=Down"),
        "recovery_progress": REGISTRY.gauge(
            "filodb_shard_recovery_progress",
            "recovery replay progress percent (0 outside recovery)"),
        "transitions": REGISTRY.counter(
            "filodb_shard_status_transitions_total",
            "status transitions by dataset and new status"),
        "replica_status_code": REGISTRY.gauge(
            "filodb_shard_replica_status_code",
            "per-REPLICA shard status code (same encoding as "
            "filodb_shard_status_code), keyed by holding node"),
    }


def selfscrape_metrics() -> dict:
    """Canonical self-telemetry metrics (ISSUE 6): the node scraping its
    own /metrics exposition into the ``_system`` dataset."""
    return {
        "scrapes": REGISTRY.counter(
            "filodb_selfscrape_scrapes_total",
            "self-scrape passes over the node's own exposition"),
        "samples": REGISTRY.counter(
            "filodb_selfscrape_samples_total",
            "samples published into the self-telemetry dataset"),
        "errors": REGISTRY.counter(
            "filodb_selfscrape_errors_total",
            "self-scrape passes that raised (skipped, never fatal)"),
        "duration": REGISTRY.gauge(
            "filodb_selfscrape_last_scrape_seconds",
            "wall time of the most recent self-scrape pass"),
    }


def workload_metrics() -> dict:
    """Canonical workload-management metrics (ISSUE 5): admission,
    cardinality quotas, deadline enforcement, and dispatch retry/hedge —
    one place defines the names so the controller, the shards, the
    gateway edge, and doc/workload.md can never drift."""
    return {
        "admitted": REGISTRY.counter(
            "filodb_admission_admitted_total",
            "queries admitted, by dataset and priority class"),
        "rejected": REGISTRY.counter(
            "filodb_admission_rejected_total",
            "queries shed with 429, by dataset/priority/reason "
            "(expired|deadline|overload|tenant_concurrency|tenant_cost)"),
        "inflight_cost": REGISTRY.gauge(
            "filodb_admission_inflight_cost",
            "estimated cost units currently admitted and running"),
        "estimated_cost": REGISTRY.histogram(
            "filodb_admission_estimated_cost_units",
            "pre-execution cost estimate per query (series-chunk units)",
            buckets=(1, 10, 100, 1_000, 10_000, 100_000, 1_000_000)),
        "sched_expired": REGISTRY.counter(
            "filodb_query_sched_expired_total",
            "queries dropped at dequeue because their deadline expired "
            "while queued (never executed)"),
        "deadline_refused": REGISTRY.counter(
            "filodb_query_deadline_refused_total",
            "remote /execplan work refused because the remaining budget "
            "could not cover it"),
        "partial_shards": REGISTRY.counter(
            "filodb_query_partial_shard_results_total",
            "queries answered partially because >=1 shard was down "
            "(allow_partial_results)"),
        "dispatch_retries": REGISTRY.counter(
            "filodb_dispatch_retries_total",
            "remote dispatch attempts retried after connection errors"),
        "dispatch_hedged": REGISTRY.counter(
            "filodb_dispatch_hedged_total",
            "remote dispatches that launched a hedged second request"),
        "dispatch_hedge_wins": REGISTRY.counter(
            "filodb_dispatch_hedge_wins_total",
            "hedged dispatches where the SECOND request answered first"),
        "dispatch_failures": REGISTRY.counter(
            "filodb_dispatch_failures_total",
            "remote dispatches that failed after exhausting retries"),
        "dispatch_failover": REGISTRY.counter(
            "filodb_dispatch_failover_total",
            "leaf dispatches retargeted at another replica, by reason "
            "(refused|unreachable|no_endpoint|hedge_retarget)"),
        "quota_active": REGISTRY.gauge(
            "filodb_quota_active_series",
            "active (alive-in-index) series per dataset/tenant"),
        "quota_limit": REGISTRY.gauge(
            "filodb_quota_limit_series",
            "configured active-series limit per dataset/tenant"),
        "quota_rejected": REGISTRY.counter(
            "filodb_quota_rejected_series_total",
            "new series rejected because their tenant is over quota"),
        "quota_dropped_samples": REGISTRY.counter(
            "filodb_quota_dropped_samples_total",
            "samples dropped (edge or shard) for over-quota new series"),
    }


def rule_metrics() -> dict:
    """Canonical rule-engine metrics (ISSUE 9, filodb_tpu/rules): group
    evaluation health, write-back volume, alert state transitions,
    notifier outcomes, and incremental-window residency — one place
    defines the names so the engine, /admin/rules, and doc/rules.md can
    never drift."""
    return {
        "eval_seconds": REGISTRY.histogram(
            "filodb_rule_eval_seconds",
            "wall time of one rule-group evaluation pass, per group"),
        "evals": REGISTRY.counter(
            "filodb_rule_evals_total",
            "rule evaluations by group and outcome (ok | failed)"),
        "missed": REGISTRY.counter(
            "filodb_rule_evals_missed_total",
            "scheduled group evaluations skipped because the previous "
            "pass overran the interval"),
        "lag": REGISTRY.gauge(
            "filodb_rule_eval_lag_seconds",
            "how far the group's last pass started behind its cadence"),
        "last_eval": REGISTRY.gauge(
            "filodb_rule_last_eval_timestamp_seconds",
            "unix time of the group's most recent evaluation pass"),
        "samples": REGISTRY.counter(
            "filodb_rule_samples_written_total",
            "recorded/ALERTS samples written back through the gateway "
            "publisher, per group"),
        "stale": REGISTRY.counter(
            "filodb_rule_series_stale_total",
            "recording-rule output series that vanished between "
            "evaluations (export stopped, state dropped)"),
        "transitions": REGISTRY.counter(
            "filodb_rule_alert_transitions_total",
            "alert state transitions by group and new state "
            "(pending | firing | resolved | inactive)"),
        "alerts_active": REGISTRY.gauge(
            "filodb_rule_alerts",
            "alert instances currently held, by group and state"),
        "notifications": REGISTRY.counter(
            "filodb_rule_notifications_total",
            "webhook notifier sends by outcome "
            "(delivered | failed | dropped)"),
        "notify_retries": REGISTRY.counter(
            "filodb_rule_notification_retries_total",
            "webhook delivery attempts retried after an error"),
        "incr_samples": REGISTRY.counter(
            "filodb_rule_incremental_samples_total",
            "newly-arrived samples consumed by incremental window "
            "state (vs re-scanning the full range), per group"),
        "incr_series": REGISTRY.gauge(
            "filodb_rule_incremental_series",
            "input series currently resident in incremental window "
            "state, per group"),
    }


def rollup_metrics() -> dict:
    """Canonical rollup-subsystem metrics (filodb_tpu/rollup): tick
    health, tier lag/stall, emission volume, routing — one place
    defines the names so the engine, the router, /admin/rollup, and
    doc/rollup.md can never drift."""
    return {
        "passes": REGISTRY.counter(
            "filodb_rollup_passes_total",
            "rollup scheduler passes completed, per dataset"),
        "pass_seconds": REGISTRY.histogram(
            "filodb_rollup_pass_seconds",
            "wall time of one rollup pass (consume + reduce + emit)"),
        "samples": REGISTRY.counter(
            "filodb_rollup_samples_written_total",
            "rolled records emitted into the tier datasets, per "
            "dataset and resolution"),
        "lag": REGISTRY.gauge(
            "filodb_rollup_lag_seconds",
            "newest consumed raw sample time minus the tier's newest "
            "emitted period stamp, per dataset/shard/resolution"),
        "errors": REGISTRY.counter(
            "filodb_rollup_tier_errors_total",
            "tier emission passes that raised (retried next tick)"),
        "deferred": REGISTRY.counter(
            "filodb_rollup_deferred_total",
            "rollup passes deferred by admission control (the rollup "
            "class yielded to user traffic)"),
        "stalled": REGISTRY.gauge(
            "filodb_rollup_stalled",
            "1 while a tier makes no progress past the stall window "
            "with work pending, else 0 — the LEVEL the self-monitoring "
            "alert rules watch (a counter's label set is born at 1, "
            "invisible to increase())"),
        "buffered": REGISTRY.gauge(
            "filodb_rollup_buffered_samples",
            "raw samples resident in rollup closure buffers, per "
            "dataset/shard"),
        "routed": REGISTRY.counter(
            "filodb_rollup_queries_routed_total",
            "queries the resolution router served from a rolled tier, "
            "per dataset and resolution (resolution=raw counts "
            "rollup-eligible queries that stayed raw)"),
        "tier_served": REGISTRY.counter(
            "filodb_rollup_tier_legs_total",
            "stitch legs materialized per storage tier "
            "(raw | rolled-local | rolled-cold), per dataset — a "
            "stitched query counts once per tier it actually read"),
    }


def resultcache_metrics() -> dict:
    """Canonical query-frontend result-cache metrics
    (query/resultcache.py): hit/miss traffic, resident bytes, LRU
    evictions, and epoch/digest invalidations — one place defines the
    names so the cache, /admin/resultcache, and doc/observability.md
    can never drift."""
    return {
        "hits": REGISTRY.counter(
            "filodb_resultcache_hits_total",
            "queries (or query segments) served from memoized partials, "
            "per dataset and kind (range segment | instant window)"),
        "misses": REGISTRY.counter(
            "filodb_resultcache_misses_total",
            "cacheable segments/windows that had to be computed fresh, "
            "per dataset and kind"),
        "skipped": REGISTRY.counter(
            "filodb_resultcache_skipped_total",
            "queries that bypassed the cache, per dataset and reason "
            "(shape|remote|range|open|instant-*)"),
        "bytes": REGISTRY.gauge(
            "filodb_resultcache_bytes",
            "resident bytes of memoized partials + instant window "
            "state, per dataset (reconciles exactly with a walk of the "
            "live entries)"),
        "evictions": REGISTRY.counter(
            "filodb_resultcache_evictions_total",
            "entries dropped to stay under the byte budget, per "
            "dataset and reason"),
        "invalidations": REGISTRY.counter(
            "filodb_resultcache_invalidations_total",
            "entries discarded / window states reset because their "
            "validity inputs changed, per dataset and reason "
            "(chunks|quarantine|routing|series|regressed)"),
        "bypass": REGISTRY.counter(
            "filodb_result_cache_bypass_total",
            "range/instant queries that bypassed the result cache "
            "entirely, per dataset and reason (remote = plan spans "
            "non-local shards, the known federation coherence gap; "
            "disabled = cache switched off; unfingerprintable = shape "
            "has no canonical fingerprint)"),
    }


def odp_metrics() -> dict:
    """Canonical on-demand-paging metrics."""
    return {
        "pagein_seconds": REGISTRY.histogram(
            "filodb_odp_pagein_seconds",
            "page-in latency (store read + decode + materialize)"),
        "partitions": REGISTRY.counter(
            "filodb_odp_partitions_paged_total",
            "partitions re-materialized from the column store"),
        "chunks": REGISTRY.counter(
            "filodb_odp_chunks_paged_total",
            "chunks read back from the column store"),
    }


def coldstore_metrics() -> dict:
    """Canonical cold-tier metrics (filodb_tpu/coldstore): bucket fetch
    traffic + failure classes, age-out volume, and the per-shard
    watermark level — one place defines the names so the store, the
    age-out loop, cli verbs, and doc/coldstore.md can never drift."""
    return {
        "fetches": REGISTRY.counter(
            "filodb_coldstore_fetches_total",
            "objects fetched from the cold bucket (cache-miss reads; "
            "prefetched objects count once, at prefetch time)"),
        "fetch_bytes": REGISTRY.counter(
            "filodb_coldstore_fetch_bytes_total",
            "object bytes fetched from the cold bucket"),
        "fetch_corrupt": REGISTRY.counter(
            "filodb_coldstore_fetch_corrupt_total",
            "fetched objects failing their key CRC (truncated or "
            "bit-rotted in the bucket) — quarantined, never served, "
            "per dataset"),
        "fetch_timeouts": REGISTRY.counter(
            "filodb_coldstore_fetch_timeouts_total",
            "fetches refused because the deadline-derived timeout "
            "expired (stalled backend or exhausted query budget)"),
        "fetch_missing": REGISTRY.counter(
            "filodb_coldstore_fetch_missing_total",
            "fetches of objects deleted between listing and get "
            "(served as absent rows, not errors)"),
        "aged_chunks": REGISTRY.counter(
            "filodb_coldstore_aged_chunks_total",
            "chunk rows migrated local -> cold by age-out passes, "
            "per dataset"),
        "aged_bytes": REGISTRY.counter(
            "filodb_coldstore_aged_bytes_total",
            "blob bytes migrated local -> cold, per dataset"),
        "watermark": REGISTRY.gauge(
            "filodb_coldstore_ageout_watermark_ms",
            "cutoff (epoch ms) of the last completed age-out pass, per "
            "dataset/shard — chunks ending before it are archived"),
    }


def downsample_metrics() -> dict:
    """Visualization downsampling (?downsample=<pixels>, ops/grid.py
    m4_grid): how often panels opt in and the point-volume reduction."""
    return {
        "queries": REGISTRY.counter(
            "filodb_downsample_queries_total",
            "range queries that requested M4 pixel downsampling"),
        "points_in": REGISTRY.counter(
            "filodb_downsample_points_in_total",
            "finite samples entering the downsampler"),
        "points_out": REGISTRY.counter(
            "filodb_downsample_points_out_total",
            "pixel-exact samples kept (<= 4 per pixel bin per series)"),
    }


def insights_metrics() -> dict:
    """Canonical workload-insights metrics (ISSUE 19,
    filodb_tpu/insights): ledger volume + the fleet aggregator's poll
    health — one place defines the names so the ledger,
    /admin/insights, /admin/fleet, and doc/observability.md can never
    drift."""
    return {
        "noted": REGISTRY.counter(
            "filodb_insights_queries_total",
            "query completions folded into the workload ledger, per "
            "dataset and outcome (ok | error | shed)"),
        "fingerprints": REGISTRY.gauge(
            "filodb_insights_fingerprints",
            "distinct plan fingerprints resident in the ledger, per "
            "node (bounded; evictions show in *_dropped_total)"),
        "dropped": REGISTRY.counter(
            "filodb_insights_dropped_total",
            "least-recently-updated fingerprint entries evicted to "
            "stay under the ledger bound, per node"),
        "fleet_polls": REGISTRY.counter(
            "filodb_insights_fleet_polls_total",
            "fleet-aggregator snapshot fetches, per peer and outcome "
            "(ok | error)"),
    }


def batch_metrics() -> dict:
    """Canonical fleet-batching metrics (ISSUE 20,
    filodb_tpu/batching): realized vmapped group sizes next to the
    ledger's co-arrival headroom estimate, plus the fallback ladder —
    one place defines the names so the batcher, /admin/insights,
    doc/observability.md, and the bench gates can never drift."""
    return {
        "groups": REGISTRY.counter(
            "filodb_batch_groups_total",
            "batched (vmapped) device launches serving >= 2 queries, "
            "per dataset"),
        "members": REGISTRY.counter(
            "filodb_batch_members_total",
            "queries served from a batched launch, per dataset "
            "(members/groups = realized mean batch size)"),
        "fallbacks": REGISTRY.counter(
            "filodb_batch_fallbacks_total",
            "dispatches demoted to the per-query chain, per dataset "
            "and reason (breaker | deadline | solo-window | "
            "member-expired | timeout | error)"),
        "peak": REGISTRY.gauge(
            "filodb_batch_realized_peak",
            "largest realized batch size since start, per dataset "
            "(compare against the insights ledger's co-arrival peak)"),
    }


def slo_metrics() -> dict:
    """Canonical tenant-SLO metrics (ISSUE 19, insights/slo.py).  The
    burn rates are LEVEL gauges on purpose — the filodb_ingest_stalled
    lesson: a counter's label set is born at 1, invisible to a rules
    ``increase()``, while a pre-registered gauge row shows the full
    0 -> burning edge to the self-monitoring alert rules."""
    return {
        "requests": REGISTRY.counter(
            "filodb_slo_requests_total",
            "queries matched against an SLO objective, per "
            "objective/tenant/node"),
        "breaches": REGISTRY.counter(
            "filodb_slo_breaches_total",
            "matched queries that were BAD (errored or exceeded the "
            "objective's latency threshold)"),
        "fast_burn": REGISTRY.gauge(
            "filodb_slo_fast_burn",
            "error-budget burn rate over the fast window (bad fraction "
            "/ budget); the SLO rule pack pages above 14.4"),
        "slow_burn": REGISTRY.gauge(
            "filodb_slo_slow_burn",
            "error-budget burn rate over the slow window; the SLO "
            "rule pack warns above 6"),
        "budget": REGISTRY.gauge(
            "filodb_slo_error_budget",
            "configured error budget (1 - availability target) per "
            "objective — a constant level, exported so dashboards can "
            "plot burn against it"),
    }


# ---------------------------------------------------------------------------
# Process-level metrics (ISSUE 4 satellite): node dashboards read RSS /
# FDs / threads / uptime / GC pressure from the SAME /metrics endpoint,
# no separate node exporter required.  All gauges are set_fn-sampled at
# scrape time; /proc reads are linux-only and degrade to 0 elsewhere.
# ---------------------------------------------------------------------------

_PROCESS_START_S = time.time()
_PAGE_SIZE = 4096
try:
    import os as _os
    _PAGE_SIZE = _os.sysconf("SC_PAGE_SIZE")
except (ImportError, ValueError, OSError):  # pragma: no cover - non-posix
    pass


def _rss_bytes() -> float:
    try:
        with open("/proc/self/statm") as f:
            return float(f.read().split()[1]) * _PAGE_SIZE
    except OSError:  # pragma: no cover - non-linux
        try:
            import resource
            return float(resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss) * 1024.0
        except Exception:  # noqa: BLE001
            return 0.0


def _open_fds() -> float:
    try:
        import os
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:  # pragma: no cover - non-linux
        return 0.0


def process_metrics() -> dict:
    """Canonical ``filodb_process_*`` family: RSS, open FDs, thread
    count, start time / uptime, and per-generation GC collections.
    Registered once at import so every /metrics scrape carries them."""
    import gc

    rss = REGISTRY.gauge("filodb_process_resident_memory_bytes",
                         "resident set size of this process")
    rss.set_fn(_rss_bytes)
    fds = REGISTRY.gauge("filodb_process_open_fds",
                         "open file descriptors")
    fds.set_fn(_open_fds)
    threads = REGISTRY.gauge("filodb_process_threads",
                             "live python threads")
    threads.set_fn(lambda: float(threading.active_count()))
    start = REGISTRY.gauge("filodb_process_start_time_seconds",
                           "unix time the process started")
    start.set(_PROCESS_START_S)
    uptime = REGISTRY.gauge("filodb_process_uptime_seconds",
                            "seconds since process start")
    uptime.set_fn(lambda: time.time() - _PROCESS_START_S)
    gens = REGISTRY.gauge("filodb_process_gc_collections",
                          "garbage collections per generation")
    for gen in range(3):
        gens.set_fn(
            (lambda g: lambda: float(gc.get_stats()[g]["collections"]))(
                gen), generation=str(gen))
    return {"rss": rss, "open_fds": fds, "threads": threads,
            "start_time": start, "uptime": uptime,
            "gc_collections": gens}


process_metrics()


class PeriodicThread:
    """Daemon loop calling ``fn`` every ``interval_s`` until stopped;
    exceptions print and the loop continues (the shared harness for
    background samplers — watermark sampling, self-scrape — so the
    stop/join/backoff behavior lives in one place)."""

    def __init__(self, fn: Callable[[], object], interval_s: float,
                 name: str):
        self.fn = fn
        self.interval_s = float(interval_s)
        self.name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.fn()
                except Exception:  # noqa: BLE001 — keep looping, loudly
                    traceback.print_exc()

        self._thread = threading.Thread(target=loop, name=self.name,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# Tracing spans
# ---------------------------------------------------------------------------


def _new_id() -> str:
    """64-bit random hex id (span/trace ids on the wire)."""
    return f"{random.getrandbits(64):016x}"


@dataclasses.dataclass
class SpanRecord:
    name: str
    start_s: float
    duration_s: float
    tags: dict
    parent: Optional[str]          # parent span NAME (log reporters)
    error: Optional[str] = None
    # trace stitching (ISSUE 2): ids travel across threads and nodes so
    # a scatter-gather fan-out reassembles into one tree
    trace_id: Optional[str] = None
    span_id: str = ""
    parent_id: Optional[str] = None


class Tracer:
    """Thread-local span stack + pluggable reporters (replaces Kamon
    span propagation via Kamon.runWithSpan).

    Each thread carries a trace context: a ``trace_id`` minted at the
    query entry point plus the current span's id.  ``capture()`` /
    ``attach()`` move that context across thread pools (scheduler
    workers, scatter-gather child dispatch), and the dispatch layer
    moves it across processes via an HTTP header + execplan-wire field.
    """

    def __init__(self) -> None:
        self._local = threading.local()
        self._reporters: list[Callable[[SpanRecord], None]] = []
        self._lock = threading.Lock()

    def add_reporter(self, fn: Callable[[SpanRecord], None]) -> None:
        with self._lock:
            self._reporters.append(fn)

    def remove_reporter(self, fn: Callable[[SpanRecord], None]) -> None:
        with self._lock:
            self._reporters = [r for r in self._reporters if r is not fn]

    def clear_reporters(self) -> None:
        with self._lock:
            self._reporters = []

    def current_span(self) -> Optional[str]:
        stack = getattr(self._local, "stack", None)
        return stack[-1][0] if stack else None

    def current_span_id(self) -> Optional[str]:
        stack = getattr(self._local, "stack", None)
        if stack:
            return stack[-1][1]
        return getattr(self._local, "parent_hint", None)

    def current_trace_id(self) -> Optional[str]:
        return getattr(self._local, "trace_id", None)

    @staticmethod
    def new_trace_id() -> str:
        return _new_id()

    def capture(self) -> tuple:
        """(trace_id, span_id) token for cross-thread propagation."""
        return self.current_trace_id(), self.current_span_id()

    @contextlib.contextmanager
    def attach(self, token):
        """Install a captured trace context on this thread: spans opened
        inside parent onto ``token``'s span id and carry its trace id.
        The span stack is swapped for a FRESH one — the context is
        foreign, so an unrelated span already open on this thread (e.g.
        a scheduler worker's own span) must not capture the parentage."""
        tid, sid = token if token else (None, None)
        old_tid = getattr(self._local, "trace_id", None)
        old_hint = getattr(self._local, "parent_hint", None)
        old_stack = getattr(self._local, "stack", None)
        self._local.trace_id = tid
        self._local.parent_hint = sid
        self._local.stack = []
        try:
            yield
        finally:
            self._local.trace_id = old_tid
            self._local.parent_hint = old_hint
            self._local.stack = old_stack

    def span(self, name: str, **tags):
        return _Span(self, name, tags)

    def record(self, name: str, duration_s: float,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None, **tags) -> SpanRecord:
        """Report a synthetic span that did not run on this thread
        (queue wait measured by a worker, a remote node's spans)."""
        rec = SpanRecord(name, time.time() - duration_s, duration_s,
                         tags, None, trace_id=trace_id, span_id=_new_id(),
                         parent_id=parent_id)
        self._report(rec)
        return rec

    def _report(self, rec: SpanRecord) -> None:
        with self._lock:
            reporters = list(self._reporters)
        for fn in reporters:
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — reporters must not break work
                traceback.print_exc()


class _Span:
    def __init__(self, tracer: Tracer, name: str, tags: dict):
        self.tracer = tracer
        self.name = name
        self.tags = tags
        self.span_id = _new_id()
        self._t0 = 0.0

    def __enter__(self):
        local = self.tracer._local
        stack = getattr(local, "stack", None)
        if stack is None:
            stack = local.stack = []
        if stack:
            self.parent, self.parent_id = stack[-1]
        else:
            self.parent = None
            self.parent_id = getattr(local, "parent_hint", None)
        stack.append((self.name, self.span_id))
        self._t0 = time.perf_counter()
        return self

    def tag(self, **tags):
        self.tags.update(tags)
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        try:  # spans must NEVER raise into the instrumented path
            self.tracer._local.stack.pop()
        except (AttributeError, IndexError):
            pass
        self.tracer._report(SpanRecord(
            self.name, time.time() - dur, dur, dict(self.tags), self.parent,
            error=repr(exc) if exc is not None else None,
            trace_id=self.tracer.current_trace_id(),
            span_id=self.span_id, parent_id=self.parent_id))
        return False


TRACER = Tracer()


def span_log_reporter(log: Callable[[str], None] = print,
                      min_duration_s: float = 0.0):
    """Span -> log line reporter (reference: KamonSpanLogReporter)."""

    def report(rec: SpanRecord) -> None:
        if rec.duration_s >= min_duration_s:
            tags = " ".join(f"{k}={v}" for k, v in rec.tags.items())
            err = f" ERROR={rec.error}" if rec.error else ""
            log(f"span {rec.name} {rec.duration_s * 1000:.2f}ms "
                f"parent={rec.parent} {tags}{err}")
    return report


# ---------------------------------------------------------------------------
# Sampling profiler
# ---------------------------------------------------------------------------


class SimpleProfiler:
    """Background stack-sampling profiler (reference:
    standalone/src/main/java/filodb/standalone/SimpleProfiler.java —
    samples thread stacks periodically, aggregates hottest frames, and
    reports every interval)."""

    def __init__(self, sample_interval_s: float = 0.01,
                 report_interval_s: float = 60.0,
                 top_k: int = 20,
                 report_fn: Optional[Callable[[str], None]] = None):
        self.sample_interval_s = sample_interval_s
        self.report_interval_s = report_interval_s
        self.top_k = top_k
        self.report_fn = report_fn or print
        self._counts: collections.Counter = collections.Counter()
        self._samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="profiler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        own = threading.get_ident()
        next_report = time.monotonic() + self.report_interval_s
        while not self._stop.wait(self.sample_interval_s):
            frames = sys._current_frames()
            with self._lock:
                self._samples += 1
                for tid, frame in frames.items():
                    if tid == own:
                        continue
                    code = frame.f_code
                    self._counts[(code.co_filename, code.co_name)] += 1
            if time.monotonic() >= next_report:
                self.report_fn(self.report())
                next_report = time.monotonic() + self.report_interval_s

    def report(self) -> str:
        with self._lock:
            total = self._samples or 1
            top = self._counts.most_common(self.top_k)
        lines = [f"profiler: {self._samples} samples"]
        for (fname, func), n in top:
            short = fname.rsplit("/", 1)[-1]
            lines.append(f"  {100.0 * n / total:5.1f}% {short}:{func}")
        return "\n".join(lines)

    def snapshot(self) -> Mapping:
        with self._lock:
            return dict(self._counts)
