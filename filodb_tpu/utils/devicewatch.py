"""Device-resource observability: HBM ledger, JIT telemetry, flight recorder.

PR 7 made the *host* side of a query visible (traces, per-stage stats,
slowlog); the resource that actually bounds the north star — TPU HBM —
stayed dark: `DeviceGrid` arenas track ``bytes_resident``/``evictions``
internally and the ODP page cache enforces a byte budget, but nothing
exposed who holds the memory, why it got evicted, or what compiled
when.  This module is the device-side counterpart, three pillars:

1. **HBM residency ledger** (:class:`HbmLedger`, singleton ``LEDGER``):
   every ``jax.device_put``/resident-plane commit in ``filodb_tpu/``
   routes through :meth:`HbmLedger.device_put` / :meth:`HbmLedger.track`
   (lint-enforced by tests/test_sentinel_lint.py), tagged with an owner
   (shard/schema/column) and a format (``dense``/``compressed``/
   ``mesh-staged``/``scratch``).  Tracked bytes are released by a
   ``weakref.finalize`` on the device array — exactly when JAX frees the
   buffer — so per-owner totals stay byte-accurate through eviction and
   GC without any explicit release calls.  Host-side byte pools that
   behave like arenas (the ODP page cache) register a sampling callback
   instead.  Exposed as ``filodb_device_hbm_bytes{owner,format}``,
   high-watermark gauges, and eviction-attribution counters
   (``filodb_device_evictions_total{owner,reason}``), reconciled against
   ``device.memory_stats()`` where the backend provides it.

2. **Compile telemetry** (:class:`CompileWatch`, singleton
   ``COMPILE_WATCH``): :func:`jit` wraps ``jax.jit`` for the entry
   points in devicestore/mesh/ops, detecting compiles via the jitted
   callable's cache growth (no per-call key hashing on the hot path) and
   recording per-program compile count, wall time, and an abstract-shape
   key.  A recompile-storm detector flags programs compiling more than N
   distinct shapes within a window — the classic JAX production failure
   — in the log, the ``filodb_jit_recompile_storms_total`` counter, and
   the slow-query log entries (utils/forensics.py).

3. **Flight recorder** (:class:`FlightRecorder`, singleton ``FLIGHT``):
   a bounded lock-free ring of recent structured events (ingest batches,
   flushes, evictions, compiles, ODP page-ins, breaker trips, query
   start/end) dumped on demand by ``/admin/flightrecorder`` and
   auto-dumped to the log on integrity failure or unhandled-exception
   shutdown — the black box for postmortems.

4. **Kernel flight deck** (:class:`KernelTimer`, singleton
   ``KERNEL_TIMER``; ISSUE 15): sampled *device-time* accounting for
   every :func:`jit`-wrapped program.  Every launch counts (exactly);
   every Nth launch per program (``kernel-sample-1-in``, default 64)
   additionally times ``block_until_ready`` on the result and folds the
   measured seconds into a per-program EWMA + streaming log-histogram.
   Joined with the per-plan ``hbm_read_bytes`` notes from devicestore,
   that yields a LIVE achieved-bytes/s per program against the
   configured HBM roof (``hbm-roof-bytes-per-s``, default 819e9 — the
   doc/kernel.md roofline, now measured on real traffic instead of
   derived offline).  A regression sentry compares each program's EWMA
   against a learned baseline (seeded after a quiet warmup, ratcheted
   DOWNWARD only, persisted in the metastore KV by the standalone
   server): sustained >= 1.5x degradation over a window fires ONE
   ``kernel.regression`` flight event per episode (re-armed on
   recovery — the recompile-storm episode discipline), counted in
   ``filodb_kernel_regressions_total{program}`` and levelled in
   ``filodb_kernel_regressed{program}`` for the self-monitoring alert
   rules.  ``/admin/kernels`` joins this ledger with the compile table.

Everything is stdlib + jax-optional: with no jax importable the ledger
wrapper falls back to identity and the compile wrapper to the plain
function, so host-only deployments lose nothing.
"""

from __future__ import annotations

import bisect
import functools
import itertools
import logging
import threading
import time
import weakref
from typing import Callable, Optional

_LOG = logging.getLogger("filodb.devicewatch")

# kill switch: set_enabled(False) turns every wrapper into a pass-through
# (used by the overhead bench to measure the instrumentation delta, and
# by operators via the standalone "devicewatch" config block)
_ENABLED = True


def set_enabled(flag: bool) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# metric handles, resolved once (hot paths must not take the registry lock)
# ---------------------------------------------------------------------------

_METRICS = None


def device_metrics() -> dict:
    """Canonical device-resource metrics: one place defines the names so
    the ledger, /metrics, and /admin/device can never drift."""
    global _METRICS
    if _METRICS is None:
        from filodb_tpu.utils.observability import REGISTRY
        _METRICS = {
            "hbm_bytes": REGISTRY.gauge(
                "filodb_device_hbm_bytes",
                "ledger-tracked device-resident bytes by owner and "
                "resident format"),
            "hbm_watermark": REGISTRY.gauge(
                "filodb_device_hbm_high_watermark_bytes",
                "high watermark of ledger-tracked bytes by owner/format"),
            "evictions": REGISTRY.counter(
                "filodb_device_evictions_total",
                "device/pool resident evictions by owner and reason "
                "(budget_overflow | epoch_purge | integrity_quarantine)"),
            "jit_compiles": REGISTRY.counter(
                "filodb_jit_compiles_total",
                "XLA program compiles by wrapped jit entry point"),
            "jit_seconds": REGISTRY.histogram(
                "filodb_jit_compile_seconds",
                "wall time of calls that compiled a new program "
                "(trace + lower + compile)"),
            "jit_storms": REGISTRY.counter(
                "filodb_jit_recompile_storms_total",
                "recompile storms detected (program exceeded the "
                "distinct-shape threshold within the window)"),
            "kernel_launches": REGISTRY.counter(
                "filodb_kernel_launches_total",
                "wrapped-program launches (every launch counts; "
                "reconciles exactly with the /admin/kernels table)"),
            "kernel_seconds": REGISTRY.counter(
                "filodb_kernel_device_seconds",
                "measured device seconds of SAMPLED launches "
                "(block_until_ready wall time, 1-in-N per program)"),
            "kernel_roofline": REGISTRY.gauge(
                "filodb_kernel_roofline_fraction",
                "live achieved HBM bytes/s per program as a fraction "
                "of the configured roof (hbm-roof-bytes-per-s)"),
            "kernel_regressions": REGISTRY.counter(
                "filodb_kernel_regressions_total",
                "kernel-regression episodes: sustained EWMA device "
                "time >= factor x learned baseline"),
            "kernel_regressed": REGISTRY.gauge(
                "filodb_kernel_regressed",
                "1 while the program's EWMA device time counts as "
                "regressed vs its learned baseline, else 0 — the LEVEL "
                "the self-monitoring alert rules watch (a counter's "
                "label set is born at 1, invisible to increase())"),
        }
    return _METRICS


# ---------------------------------------------------------------------------
# 1. HBM residency ledger
# ---------------------------------------------------------------------------


class HbmLedger:
    """Process-wide accounting of device-resident bytes by owner/format.

    ``track`` registers a device array under ``(owner, fmt)`` and arms a
    ``weakref.finalize`` that gives the bytes back when JAX frees the
    buffer; totals therefore reconcile exactly with the set of live
    tracked arrays at any point (tests/test_devicewatch.py asserts this
    across commit -> query -> overflow-eviction -> ODP churn).  The
    active query's ExecContext is credited/debited so QueryStats carries
    the HBM delta a query caused."""

    def __init__(self) -> None:
        # the ledger sits between the device caches and the metrics
        # registry in the repo's lock hierarchy (filolint lockorder.py
        # holds these; a future back-edge is a build failure):
        # lock-order: DeviceGridCache._lock < HbmLedger._lock
        # lock-order: HbmLedger._lock < MetricsRegistry._lock
        self._lock = threading.Lock()
        # (owner, fmt) -> live bytes / high watermark / live array count
        self._bytes: dict[tuple, int] = {}
        self._marks: dict[tuple, int] = {}
        self._counts: dict[tuple, int] = {}
        # per-device live bytes (reconciliation vs device.memory_stats)
        self._dev_bytes: dict[str, int] = {}
        # id(arr) -> finalizer: dedups repeat track() of one array and
        # keeps the finalize object alive
        self._fins: dict[int, object] = {}
        # host byte pools that behave like arenas (ODP page cache):
        # name -> (bytes_fn, budget_fn or None)
        self._pools: dict[str, tuple] = {}

    # ------------------------------------------------------------- tracking

    def device_put(self, x, device=None, *, owner: str,
                   fmt: str = "dense"):
        """``jax.device_put`` + ledger registration.  The ONLY sanctioned
        way to move bytes onto the accelerator from ``filodb_tpu/``
        (lint-enforced); a put of an already-resident array is a no-op
        in jax and is NOT re-tracked (its original owner keeps it)."""
        import jax
        out = jax.device_put(x, device)
        if _ENABLED and out is not x:
            self.track(out, owner=owner, fmt=fmt)
        return out

    def track(self, arr, *, owner: str, fmt: str = "dense") -> None:
        """Register an already-device-resident array (e.g. the output of
        a staging jit program).  Idempotent per array identity."""
        if not _ENABLED or arr is None:
            return
        try:
            nbytes = int(arr.nbytes)
            key = id(arr)
        except Exception:  # noqa: BLE001 — tracers/odd leaves: not resident
            return
        dev = self._device_label(arr)
        lkey = (owner, fmt)
        with self._lock:
            if key in self._fins:
                return
            try:
                fin = weakref.finalize(arr, self._untrack, key, lkey, dev,
                                       nbytes)
            except TypeError:
                return            # object without weakref support
            fin.atexit = False    # no dump of bookkeeping at interpreter exit
            self._fins[key] = fin
            total = self._bytes.get(lkey, 0) + nbytes
            self._bytes[lkey] = total
            self._counts[lkey] = self._counts.get(lkey, 0) + 1
            if total > self._marks.get(lkey, 0):
                self._marks[lkey] = total
                device_metrics()["hbm_watermark"].set(total, owner=owner,
                                                      format=fmt)
            self._dev_bytes[dev] = self._dev_bytes.get(dev, 0) + nbytes
            # gauge write stays UNDER the ledger lock: a concurrent
            # finalizer's set racing a deferred set here would leave the
            # exported residency permanently stale (internally-ordered
            # totals must reach the gauge in the same order)
            device_metrics()["hbm_bytes"].set(total, owner=owner,
                                              format=fmt)
        self._note_query_delta(nbytes)

    def _untrack(self, key: int, lkey: tuple, dev: str,
                 nbytes: int) -> None:
        """weakref.finalize callback: the buffer was freed."""
        with self._lock:
            self._fins.pop(key, None)
            total = self._bytes.get(lkey, 0) - nbytes
            self._bytes[lkey] = total
            self._counts[lkey] = self._counts.get(lkey, 0) - 1
            self._dev_bytes[dev] = self._dev_bytes.get(dev, 0) - nbytes
            try:
                # under the lock, same ordering argument as track()
                device_metrics()["hbm_bytes"].set(total, owner=lkey[0],
                                                  format=lkey[1])
            except Exception:  # noqa: BLE001 — interpreter teardown
                return
        self._note_query_delta(-nbytes)

    @staticmethod
    def _device_label(arr) -> str:
        try:
            devs = getattr(arr, "devices", None)
            if callable(devs):
                ds = sorted(str(d) for d in devs())
                return ds[0] if len(ds) == 1 else "+".join(ds)
        except Exception:  # noqa: BLE001
            pass
        return "unknown"

    @staticmethod
    def _note_query_delta(nbytes: int) -> None:
        """Attribute a residency change to the query that caused it (the
        finalizer runs inline on CPython refcount drops, so eviction
        debits land on the evicting query's thread too)."""
        try:
            from filodb_tpu.query.exec import active_exec_ctx
            ctx = active_exec_ctx()
            if ctx is not None:
                ctx.note_counts(hbm_delta=nbytes)
        except Exception:  # noqa: BLE001 — accounting never breaks work
            pass

    # -------------------------------------------------------------- pools

    def register_pool(self, name: str, bytes_fn: Callable[[], int],
                      budget_fn: Optional[Callable[[], int]] = None,
                      fmt: str = "odp-page-cache") -> None:
        """Register a host-side byte pool (sampled at read time).  The
        pool shows in the ledger tree and as
        ``filodb_device_hbm_bytes{owner=<name>,format=<fmt>}``."""
        with self._lock:
            self._pools[name] = (bytes_fn, budget_fn, fmt)
        device_metrics()["hbm_bytes"].set_fn(
            lambda: float(self._pool_bytes(name)), owner=name, format=fmt)

    def deregister_pool(self, name: str) -> None:
        with self._lock:
            pool = self._pools.pop(name, None)
        if pool is not None:
            # remove under the fmt the pool REGISTERED with — a
            # hardcoded label here leaked the set_fn (and its captured
            # instance) for every non-default fmt
            device_metrics()["hbm_bytes"].remove(owner=name,
                                                 format=pool[2])

    def _pool_bytes(self, name: str) -> int:
        pool = self._pools.get(name)
        if pool is None:
            return 0
        try:
            return int(pool[0]())
        except Exception:  # noqa: BLE001 — pool owner shut down
            return 0

    # ----------------------------------------------------------- evictions

    def note_eviction(self, owner: str, reason: str, n: int = 1,
                      nbytes: int = 0) -> None:
        """Attribute an eviction: ``budget_overflow`` (arena over its
        byte budget), ``epoch_purge`` (data changed: freeze/repin/
        invalidation), or ``integrity_quarantine``."""
        if not _ENABLED:
            return
        device_metrics()["evictions"].inc(n, owner=owner, reason=reason)
        FLIGHT.record("evict", owner=owner, reason=reason, n=n,
                      bytes=nbytes)

    # ------------------------------------------------------------- reading

    def owners(self) -> dict:
        """{owner: {format: {bytes, high_watermark, arrays}}} snapshot."""
        with self._lock:
            keys = set(self._bytes) | set(self._marks)
            out: dict = {}
            for owner, fmt in sorted(keys):
                out.setdefault(owner, {})[fmt] = {
                    "bytes": self._bytes.get((owner, fmt), 0),
                    "high_watermark": self._marks.get((owner, fmt), 0),
                    "arrays": self._counts.get((owner, fmt), 0),
                }
        return out

    def total_bytes(self, owner: Optional[str] = None) -> int:
        with self._lock:
            return sum(v for (o, _f), v in self._bytes.items()
                       if owner is None or o == owner)

    def pools(self) -> dict:
        with self._lock:
            names = list(self._pools.items())
        out = {}
        for name, (bytes_fn, budget_fn, _fmt) in names:
            row = {"bytes": 0}
            try:
                row["bytes"] = int(bytes_fn())
                if budget_fn is not None:
                    row["budget"] = int(budget_fn())
            except Exception:  # noqa: BLE001 — pool owner shut down
                pass
            out[name] = row
        return out

    def reconcile(self) -> dict:
        """Per-device ledger totals vs ``device.memory_stats()`` where
        the backend reports it (TPU/GPU ``bytes_in_use``); the gap is
        XLA scratch + untracked allocations."""
        with self._lock:
            dev_bytes = dict(self._dev_bytes)
        out = {}
        stats_by_label = {}
        try:
            import jax
            for d in jax.devices():
                stats_by_label[str(d)] = d.memory_stats()
        except Exception:  # noqa: BLE001 — no backend
            pass
        for label in sorted(set(dev_bytes) | set(stats_by_label)):
            row = {"ledger_bytes": dev_bytes.get(label, 0)}
            st = stats_by_label.get(label)
            if isinstance(st, dict) and "bytes_in_use" in st:
                row["bytes_in_use"] = int(st["bytes_in_use"])
                row["untracked_bytes"] = \
                    row["bytes_in_use"] - row["ledger_bytes"]
                if "bytes_limit" in st:
                    row["bytes_limit"] = int(st["bytes_limit"])
            out[label] = row
        return out


LEDGER = HbmLedger()


# ---------------------------------------------------------------------------
# 2. JIT compile telemetry + recompile-storm detector
# ---------------------------------------------------------------------------


class CompileWatch:
    """Per-program compile table + storm detection.

    A *storm* is one program compiling >= ``storm_shapes`` distinct
    shapes within ``storm_window_s`` — in JAX that means some query/data
    axis is leaking into the abstract shape (unpadded lanes, per-request
    nsteps, ...) and every request pays a fresh XLA compile.  Detection
    logs once per storm, bumps the storm counter, and stays "active" for
    one window so the slow-query log can flag affected entries."""

    def __init__(self, storm_shapes: int = 8,
                 storm_window_s: float = 60.0):
        self.storm_shapes = int(storm_shapes)
        self.storm_window_s = float(storm_window_s)
        self._lock = threading.Lock()
        # program -> row dict (compiles/seconds/shapes/recent/storms)
        self._progs: dict[str, dict] = {}

    def configure(self, storm_shapes: Optional[int] = None,
                  storm_window_s: Optional[float] = None) -> None:
        with self._lock:
            if storm_shapes is not None:
                self.storm_shapes = max(2, int(storm_shapes))
            if storm_window_s is not None:
                self.storm_window_s = max(1.0, float(storm_window_s))

    def note_compile(self, program: str, seconds: float,
                     shape_key: str) -> None:
        m = device_metrics()
        m["jit_compiles"].inc(program=program)
        m["jit_seconds"].observe(seconds, program=program)
        now = time.monotonic()
        storm = False
        with self._lock:
            row = self._progs.get(program)
            if row is None:
                row = self._progs[program] = {
                    "compiles": 0, "seconds": 0.0, "shapes": [],
                    "recent": [], "storms": 0, "storm_until": 0.0,
                    "last_key": ""}
            row["compiles"] += 1
            row["seconds"] += seconds
            row["last_key"] = shape_key
            if shape_key not in row["shapes"]:
                row["shapes"].append(shape_key)
                del row["shapes"][:-64]          # bound the key table
            recent = row["recent"]
            recent.append(now)
            cutoff = now - self.storm_window_s
            while recent and recent[0] < cutoff:
                recent.pop(0)
            if len(recent) >= self.storm_shapes \
                    and now >= row["storm_until"]:
                row["storms"] += 1
                row["storm_until"] = now + self.storm_window_s
                storm = True
        FLIGHT.record("jit.compile", program=program,
                      seconds=round(seconds, 6), key=shape_key)
        if storm:
            m["jit_storms"].inc(program=program)
            FLIGHT.record("jit.storm", program=program,
                          window_s=self.storm_window_s,
                          compiles_in_window=self.storm_shapes)
            _LOG.warning(
                "recompile storm: program %r compiled %d distinct shapes "
                "within %.0fs (last key %s) — some query/data axis is "
                "reaching the abstract shape; expect every request to "
                "pay a fresh XLA compile", program, self.storm_shapes,
                self.storm_window_s, shape_key)

    def active_storms(self) -> list[str]:
        """Programs inside a storm window right now (slowlog flag)."""
        now = time.monotonic()
        with self._lock:
            return [p for p, row in self._progs.items()
                    if row["storm_until"] > now]

    def table(self) -> list[dict]:
        """The /admin/device compile table, most-compiled first."""
        with self._lock:
            rows = [{"program": p, "compiles": r["compiles"],
                     "compile_seconds": round(r["seconds"], 6),
                     "distinct_shapes": len(r["shapes"]),
                     "storms": r["storms"],
                     "last_shape_key": r["last_key"]}
                    for p, r in self._progs.items()]
        rows.sort(key=lambda r: -r["compiles"])
        return rows


COMPILE_WATCH = CompileWatch()


# ---------------------------------------------------------------------------
# 2b. Kernel flight deck: sampled device-time ledger + regression sentry
# ---------------------------------------------------------------------------

# streaming-histogram bucket edges (seconds): powers of two from 1us to
# ~16s — wide enough for a CPU-interpret kernel, fine enough to tell a
# 2x regression from noise
_KHIST_EDGES = tuple(2.0 ** i * 1e-6 for i in range(25))


class KernelTimer:
    """Per-program device-time ledger, sampled (ISSUE 15).

    Every wrapped launch counts (``launches`` advances on each call and
    reconciles exactly with ``filodb_kernel_launches_total``); every Nth
    launch per program is *sampled*: the wrapper times
    ``block_until_ready`` on the result and folds the wall seconds —
    which on an otherwise-idle device IS the dispatch+device time — into
    an EWMA, a streaming log-histogram, and the active query's
    per-program ``devicePrograms`` split.  Launches that compiled are
    never folded (trace+compile wall time is host work; the runtime
    compile telemetry above already accounts it).

    The **regression sentry**: once a program has ``baseline_min_samples``
    sampled launches its baseline seeds from the EWMA and thereafter
    ratchets DOWNWARD only (a program can only ever prove itself
    faster).  An EWMA sustained >= ``regression_factor`` x baseline for
    ``regression_window_s`` opens ONE episode: a ``kernel.regression``
    flight event, ``filodb_kernel_regressions_total{program}``, and the
    ``filodb_kernel_regressed{program}`` level flips to 1 until the EWMA
    recovers below the factor (re-armed — the recompile-storm episode
    discipline).  Baselines persist through an attached store (the
    standalone server wires the metastore KV) so a restart does not
    relearn a regressed program's slow state as its baseline: the
    persisted (healthy) floor wins.

    Deterministic fault hook: ``set_fault_delay(program, s)`` sleeps
    inside the sampled timing region — the injection point
    ``integrity/faultinject.py`` drives for the sentry chaos tests.
    """

    def __init__(self, sample_1_in: int = 64,
                 hbm_roof_bytes_per_s: float = 819e9,
                 regression_factor: float = 1.5,
                 regression_window_s: float = 30.0,
                 baseline_min_samples: int = 8,
                 ewma_alpha: float = 0.25):
        self.sample_1_in = int(sample_1_in)
        self.hbm_roof_bytes_per_s = float(hbm_roof_bytes_per_s)
        self.regression_factor = float(regression_factor)
        self.regression_window_s = float(regression_window_s)
        self.baseline_min_samples = int(baseline_min_samples)
        self.ewma_alpha = float(ewma_alpha)
        self._lock = threading.Lock()
        self._rows: dict[str, dict] = {}
        self._fault: dict[str, float] = {}
        # baseline persistence hooks (standalone wires the metastore KV)
        self._baseline_save: Optional[Callable[[str, float], None]] = None

    def configure(self, sample_1_in: Optional[int] = None,
                  hbm_roof_bytes_per_s: Optional[float] = None,
                  regression_factor: Optional[float] = None,
                  regression_window_s: Optional[float] = None,
                  baseline_min_samples: Optional[int] = None) -> None:
        with self._lock:
            if sample_1_in is not None:
                # 0 disables sampling entirely; 1 = time every launch
                self.sample_1_in = max(0, int(sample_1_in))
            if hbm_roof_bytes_per_s is not None:
                self.hbm_roof_bytes_per_s = max(1.0,
                                                float(hbm_roof_bytes_per_s))
            if regression_factor is not None:
                self.regression_factor = max(1.01,
                                             float(regression_factor))
            if regression_window_s is not None:
                self.regression_window_s = max(0.0,
                                               float(regression_window_s))
            if baseline_min_samples is not None:
                self.baseline_min_samples = max(1,
                                                int(baseline_min_samples))

    def attach_baseline_store(self, load_fn: Optional[Callable] = None,
                              save_fn: Optional[Callable] = None) -> None:
        """Wire baseline persistence: ``load_fn() -> {program: seconds}``
        merged in now (min wins — a persisted healthy floor beats a
        freshly-relearned slow state), ``save_fn(program, seconds)``
        called on seed/ratchet (rate-limited to >=5% improvements)."""
        stored: dict = {}
        if load_fn is not None:
            try:
                stored = {str(k): float(v)
                          for k, v in (load_fn() or {}).items()}
            except Exception:  # noqa: BLE001 — a broken store loses
                stored = {}   # persistence, never serving
        with self._lock:
            self._baseline_save = save_fn
            for program, sec in stored.items():
                row = self._row_locked(program)
                if row["baseline"] is None or sec < row["baseline"]:
                    row["baseline"] = sec
                row["persisted_baseline"] = sec

    # ------------------------------------------------------- fault hook

    def set_fault_delay(self, program: str, seconds: float) -> None:
        with self._lock:
            self._fault[program] = float(seconds)

    def clear_fault_delay(self, program: str) -> None:
        with self._lock:
            self._fault.pop(program, None)

    # ----------------------------------------------------------- ledger

    def _row_locked(self, program: str) -> dict:
        row = self._rows.get(program)
        if row is None:
            row = self._rows[program] = {
                "launches": 0, "sampled": 0, "seconds": 0.0,
                "ewma": None, "hist": [0] * (len(_KHIST_EDGES) + 1),
                "last_key": "", "bytes": 0,
                "baseline": None, "persisted_baseline": None,
                "over_since": None, "regressed": False, "episodes": 0,
            }
        return row

    def tick(self, program: str) -> bool:
        """Count one launch; True when this launch should be sampled."""
        n = self.sample_1_in
        with self._lock:
            row = self._row_locked(program)
            row["launches"] += 1
            launch = row["launches"]
        device_metrics()["kernel_launches"].inc(program=program)
        return n > 0 and (launch - 1) % n == 0

    def note_bytes(self, program: str, nbytes: int) -> None:
        """Attribute the HBM bytes a serving program read (devicestore's
        per-plan hbm_read_bytes notes) — the numerator of the live
        achieved-bytes/s join.  Gated on the kill switch like the
        wrapper: with devicewatch off, launches freeze, and bytes
        accumulating against a frozen launch count would permanently
        inflate achieved-bytes/s after a disable/enable cycle."""
        if not _ENABLED or nbytes <= 0:
            return
        with self._lock:
            self._row_locked(program)["bytes"] += int(nbytes)

    def sample(self, program: str, out, t0: float,
               args: tuple = (), kwargs: Optional[dict] = None) -> None:
        """Time a sampled launch: wait for the result on device, fold
        the wall seconds since ``t0`` (the pre-dispatch stamp).  Runs
        OUTSIDE the timer lock — the wait can be milliseconds."""
        with self._lock:
            delay = self._fault.get(program)
        if delay:
            time.sleep(delay)   # deterministic faultinject slowdown
        try:
            import jax
            # first-leaf probe (outputs are uniformly concrete or
            # uniformly tracers): a wrapped program invoked inside an
            # OUTER trace returns tracers — trace time, not device time
            leaf = out
            while isinstance(leaf, (tuple, list)) and leaf:
                leaf = leaf[0]
            if isinstance(leaf, dict) and leaf:
                leaf = next(iter(leaf.values()))
            if isinstance(leaf, jax.core.Tracer):
                return
            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — accounting never breaks work
            return
        dt = time.perf_counter() - t0
        # the CHEAP key (shapes + scalars, no dtype formatting): this
        # runs on the serving path per sample, where the full
        # descriptive _shape_key (compile-only) costs ~100us
        self._fold(program, dt, _sampled_key(args, kwargs or {}))

    def _fold(self, program: str, dt: float, shape_key: str) -> None:
        now = time.monotonic()
        fired = recovered = False
        persist = None
        with self._lock:
            row = self._row_locked(program)
            row["sampled"] += 1
            row["seconds"] += dt
            prev = row["ewma"]
            ew = dt if prev is None \
                else prev + self.ewma_alpha * (dt - prev)
            row["ewma"] = ew
            row["hist"][bisect.bisect_left(_KHIST_EDGES, dt)] += 1
            row["last_key"] = shape_key
            base = row["baseline"]
            if base is None:
                if row["sampled"] >= self.baseline_min_samples:
                    row["baseline"] = base = ew          # seed
            elif ew < base and row["sampled"] >= self.baseline_min_samples:
                # ratchet DOWN — but only once the EWMA has warmed: a
                # restart resets the EWMA, so the FIRST sample (ew = dt
                # exactly) of a mixed-shape program could otherwise
                # ratchet a loaded healthy baseline down to one tiny
                # query's time and page every normal launch thereafter
                row["baseline"] = base = ew
            last = row["persisted_baseline"]
            if base is not None and (last is None or base < last * 0.95):
                row["persisted_baseline"] = base
                persist = base
            if base is not None:
                if ew >= self.regression_factor * base:
                    if row["over_since"] is None:
                        row["over_since"] = now
                    elif not row["regressed"] and now - row["over_since"] \
                            >= self.regression_window_s:
                        row["regressed"] = True
                        row["episodes"] += 1
                        fired = True
                else:
                    row["over_since"] = None
                    if row["regressed"]:
                        row["regressed"] = False
                        recovered = True
            bytes_total = row["bytes"]
            launches = row["launches"]
            save = self._baseline_save
        m = device_metrics()
        m["kernel_seconds"].inc(dt, program=program)
        if bytes_total and launches and ew > 0:
            m["kernel_roofline"].set(
                (bytes_total / launches) / ew / self.hbm_roof_bytes_per_s,
                program=program)
        if persist is not None:
            # seeding also exports the regressed=0 level row so the
            # alert rules see the healthy state before any episode.
            # A fire is impossible here: persist only happens on
            # seed (base = ew) or ratchet-down (ew < base), and both
            # contradict ew >= factor * base.
            m["kernel_regressed"].set(0.0, program=program)
            if save is not None:
                try:
                    save(program, persist)
                except Exception:  # noqa: BLE001 — persistence is
                    pass           # best-effort, never serving-fatal
        if fired:
            m["kernel_regressions"].inc(program=program)
            m["kernel_regressed"].set(1.0, program=program)
            FLIGHT.record("kernel.regression", program=program,
                          ewma_s=round(ew, 6),
                          baseline_s=round(base, 6),
                          factor=self.regression_factor)
            _LOG.warning(
                "kernel regression: program %r EWMA device time %.6fs "
                "is >= %.2fx its learned baseline %.6fs (sustained "
                "%.1fs) — check /admin/kernels for the roofline "
                "position and /admin/device for recompile storms",
                program, ew, self.regression_factor, base,
                self.regression_window_s)
        if recovered:
            m["kernel_regressed"].set(0.0, program=program)
            FLIGHT.record("kernel.recovery", program=program,
                          ewma_s=round(ew, 6),
                          baseline_s=round(base, 6))
        self._note_query_program(program, dt)

    @staticmethod
    def _note_query_program(program: str, dt: float) -> None:
        """Attribute a sampled launch's device seconds to the query
        running on this thread (QueryStats.devicePrograms split)."""
        try:
            from filodb_tpu.query.exec import active_exec_ctx
            ctx = active_exec_ctx()
            if ctx is not None:
                ctx.note_device_program(program, dt)
        except Exception:  # noqa: BLE001 — accounting never breaks work
            pass

    # ---------------------------------------------------------- reading

    def table(self) -> list[dict]:
        """The /admin/kernels ledger rows, most-launched first."""
        roof = self.hbm_roof_bytes_per_s
        with self._lock:
            rows = []
            for program, r in self._rows.items():
                ew = r["ewma"]
                achieved = None
                if r["bytes"] and r["launches"] and ew:
                    achieved = (r["bytes"] / r["launches"]) / ew
                rows.append({
                    "program": program,
                    "launches": r["launches"],
                    "sampled": r["sampled"],
                    "device_seconds": round(r["seconds"], 6),
                    "ewma_device_s": round(ew, 6) if ew is not None
                    else None,
                    "bytes_total": r["bytes"],
                    "achieved_bytes_per_s": round(achieved, 1)
                    if achieved is not None else None,
                    "roofline_fraction": round(achieved / roof, 6)
                    if achieved is not None else None,
                    "baseline_s": round(r["baseline"], 6)
                    if r["baseline"] is not None else None,
                    "regressed": r["regressed"],
                    "episodes": r["episodes"],
                    "last_shape_key": r["last_key"],
                    "seconds_histogram": {
                        ("+Inf" if i == len(_KHIST_EDGES)
                         else repr(_KHIST_EDGES[i])): n
                        for i, n in enumerate(r["hist"]) if n},
                })
        rows.sort(key=lambda r: -r["launches"])
        return rows


KERNEL_TIMER = KernelTimer()


def kernel_summary() -> dict:
    """The /admin/kernels payload: the sampled device-time ledger joined
    with the compile table (one row per program carries launches,
    compiles, EWMA device time, achieved GB/s, roofline %, sentry
    state)."""
    compiles = {r["program"]: r for r in COMPILE_WATCH.table()}
    rows = KERNEL_TIMER.table()
    for row in rows:
        c = compiles.get(row["program"])
        row["compiles"] = c["compiles"] if c else 0
        row["compile_seconds"] = c["compile_seconds"] if c else 0.0
        row["storms"] = c["storms"] if c else 0
    return {
        "enabled": _ENABLED,
        "sample_1_in": KERNEL_TIMER.sample_1_in,
        "hbm_roof_bytes_per_s": KERNEL_TIMER.hbm_roof_bytes_per_s,
        "regression": {
            "factor": KERNEL_TIMER.regression_factor,
            "window_s": KERNEL_TIMER.regression_window_s,
            "baseline_min_samples": KERNEL_TIMER.baseline_min_samples,
        },
        "programs": rows,
    }


def _sampled_key(args: tuple, kwargs: dict) -> str:
    """Cheap shape key for SAMPLED launches: leaf shapes + small
    scalars, no dtype formatting — runs on the serving path once per
    sample, so it must stay in the tens of microseconds (the full
    descriptive :func:`_shape_key` is compile-only)."""
    try:
        from jax import tree_util
        leaves, _ = tree_util.tree_flatten((args, kwargs))
        parts = []
        for leaf in leaves[:32]:
            shape = getattr(leaf, "shape", None)
            parts.append(str(shape) if shape is not None
                         else str(leaf)[:16])
        if len(leaves) > 32:
            parts.append(f"...+{len(leaves) - 32}")
        return ";".join(parts)
    except Exception:  # noqa: BLE001 — key is best-effort description
        return "?"


def _shape_key(args: tuple, kwargs: dict) -> str:
    """Descriptive abstract-shape key, computed ONLY when a compile was
    detected (never on the cached hot path)."""
    try:
        from jax import tree_util
        leaves, treedef = tree_util.tree_flatten((args, kwargs))
        parts = []
        for leaf in leaves[:32]:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is not None and dtype is not None:
                parts.append(f"{dtype}[{','.join(map(str, shape))}]")
            else:
                parts.append(repr(leaf)[:32])
        if len(leaves) > 32:
            parts.append(f"...+{len(leaves) - 32}")
        return ";".join(parts)
    except Exception:  # noqa: BLE001 — key is best-effort description
        return "?"


def jit(fn=None, *, program: Optional[str] = None, **jit_kwargs):
    """Drop-in ``jax.jit`` replacement with compile telemetry.

    Usable exactly like the sites it replaces::

        @functools.partial(devicewatch.jit, static_argnames=("q",))
        def prog(...): ...
        staged = devicewatch.jit(fn)

    Compile detection reads the jitted callable's cache size (one
    attribute call per invocation; no argument hashing), so the wrapper
    adds ~1us to the hot path.  On jax builds without ``_cache_size``
    telemetry degrades to pass-through rather than guessing."""
    if fn is None:
        return functools.partial(jit, program=program, **jit_kwargs)
    import jax
    jitted = jax.jit(fn, **jit_kwargs)
    name = program or getattr(fn, "__name__", None) or repr(fn)
    cache_size = getattr(jitted, "_cache_size", None)
    if cache_size is None:  # pragma: no cover - older/newer jax API drift
        return jitted

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        if not _ENABLED:
            return jitted(*a, **kw)
        before = cache_size()
        sampled = KERNEL_TIMER.tick(name)
        t0 = time.perf_counter()
        out = jitted(*a, **kw)
        if cache_size() > before:
            COMPILE_WATCH.note_compile(name, time.perf_counter() - t0,
                                       _shape_key(a, kw))
        elif sampled:
            # never fold a compiling launch: its wall time is host
            # trace+compile work, already on the compile telemetry —
            # a cold-start sample would poison the device-time EWMA
            # (and seed the sentry baseline from compile noise)
            KERNEL_TIMER.sample(name, out, t0, a, kw)
        return out

    wrapper._jitted = jitted   # AOT escape hatch (lower/trace)
    # the ledger key, readable off the callable: consumers that
    # attribute bytes to a program (devicestore._note_kernel_bytes)
    # derive the name from HERE instead of repeating the literal, so a
    # rename cannot decouple the bytes/launches join
    wrapper._program = name
    return wrapper


# ---------------------------------------------------------------------------
# 3. Flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded lock-free ring of recent structured events.

    ``record`` is a counter fetch + one list-slot store (both atomic
    under the GIL), safe from any thread on any hot path.  A torn read
    in ``events`` can at worst miss/duplicate the oldest slot — fine
    for a postmortem buffer, and why there is no lock to convoy on."""

    def __init__(self, capacity: int = 2048):
        self._cap = max(16, int(capacity))
        self._buf: list = [None] * self._cap
        self._ctr = itertools.count()

    def resize(self, capacity: int) -> None:
        """Replace the ring (standalone config / POST /admin/config);
        old events are kept up to the new capacity.  The new buffer is
        fully built before any shared state is swapped, and record()
        indexes a local snapshot, so concurrent records during a resize
        can at worst land in the retiring buffer — never out of
        bounds."""
        events = self.events()
        cap = max(16, int(capacity))
        buf = [None] * cap
        ctr = itertools.count(len(events))
        for i, ev in enumerate(events[-cap:]):
            buf[i % cap] = (ev["t_s"], i, ev["kind"],
                            {k: v for k, v in ev.items()
                             if k not in ("t_s", "seq", "kind")})
        self._buf, self._ctr, self._cap = buf, ctr, cap

    @property
    def capacity(self) -> int:
        return self._cap

    def record(self, kind: str, **fields) -> None:
        if not _ENABLED:
            return
        i = next(self._ctr)
        buf = self._buf       # snapshot: a concurrent resize swaps the
        buf[i % len(buf)] = (time.time(), i, kind, fields)  # whole list

    def events(self, limit: Optional[int] = None,
               kind: Optional[str] = None) -> list[dict]:
        """Oldest-first JSON-safe dump."""
        rows = [e for e in list(self._buf) if e is not None]
        rows.sort(key=lambda e: e[1])
        if kind is not None:
            rows = [e for e in rows if e[2] == kind]
        if limit is not None:
            rows = rows[-int(limit):]
        return [{"t_s": t, "seq": seq, "kind": k, **fields}
                for t, seq, k, fields in rows]

    def dump_to_log(self, reason: str, limit: int = 200) -> None:
        """The black box hits the ground: write the recent event tail to
        the log (integrity failure / unhandled-exception shutdown)."""
        try:
            events = self.events(limit=limit)
            lines = [f"flight recorder dump ({reason}): "
                     f"{len(events)} recent events"]
            for ev in events:
                fields = " ".join(f"{k}={v}" for k, v in ev.items()
                                  if k not in ("t_s", "seq", "kind"))
                lines.append(f"  [{ev['t_s']:.3f}] #{ev['seq']} "
                             f"{ev['kind']} {fields}")
            _LOG.error("%s", "\n".join(lines))
        except Exception:  # noqa: BLE001 — the black box must never throw
            pass


FLIGHT = FlightRecorder()

_CRASH_HOOKS_INSTALLED = False


def install_crash_hooks() -> None:
    """Dump the flight recorder on unhandled exceptions (main thread and
    worker threads) before the previous hook runs — the reference's
    "what was the system doing in the seconds before the crash"."""
    global _CRASH_HOOKS_INSTALLED
    if _CRASH_HOOKS_INSTALLED:
        return
    _CRASH_HOOKS_INSTALLED = True
    import sys

    prev_sys = sys.excepthook

    def _sys_hook(exc_type, exc, tb):
        FLIGHT.dump_to_log(f"unhandled {exc_type.__name__}")
        prev_sys(exc_type, exc, tb)

    sys.excepthook = _sys_hook
    prev_thread = threading.excepthook

    def _thread_hook(args):
        FLIGHT.dump_to_log(
            f"unhandled {args.exc_type.__name__} in thread "
            f"{getattr(args.thread, 'name', '?')}")
        prev_thread(args)

    threading.excepthook = _thread_hook


def configure(conf: Optional[dict] = None) -> None:
    """Apply the standalone ``"devicewatch"`` config block:
    ``{"enabled": bool, "flight-recorder-size": int,
    "jit-storm-shapes": int, "jit-storm-window-s": float,
    "kernel-sample-1-in": int (0 disables sampling),
    "hbm-roof-bytes-per-s": float,
    "kernel-regression-factor": float,
    "kernel-regression-window-s": float,
    "kernel-baseline-min-samples": int}``."""
    conf = conf or {}
    if "enabled" in conf:
        from filodb_tpu.core.storeconfig import parse_bool
        set_enabled(parse_bool(conf["enabled"]))
    if "flight-recorder-size" in conf:
        FLIGHT.resize(int(conf["flight-recorder-size"]))
    COMPILE_WATCH.configure(
        storm_shapes=conf.get("jit-storm-shapes"),
        storm_window_s=conf.get("jit-storm-window-s"))
    KERNEL_TIMER.configure(
        sample_1_in=conf.get("kernel-sample-1-in"),
        hbm_roof_bytes_per_s=conf.get("hbm-roof-bytes-per-s"),
        regression_factor=conf.get("kernel-regression-factor"),
        regression_window_s=conf.get("kernel-regression-window-s"),
        baseline_min_samples=conf.get("kernel-baseline-min-samples"))


# ---------------------------------------------------------------------------
# /admin/device summary
# ---------------------------------------------------------------------------


def device_summary() -> dict:
    """The process-wide device-resource view: ledger tree, pools,
    per-device reconciliation, compile table, storm state.  The HTTP
    layer adds per-dataset arena budgets (it owns the bindings)."""
    return {
        "enabled": _ENABLED,
        "ledger": {
            "owners": LEDGER.owners(),
            "total_bytes": LEDGER.total_bytes(),
            "pools": LEDGER.pools(),
        },
        "devices": LEDGER.reconcile(),
        "compile": {
            "programs": COMPILE_WATCH.table(),
            "active_storms": COMPILE_WATCH.active_storms(),
            "storm_shapes": COMPILE_WATCH.storm_shapes,
            "storm_window_s": COMPILE_WATCH.storm_window_s,
        },
        "flight_recorder": {"capacity": FLIGHT.capacity},
    }
