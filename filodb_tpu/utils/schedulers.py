"""Thread-discipline assertions.

Capability match for the reference's FiloSchedulers (reference:
core/src/main/scala/filodb.core/FiloSchedulers.scala —
assertThreadName gated by ``filodb.scheduler.enable-assertions``, used
pervasively to catch work running on the wrong scheduler, e.g.
TimeSeriesShard.scala:532,757 asserting the ingest thread and
ExecPlan.scala:109,124 asserting the query pool).  The single-writer-
per-shard discipline (SURVEY.md §2.7 item 4) is enforced the same way:
cheap no-ops in production, hard failures in tests/debug runs.
"""

from __future__ import annotations

import os
import threading

INGEST_PREFIX = "ingest-"
QUERY_PREFIX = "query-"

_enabled = os.environ.get("FILODB_TPU_ASSERT_THREADS", "0") != "0"


def enable_assertions(on: bool = True) -> None:
    global _enabled
    _enabled = on


def assertions_enabled() -> bool:
    return _enabled


class WrongThreadError(AssertionError):
    pass


def assert_thread_name(prefix: str) -> None:
    """Fail if the current thread's name doesn't carry the expected
    prefix (reference: FiloSchedulers.assertThreadName)."""
    if not _enabled:
        return
    name = threading.current_thread().name
    if not name.startswith(prefix):
        raise WrongThreadError(
            f"expected a {prefix!r}* thread, but running on {name!r}")


def ingest_check_for(dataset: str, shard: int):
    """The hook installed as TimeSeriesShard.ingest_sched_check: ingest
    must only run on that shard's dedicated ingest thread."""
    expected = f"{INGEST_PREFIX}{dataset}-{shard}"

    def check() -> None:
        if not _enabled:
            return
        name = threading.current_thread().name
        if name != expected:
            raise WrongThreadError(
                f"shard {dataset}/{shard} ingest ran on thread {name!r}, "
                f"expected {expected!r} (single-writer-per-shard)")
    return check
