"""Query forensics: recent trace trees, slow-query log, on-demand profiler.

Grows the orphaned tracing layer (utils/observability.py) into the
subsystem the reference operates with: Kamon's span reporters feed a
trace view, the SpanLogReporter surfaces slow operations, and
SimpleProfiler answers "where is the time going right now"
(reference: KamonLogger.scala:146, SimpleProfiler.java).

Everything here is bounded and lock-cheap: the query path only appends
span records; trees are assembled at read time (/admin endpoints)."""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Optional

from filodb_tpu.utils.observability import (SpanRecord, TRACER,
                                            query_metrics)


def span_to_dict(rec: SpanRecord) -> dict:
    """JSON-safe span for the /execplan response and admin endpoints."""
    return {"name": rec.name, "start_s": rec.start_s,
            "duration_s": rec.duration_s,
            "tags": {k: str(v) for k, v in rec.tags.items()},
            "error": rec.error, "trace_id": rec.trace_id,
            "span_id": rec.span_id, "parent_id": rec.parent_id}


def span_from_dict(d: dict) -> SpanRecord:
    return SpanRecord(d.get("name", ""), float(d.get("start_s", 0.0)),
                      float(d.get("duration_s", 0.0)),
                      dict(d.get("tags", {})), None,
                      error=d.get("error"), trace_id=d.get("trace_id"),
                      span_id=d.get("span_id", ""),
                      parent_id=d.get("parent_id"))


class TraceStore:
    """Bounded store of completed spans grouped by trace id.

    Registered as a TRACER reporter: every span carrying a trace id
    lands here (spans without one — background flushes, gateway batches
    outside a query — are skipped).  ``ingest_remote`` merges the spans
    a data node returned with its /execplan response, so the
    coordinator holds ONE stitched tree per scatter-gather query."""

    def __init__(self, max_traces: int = 256, max_spans_per_trace: int = 512,
                 slowlog_size: int = 128,
                 slow_threshold_s: float = 1.0,
                 sample_rate: float = 0.0):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self.slow_threshold_s = slow_threshold_s
        # head-sampling (ISSUE 19): retain this fraction of NORMAL
        # (sub-threshold) traces too, flagged sampled=true, so the
        # retained set is fleet-representative instead of slow-only.
        # Default 0 (off); runtime-adjustable via POST /admin/config
        # trace-sample-rate.
        self.sample_rate = float(sample_rate)
        self._traces: collections.OrderedDict[str, list[SpanRecord]] = \
            collections.OrderedDict()
        self._slowlog: collections.deque = collections.deque(
            maxlen=slowlog_size)
        self._lock = threading.Lock()

    # -------------------------------------------------------------- writes

    def report(self, rec: SpanRecord) -> None:
        """TRACER reporter hook (exceptions are swallowed upstream)."""
        if not rec.trace_id:
            return
        with self._lock:
            spans = self._traces.get(rec.trace_id)
            if spans is None:
                spans = self._traces[rec.trace_id] = []
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            if len(spans) < self.max_spans_per_trace:
                spans.append(rec)

    def ingest_remote(self, trace_id: str, spans: list[dict]) -> None:
        """Merge spans shipped back by a remote /execplan execution.
        Dedup by span id UNDER the lock: a node serving several leaves
        of one query returns its whole per-trace span set with each
        response, and two dispatch threads may merge concurrently."""
        recs = []
        for d in spans:
            try:
                rec = span_from_dict(d)
            except (TypeError, ValueError):
                continue
            rec.trace_id = trace_id
            recs.append(rec)
        with self._lock:
            cur = self._traces.get(trace_id)
            if cur is None:
                cur = self._traces[trace_id] = []
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            have = {r.span_id for r in cur}
            for rec in recs:
                if rec.span_id and rec.span_id in have:
                    continue
                if len(cur) >= self.max_spans_per_trace:
                    break
                cur.append(rec)
                have.add(rec.span_id)

    def note_complete(self, trace_id: Optional[str], duration_s: float,
                      query: str = "", dataset: str = "",
                      error: Optional[str] = None) -> None:
        """Called once per finished query at the entry point; slow ones
        keep their whole span tree in the slow-query ring.  Fast ones
        are head-sampled at ``sample_rate`` (flagged sampled=true) so a
        low always-on fraction of NORMAL traces is retained too."""
        if not trace_id:
            return
        sampled = False
        if duration_s < self.slow_threshold_s:
            rate = self.sample_rate
            if rate <= 0.0 or random.random() >= rate:
                return
            sampled = True
        else:
            try:
                query_metrics()["slow_queries"].inc(dataset=dataset)
            except Exception:  # noqa: BLE001 — forensics never fails a query
                pass
        entry = {"trace_id": trace_id, "query": query, "dataset": dataset,
                 "duration_s": duration_s, "when_s": time.time(),
                 "error": error, "sampled": sampled,
                 "tree": self.tree(trace_id)}
        try:
            # a slow query DURING a recompile storm is usually slow
            # BECAUSE of it: flag the programs so the operator reading
            # /admin/slowlog doesn't chase the wrong stage (ISSUE 4)
            from filodb_tpu.utils.devicewatch import COMPILE_WATCH
            storms = COMPILE_WATCH.active_storms()
            if storms:
                entry["recompile_storms"] = storms
        except Exception:  # noqa: BLE001 — forensics never fails a query
            pass
        with self._lock:
            self._slowlog.append(entry)

    # --------------------------------------------------------------- reads

    def spans_for(self, trace_id: str) -> list[SpanRecord]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def trace_ids(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    def tree(self, trace_id: str) -> list[dict]:
        """Spans nested by parent span id.  Spans whose parent is not in
        the trace (or None) are roots; remote subtrees therefore hang
        off the coordinator's dispatch span that minted their parent."""
        spans = self.spans_for(trace_id)
        by_id = {}
        for rec in spans:
            d = span_to_dict(rec)
            d["children"] = []
            by_id[rec.span_id] = d
        roots = []
        for rec in spans:
            node = by_id[rec.span_id]
            parent = by_id.get(rec.parent_id) if rec.parent_id else None
            if parent is not None and parent is not node:
                parent["children"].append(node)
            else:
                roots.append(node)
        for d in by_id.values():
            d["children"].sort(key=lambda c: c["start_s"])
        roots.sort(key=lambda c: c["start_s"])
        return roots

    def slowlog(self) -> list[dict]:
        with self._lock:
            return list(self._slowlog)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._slowlog.clear()


TRACE_STORE = TraceStore()
TRACER.add_reporter(TRACE_STORE.report)


# ONE single-flight guard for BOTH profile surfaces (/debug/profilez
# host stack sampling AND /debug/device_profilez jax device traces): a
# host sampling run and a device trace capture interleaving would
# attribute each other's overhead to the profiled workload (ISSUE 15)
_PROFILE_LOCK = threading.Lock()


class ProfilerBusy(RuntimeError):
    """A profile run is already in flight (single-flight guard)."""


class DeviceProfilerUnavailable(RuntimeError):
    """jax's profiler cannot run here (no jax / backend refused)."""


def profile(seconds: float = 2.0, sample_interval_s: float = 0.005,
            top_k: int = 30) -> dict:
    """Run the sampling profiler for ``seconds`` and return aggregated
    hot frames (the /debug/profilez payload; reference: SimpleProfiler
    launched at server start, here on demand).  Single-flight: the
    endpoint is unauthenticated and each run costs a sampling thread
    walking every stack, so concurrent requests are refused rather
    than stacked."""
    from filodb_tpu.utils.observability import SimpleProfiler
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise ProfilerBusy("a profile run is already in progress")
    try:
        seconds = max(0.05, min(float(seconds), 60.0))
        prof = SimpleProfiler(sample_interval_s=sample_interval_s,
                              report_interval_s=1e9)
        prof.start()
        time.sleep(seconds)
        prof.stop()
    finally:
        _PROFILE_LOCK.release()
    counts = prof.snapshot()
    total = max(1, prof._samples)
    frames = [{"file": f.rsplit("/", 1)[-1], "function": fn,
               "samples": n, "pct": round(100.0 * n / total, 2)}
              for (f, fn), n in sorted(counts.items(),
                                       key=lambda kv: -kv[1])[:top_k]]
    return {"seconds": seconds, "samples": total, "frames": frames}


# how many device trace capture dirs to retain under the trace root:
# XLA traces of a busy device run tens to hundreds of MB and the host
# profiler's sibling endpoint writes nothing, so an unbounded capture
# dir would let a polling script fill the server's disk over a long
# incident — oldest captures are pruned before each new one
DEVICE_TRACE_RETAIN = 8


def device_profile(seconds: float = 2.0,
                   trace_root: Optional[str] = None) -> dict:
    """Capture a ``jax.profiler`` device trace for ``seconds`` into a
    server-side directory and return its path (the
    ``/debug/device_profilez`` payload; ISSUE 15) — the exact hook a
    training/inference stack needs to see what the accelerator actually
    executed (XLA program timeline, per-op device time), where the host
    profiler above only sees the Python frames waiting on it.

    Single-flight on the SAME ``_PROFILE_LOCK`` as :func:`profile`:
    the two captures interleaving would attribute each other's
    overhead.  The sleep inside the held lock is the design — the lock
    IS the "one profile at a time" contract, acquired non-blocking so
    contenders get ``ProfilerBusy`` (HTTP 503) instead of queueing."""
    import os
    import tempfile
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise ProfilerBusy("a profile run is already in progress")
    try:
        seconds = max(0.05, min(float(seconds), 60.0))
        try:
            import jax
            profiler = jax.profiler
        except Exception as e:  # noqa: BLE001 — host-only deployment
            raise DeviceProfilerUnavailable(
                f"jax profiler unavailable: {e}") from e
        root = trace_root or os.path.join(tempfile.gettempdir(),
                                          "filodb-device-traces")
        os.makedirs(root, exist_ok=True)
        _prune_trace_dirs(root, keep=DEVICE_TRACE_RETAIN - 1)
        path = tempfile.mkdtemp(
            prefix=time.strftime("trace-%Y%m%d-%H%M%S-"), dir=root)
        try:
            profiler.start_trace(path)
        except Exception as e:  # noqa: BLE001 — backend refused
            raise DeviceProfilerUnavailable(
                f"device trace capture failed to start: {e}") from e
        try:
            time.sleep(seconds)
        finally:
            try:
                profiler.stop_trace()
            except Exception:  # noqa: BLE001 — capture dir still useful
                pass
        files = sum(len(fs) for _r, _d, fs in os.walk(path))
        return {"seconds": seconds, "trace_dir": path, "files": files,
                "retained": DEVICE_TRACE_RETAIN}
    finally:
        _PROFILE_LOCK.release()


def _prune_trace_dirs(root: str, keep: int) -> None:
    """Drop the oldest capture dirs so at most ``keep`` remain (the
    timestamped ``trace-*`` prefix makes lexical order chronological).
    Runs under the profile lock, so captures never race the sweep."""
    import os
    import shutil
    try:
        dirs = sorted(e for e in os.listdir(root)
                      if e.startswith("trace-")
                      and os.path.isdir(os.path.join(root, e)))
    except OSError:
        return
    for name in dirs[:max(0, len(dirs) - max(0, keep))]:
        try:
            shutil.rmtree(os.path.join(root, name))
        except OSError:  # noqa: PERF203 — an operator mid-copy wins
            pass
