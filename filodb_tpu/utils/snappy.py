"""Pure-Python snappy block format (compress + decompress).

The Prometheus remote-read/write protocol frames its protobuf payloads
with raw snappy block compression (no framing format).  No snappy
module may be installed in this environment, so this is a from-scratch
implementation of the block format spec
(github.com/google/snappy/blob/main/format_description.txt):

- preamble: varint uncompressed length
- elements: tag byte, low 2 bits select the type
    00 literal  (len-1 in tag>>2; 60..63 mean 1..4 extra length bytes)
    01 copy     (len = 4 + ((tag>>2) & 7), offset = ((tag>>5) << 8) | byte)
    10 copy     (len = (tag>>2) + 1, offset = 2-byte LE)
    11 copy     (len = (tag>>2) + 1, offset = 4-byte LE)

The compressor is a greedy single-pass matcher with a 4-byte hash table
(the same shape as the C implementation's fast path, minus tuning); it
round-trips with the reference decompressor and compresses repetitive
label sets well — exact output bytes differ from C snappy, which is fine:
the format, not the compressor, is the contract.
"""

from __future__ import annotations

from filodb_tpu.utils.leb128 import decode as _uvarint_decode
from filodb_tpu.utils.leb128 import encode as _uvarint_encode


def decompress(buf: bytes, max_len: int = 1 << 32) -> bytes:
    """Decompress one snappy block.  ``max_len`` bounds the declared
    uncompressed size (copy elements amplify ~21x, so callers handling
    untrusted input must cap this)."""
    want, pos = _uvarint_decode(buf, 0)
    if want > max_len:
        raise ValueError("declared length too large")
    out = bytearray()
    n = len(buf)
    while pos < n:
        tag = buf[pos]
        pos += 1
        typ = tag & 3
        if typ == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                if pos + extra > n:
                    raise ValueError("truncated literal length")
                ln = int.from_bytes(buf[pos:pos + extra], "little")
                pos += extra
            ln += 1
            if pos + ln > n:
                raise ValueError("truncated literal")
            out += buf[pos:pos + ln]
            pos += ln
            continue
        if typ == 1:
            if pos >= n:
                raise ValueError("truncated copy1")
            ln = 4 + ((tag >> 2) & 0x7)
            off = ((tag >> 5) << 8) | buf[pos]
            pos += 1
        elif typ == 2:
            if pos + 2 > n:
                raise ValueError("truncated copy2")
            ln = (tag >> 2) + 1
            off = int.from_bytes(buf[pos:pos + 2], "little")
            pos += 2
        else:
            if pos + 4 > n:
                raise ValueError("truncated copy4")
            ln = (tag >> 2) + 1
            off = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise ValueError("copy offset out of range")
        start = len(out) - off
        if ln <= off:
            # non-overlapping: one slice copy
            out += out[start:start + ln]
        else:
            # overlapping copy == repeat the off-byte pattern (RLE-style)
            pattern = bytes(out[start:start + off])
            out += (pattern * (ln // off + 1))[:ln]
    if len(out) != want:
        raise ValueError(f"length mismatch: got {len(out)}, want {want}")
    return bytes(out)


def _emit_literal(out: bytearray, data: memoryview, start: int, end: int) -> None:
    ln = end - start
    if ln == 0:
        return
    ln1 = ln - 1
    if ln1 < 60:
        out.append(ln1 << 2)
    else:
        nbytes = (ln1.bit_length() + 7) // 8
        out.append((59 + nbytes) << 2)
        out += ln1.to_bytes(nbytes, "little")
    out += data[start:end]


def _emit_copy(out: bytearray, off: int, ln: int) -> None:
    # prefer copy2 (covers len<=64, off<=65535); chunk longer matches
    while ln >= 68:
        out.append((63 << 2) | 2)
        out += off.to_bytes(2, "little")
        ln -= 64
    if ln > 64:
        out.append((59 << 2) | 2)   # 60-byte copy, leave >=4 remainder
        out += off.to_bytes(2, "little")
        ln -= 60
    if 4 <= ln <= 11 and off < 2048:
        out.append(((off >> 8) << 5) | ((ln - 4) << 2) | 1)
        out.append(off & 0xFF)
    else:
        out.append(((ln - 1) << 2) | 2)
        out += off.to_bytes(2, "little")


def compress(data: bytes) -> bytes:
    """Compress one snappy block (greedy 4-byte hash matcher)."""
    n = len(data)
    out = bytearray(_uvarint_encode(n))
    if n < 4:
        _emit_literal(out, memoryview(data), 0, n)
        return bytes(out)
    mv = memoryview(data)
    table: dict[bytes, int] = {}
    lit_start = 0
    i = 0
    limit = n - 4
    while i <= limit:
        key = bytes(mv[i:i + 4])
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF:
            # extend the match
            ln = 4
            max_ln = n - i
            while ln < max_ln and data[cand + ln] == data[i + ln]:
                ln += 1
            _emit_literal(out, mv, lit_start, i)
            _emit_copy(out, i - cand, ln)
            i += ln
            lit_start = i
        else:
            i += 1
    _emit_literal(out, mv, lit_start, n)
    return bytes(out)
