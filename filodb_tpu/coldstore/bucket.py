"""S3-shaped object bucket: the cold tier's storage primitive.

Four verbs — ``put_object`` / ``get_object`` / ``list_objects`` /
``delete_object`` — deliberately shaped like an S3 client so a real
object-store backend slots in without touching ``ColdChunkStore``.
Objects are immutable blobs under flat string keys; there is no
rename, no append, no partial read.

``get_object`` takes a REQUIRED keyword-only ``timeout_s``: every
fetch is a network hop in the real deployment, and the filolint
deadline-threading rule enforces that each call-site derives that
timeout from the query's remaining budget (never a bare constant, and
never ``None``).  A fetch that cannot finish inside the budget raises
:class:`BucketTimeout` — the loud refusal path, never a wedge.

``LocalFSBucket`` is the bundled implementation: one file per object
under a root directory, atomic puts via tmp + rename.  It also hosts
the chaos hooks the cold-path fault-injection tests drive:

* ``stall_s`` — every get sleeps ``min(stall_s, timeout_s)`` and then
  raises :class:`BucketTimeout` if the stall exceeds the budget,
  emulating a hung object store that honors client-side timeouts.
* byte-level corruption/truncation is done directly on the backing
  file (see tests / integrity.faultinject) — the bucket serves
  whatever bytes are on disk, and the CRC-on-fetch layer above must
  catch it.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Sequence


class BucketTimeout(OSError):
    """An object fetch could not finish inside its deadline-derived
    timeout (stalled backend, exhausted query budget).  Callers treat
    this as a refusal — fail the query loudly — never as data."""


class ObjectMissing(KeyError):
    """The requested key does not exist in the bucket."""


class ObjectBucket:
    """The S3-shaped interface.  All keys are ``/``-separated ASCII
    strings; all values are immutable byte blobs."""

    def put_object(self, key: str, data: bytes) -> None:
        """Store ``data`` under ``key`` (overwrite allowed; must be
        atomic — a reader never observes a torn write)."""
        raise NotImplementedError

    def get_object(self, key: str, *, timeout_s: float) -> bytes:
        """Fetch the full object.  ``timeout_s`` is mandatory and must
        come from the caller's remaining budget; raises
        :class:`BucketTimeout` when the fetch cannot finish in time
        and :class:`ObjectMissing` when the key does not exist."""
        raise NotImplementedError

    def list_objects(self, prefix: str) -> list:
        """All ``(key, size_bytes)`` pairs whose key starts with
        ``prefix``, sorted by key.  Metadata-only — no object bodies
        are read."""
        raise NotImplementedError

    def delete_object(self, key: str) -> bool:
        """Delete ``key``; True when it existed."""
        raise NotImplementedError


def _check_key(key: str) -> str:
    if not key or key.startswith("/") or ".." in key.split("/"):
        raise ValueError(f"invalid object key: {key!r}")
    return key


class LocalFSBucket(ObjectBucket):
    """One file per object under ``root``; the bundled cold backend and
    the chaos-test double (a real S3 client implements the same four
    verbs against a remote endpoint)."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        # chaos hook: every get stalls this long (bounded by the
        # caller's timeout) before serving — emulates a hung backend
        self.stall_s = 0.0
        self._write_lock = threading.Lock()

    # -- key <-> path -------------------------------------------------------

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *(_check_key(key).split("/")))

    # -- verbs --------------------------------------------------------------

    def put_object(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp-{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic: readers see old bytes or new, never torn

    def get_object(self, key: str, *, timeout_s: float) -> bytes:
        if timeout_s is None or timeout_s <= 0:
            raise BucketTimeout(
                f"no budget left to fetch {key} (timeout_s={timeout_s})")
        if self.stall_s > 0:
            # honor the client timeout the way a real SDK does: wait at
            # most timeout_s, then give up — the caller's thread is
            # delayed but never wedged past its budget
            time.sleep(min(self.stall_s, timeout_s))
            if self.stall_s >= timeout_s:
                raise BucketTimeout(
                    f"fetch of {key} exceeded its {timeout_s:.3f}s budget "
                    f"(backend stalled)")
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise ObjectMissing(key) from None

    def list_objects(self, prefix: str) -> list:
        _check_key(prefix)
        # walk only the deepest directory the prefix pins down
        parts = prefix.split("/")
        base = os.path.join(self.root, *parts[:-1]) if len(parts) > 1 \
            else self.root
        out = []
        if not os.path.isdir(base):
            return out
        for dirpath, _dirs, files in os.walk(base):
            for name in files:
                if name.startswith(".") or ".tmp-" in name:
                    continue
                path = os.path.join(dirpath, name)
                key = os.path.relpath(path, self.root).replace(os.sep, "/")
                if key.startswith(prefix):
                    try:
                        out.append((key, os.path.getsize(path)))
                    except OSError:
                        continue  # deleted mid-walk
        out.sort()
        return out

    def delete_object(self, key: str) -> bool:
        try:
            os.remove(self._path(key))
            return True
        except FileNotFoundError:
            return False

    # -- chaos helpers (tests) ---------------------------------------------

    def corrupt_object(self, key: str, mode: str = "flip") -> None:
        """Damage the stored bytes in place: ``flip`` xors one payload
        byte, ``truncate`` drops the tail half.  The bucket itself
        stays oblivious — detection belongs to CRC-on-fetch above."""
        path = self._path(key)
        with self._write_lock:
            with open(path, "rb") as f:
                data = bytearray(f.read())
            if mode == "truncate":
                data = data[:max(1, len(data) // 2)]
            else:
                pos = len(data) // 2
                data[pos] ^= 0xFF
            with open(path, "wb") as f:
                f.write(bytes(data))

    def object_keys(self, prefix: str = "") -> list:
        return [k for k, _sz in self.list_objects(prefix)] if prefix \
            else [k for k, _sz in self.list_objects("chunks/")]
