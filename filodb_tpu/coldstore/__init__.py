"""Cold tier: object-store chunk archive beneath the local column store.

Capability match for the reference's Cassandra ChunkSource layer
(PAPER.md layer map: months of history served from a distributed store
beneath the memstore) rebuilt the way modern TSDBs do it — an S3-shaped
object bucket (get/put/list/delete) holding immutable chunk objects,
fronted by the existing local DiskColumnStore as the warm tier:

* :mod:`filodb_tpu.coldstore.bucket` — the ``ObjectBucket`` interface
  and the local-FS implementation (``LocalFSBucket``), plus the fault
  hooks chaos tests drive (stall injection, byte truncation).
* :mod:`filodb_tpu.coldstore.store` — ``ColdChunkStore``, a
  :class:`~filodb_tpu.store.columnstore.ColumnStore` over a bucket
  (CRC verified on EVERY fetch, quarantine intact, deadline-derived
  fetch timeouts), and ``TieredColumnStore`` which merges
  local-then-cold transparently so ODP and the rollup engine never
  know which tier served a chunk.
* :mod:`filodb_tpu.coldstore.ageout` — the retention policy: rows past
  the retention floor move local → bucket (upload, read-back verify,
  THEN delete), with a persisted per-shard watermark the resolution
  router reads as the rolled-local/rolled-cold stitch boundary.
"""

from filodb_tpu.coldstore.bucket import (BucketTimeout, LocalFSBucket,
                                         ObjectBucket, ObjectMissing)
from filodb_tpu.coldstore.store import ColdChunkStore, TieredColumnStore
from filodb_tpu.coldstore.ageout import AgeOutManager

__all__ = [
    "ObjectBucket", "LocalFSBucket", "BucketTimeout", "ObjectMissing",
    "ColdChunkStore", "TieredColumnStore", "AgeOutManager",
]
