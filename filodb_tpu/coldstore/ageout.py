"""Retention-driven tier migration: local sqlite rows → bucket objects.

One pass per (dataset, shard): every chunk row wholly older than
``now - retention`` is uploaded (read-back CRC-verified), and ONLY
then deleted locally — a crash between upload and delete leaves the
row in both tiers, which the TieredColumnStore read path dedupes
(local wins) and the next pass re-uploads idempotently (same key,
same bytes).  Corrupt local rows are quarantined by the verified scan
and stay local: corruption never gets archived as truth.

The per-shard WATERMARK (the cutoff of the last completed pass)
persists in the metastore KV under ``coldstore_ageout:{ds}:{shard}``;
``floor_ms(dataset)`` — the min across shards — is the boundary the
rollup resolution router uses as the rolled-local / rolled-cold
stitch point.  The boundary is attribution-only for correctness: both
stitch legs read through the same TieredColumnStore, so a stale
watermark can misattribute a tier but never change results.
"""

from __future__ import annotations

import logging
import time
from typing import Optional, Sequence

from filodb_tpu.coldstore.store import ColdChunkStore, ColdWriteError

_LOG = logging.getLogger("filodb.coldstore")

KV_PREFIX = "coldstore_ageout:"


class AgeOutManager:
    """Moves aged chunk rows local → cold and tracks the per-shard
    watermarks the stitch router reads."""

    def __init__(self, local, cold: ColdChunkStore, metastore=None,
                 now_ms_fn=None, delete_batch: int = 512) -> None:
        self.local = local
        self.cold = cold
        self.metastore = metastore
        self._now_ms = now_ms_fn or (lambda: int(time.time() * 1000))
        self.delete_batch = delete_batch
        # (dataset, shard) -> cutoff_ms of the last COMPLETED pass
        self._watermarks: dict = {}
        self._loaded_kv = False

    # ---------------------------------------------------------- watermarks

    def _load_kv(self) -> None:
        if self._loaded_kv or self.metastore is None:
            return
        self._loaded_kv = True
        for key, val in self.metastore.list_kv(KV_PREFIX).items():
            try:
                _pfx, ds, shard = key.rsplit(":", 2)
                self._watermarks[(ds, int(shard))] = int(val)
            except ValueError:
                _LOG.warning("ignoring malformed age-out watermark %s=%s",
                             key, val)

    def _set_watermark(self, dataset: str, shard: int, cutoff: int) -> None:
        self._watermarks[(dataset, shard)] = cutoff
        if self.metastore is not None:
            self.metastore.write_kv(f"{KV_PREFIX}{dataset}:{shard}",
                                    str(cutoff))

    def watermark_ms(self, dataset: str, shard: int) -> int:
        """Cutoff of the last completed pass for one shard; 0 = never."""
        self._load_kv()
        return self._watermarks.get((dataset, shard), 0)

    def floor_ms(self, dataset: str) -> int:
        """The dataset's cold boundary: chunks ending before this are
        guaranteed archived on EVERY shard that ever completed a pass —
        the min across recorded shard watermarks, 0 when none exist
        (no cold leg yet)."""
        self._load_kv()
        marks = [wm for (ds, _sh), wm in self._watermarks.items()
                 if ds == dataset]
        return min(marks) if marks else 0

    # ---------------------------------------------------------- passes

    def _shards(self, dataset: str,
                shards: Optional[Sequence[int]]) -> list:
        if shards is not None:
            return list(shards)
        return self.local.list_shards(dataset)

    def plan(self, dataset: str, retention_ms: int,
             shards: Optional[Sequence[int]] = None) -> dict:
        """Dry-run: what a pass WOULD move, metadata-only (no uploads,
        no deletes, no watermark advance)."""
        cutoff = self._now_ms() - retention_ms
        per_shard = []
        total_rows = total_bytes = 0
        for sh in self._shards(dataset, shards):
            rows, nbytes = self.local.count_chunks_aged(dataset, sh, cutoff)
            per_shard.append({"shard": sh, "chunks": rows, "bytes": nbytes,
                              "watermark_ms": self.watermark_ms(dataset, sh)})
            total_rows += rows
            total_bytes += nbytes
        return {"dataset": dataset, "cutoff_ms": cutoff,
                "retention_ms": retention_ms, "shards": per_shard,
                "total_chunks": total_rows, "total_bytes": total_bytes}

    def run(self, dataset: str, retention_ms: int,
            shards: Optional[Sequence[int]] = None) -> dict:
        """One migration pass.  Returns the summary dict; raises on an
        upload/verify failure (the shard's watermark does not advance,
        nothing local was deleted for the failed row)."""
        from filodb_tpu.utils.observability import coldstore_metrics
        m = coldstore_metrics()
        cutoff = self._now_ms() - retention_ms
        per_shard = []
        total_rows = total_bytes = 0
        for sh in self._shards(dataset, shards):
            moved = moved_bytes = 0
            doomed: list = []
            try:
                for (pk, cid, nr, st, et, schema_hash, blob, crc,
                     itime) in self.local.scan_chunk_rows_aged(
                         dataset, sh, cutoff):
                    self.cold.put_chunk_row(
                        dataset, sh, pk, cid, nr, st, et, schema_hash,
                        itime, bytes(blob), crc, verify=True)
                    doomed.append((pk, cid))
                    moved += 1
                    moved_bytes += len(blob)
                    if len(doomed) >= self.delete_batch:
                        self.local.delete_chunk_rows(dataset, sh, doomed)
                        doomed.clear()
            except ColdWriteError:
                # verified rows already uploaded+deleted stay correct;
                # the failed row is still local and the watermark does
                # not advance — next pass retries
                if doomed:
                    self.local.delete_chunk_rows(dataset, sh, doomed)
                raise
            if doomed:
                self.local.delete_chunk_rows(dataset, sh, doomed)
            self._set_watermark(dataset, sh, cutoff)
            if moved:
                m["aged_chunks"].inc(moved, dataset=dataset)
                m["aged_bytes"].inc(moved_bytes, dataset=dataset)
                _LOG.info("aged out %d chunks (%d bytes) %s/%d -> cold "
                          "(cutoff=%d)", moved, moved_bytes, dataset, sh,
                          cutoff)
            m["watermark"].set(cutoff, dataset=dataset, shard=str(sh))
            per_shard.append({"shard": sh, "chunks": moved,
                              "bytes": moved_bytes, "watermark_ms": cutoff})
            total_rows += moved
            total_bytes += moved_bytes
        return {"dataset": dataset, "cutoff_ms": cutoff,
                "retention_ms": retention_ms, "shards": per_shard,
                "total_chunks": total_rows, "total_bytes": total_bytes}
