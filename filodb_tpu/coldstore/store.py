"""ColdChunkStore: a ColumnStore over an object bucket, and the
TieredColumnStore that merges it beneath the local disk tier.

Object layout — ALL chunk metadata lives in the key, so planning a
read costs one ``list_objects`` (metadata-only) and zero fetches::

    chunks/{dataset}/{shard}/{partkey hex}/
        {chunk_id}.{num_rows}.{start}.{end}.{schema_hash}.{itime}.{crc:08x}

The body is the same framed vectors blob sqlite stores (see
persistence.pack_vectors) and the CRC in the key is
``integrity.chunk_crc`` over that body — verified on EVERY fetch, even
on the defer-verify path (the bucket is the untrusted hop; a truncated
or bit-rotted object fails the check, is quarantined through the
standard ``integrity.report_corrupt`` funnel, and is NEVER served).

Deadlines: every ``get_object`` carries a ``timeout_s`` derived from
the active query's remaining budget (``deadline.budget_timeout_s``),
capped by the store's ``fetch_timeout_s``; the filolint
deadline-threading rule enforces the derivation at every call-site.

Locks: the index lock guards METADATA ONLY — no bucket I/O ever runs
under it.  For the ODP path (whose page-in classifies partitions under
its own ``_odp_lock``), :meth:`ColdChunkStore.prefetch_cold` fetches
the needed objects OUTSIDE any lock into a thread-local staging dict;
the locked read then consumes staged bytes without touching the
bucket.  A stalled bucket therefore stalls only the fetching thread up
to its own deadline — never a lock convoy.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Iterator, Optional, Sequence

from filodb_tpu import integrity
from filodb_tpu.coldstore.bucket import (BucketTimeout, ObjectBucket,
                                         ObjectMissing)
from filodb_tpu.core.chunk import ChunkSet, ChunkSetInfo
from filodb_tpu.integrity import CorruptVectorError
from filodb_tpu.store.columnstore import (ColumnStore, PartKeyRecord,
                                          ScanBytesExceeded)
from filodb_tpu.store.persistence import pack_vectors, unpack_vectors
from filodb_tpu.workload import deadline as dl

_KEY_ROOT = "chunks"
_MAX_TIME = 1 << 62


class ColdWriteError(OSError):
    """An age-out upload failed its read-back verification — the local
    row must NOT be deleted."""


@dataclasses.dataclass(frozen=True)
class ColdChunkMeta:
    """One archived chunk, decoded entirely from its object key."""
    key: str
    partkey: bytes
    chunk_id: int
    num_rows: int
    start_time: int
    end_time: int
    schema_hash: int
    ingestion_time: int
    crc: int
    size: int


def object_key(dataset: str, shard: int, partkey: bytes, chunk_id: int,
               num_rows: int, start_time: int, end_time: int,
               schema_hash: int, ingestion_time: int, crc: int) -> str:
    return (f"{_KEY_ROOT}/{dataset}/{shard}/{partkey.hex()}/"
            f"{chunk_id}.{num_rows}.{start_time}.{end_time}."
            f"{schema_hash}.{ingestion_time}.{crc:08x}")


def parse_object_key(key: str, size: int) -> Optional[ColdChunkMeta]:
    """Decode a chunk object key; None for foreign/malformed keys (a
    stray file in the bucket must not break planning)."""
    parts = key.split("/")
    if len(parts) != 5 or parts[0] != _KEY_ROOT:
        return None
    try:
        pk = bytes.fromhex(parts[3])
        cid, nr, st, et, sh, it, crc_hex = parts[4].split(".")
        return ColdChunkMeta(key, pk, int(cid), int(nr), int(st), int(et),
                             int(sh), int(it), int(crc_hex, 16), size)
    except (ValueError, IndexError):
        return None


class ColdChunkStore(ColumnStore):
    """A read-mostly ColumnStore tier over an :class:`ObjectBucket`.

    Writes happen via the age-out path (:meth:`put_chunk_row`, with
    read-back verification) or :meth:`write_chunks` (tests / direct
    archive loads).  Part keys are NOT archived — they stay in the
    local tier's sqlite, which remains the source of truth for series
    existence; the cold tier holds chunk bodies only."""

    #: per-thread staged-prefetch cap; crossing it drops the staging
    #: dict wholesale (leftovers only accumulate from aborted page-ins)
    max_staged_bytes = 256 << 20

    def __init__(self, bucket: ObjectBucket,
                 fetch_timeout_s: float = 30.0) -> None:
        self.bucket = bucket
        self.fetch_timeout_s = float(fetch_timeout_s)
        # (dataset, shard) -> partkey -> [ColdChunkMeta] sorted by chunk_id
        self._index: dict = {}
        # guards _index METADATA only — never held across bucket I/O
        self._index_lock = threading.Lock()
        self._staged = threading.local()
        # (dataset, shard) -> bytes fetched (HBM-ledger cold-page owner
        # reads this; monotonic counter, not residency)
        self._fetched_bytes: dict = {}
        # devicewatch pool owners registered per touched shard
        # (fmt=cold-page rows in /admin/device + filodb_device_hbm_bytes)
        self._ledger_owners: set = set()

    # ------------------------------------------------------------- index

    def _shard_index(self, dataset: str, shard: int) -> dict:
        key = (dataset, shard)
        got = self._index.get(key)
        if got is not None:
            return got
        # build OUTSIDE the lock (listing is metadata-only but can walk
        # many directories); losers of the install race discard
        metas: dict = {}
        prefix = f"{_KEY_ROOT}/{dataset}/{shard}/"
        for okey, size in self.bucket.list_objects(prefix):
            m = parse_object_key(okey, size)
            if m is not None:
                metas.setdefault(m.partkey, []).append(m)
        for lst in metas.values():
            lst.sort(key=lambda m: m.chunk_id)
        with self._index_lock:
            return self._index.setdefault(key, metas)

    def _select(self, dataset: str, shard: int,
                partkeys: Optional[Sequence[bytes]], start_time: int,
                end_time: int, itime_range: Optional[tuple] = None
                ) -> list:
        """Metas overlapping the query window, sorted (partkey,
        chunk_id); quarantined chunks are excluded BEFORE any fetch."""
        idx = self._shard_index(dataset, shard)
        quarantine = integrity.QUARANTINE
        with self._index_lock:
            pks = sorted(idx.keys()) if partkeys is None else \
                [pk for pk in sorted(set(partkeys)) if pk in idx]
            out = []
            for pk in pks:
                for m in idx.get(pk, ()):
                    if m.end_time < start_time or m.start_time > end_time:
                        continue
                    if itime_range is not None and not (
                            itime_range[0] <= m.ingestion_time
                            <= itime_range[1]):
                        continue
                    if quarantine.is_quarantined(m.partkey, m.chunk_id):
                        continue
                    out.append(m)
        return out

    def _index_add(self, dataset: str, shard: int, meta: ColdChunkMeta) -> None:
        with self._index_lock:
            idx = self._index.get((dataset, shard))
            if idx is None:
                return  # not loaded yet; the eventual listing sees the object
            lst = [m for m in idx.get(meta.partkey, ())
                   if m.chunk_id != meta.chunk_id]
            lst.append(meta)
            lst.sort(key=lambda m: m.chunk_id)
            idx[meta.partkey] = lst

    # ------------------------------------------------------------- fetch

    def _fetch_timeout_s(self) -> float:
        """Per-fetch timeout from the active query's REMAINING budget
        (capped by fetch_timeout_s); full cap outside query context
        (age-out verification, offline sweeps)."""
        from filodb_tpu.query.exec import active_exec_ctx
        ctx = active_exec_ctx()
        if ctx is not None:
            return dl.budget_timeout_s(ctx.query_context,
                                       self.fetch_timeout_s)
        return self.fetch_timeout_s

    def _staging(self) -> dict:
        blobs = getattr(self._staged, "blobs", None)
        if blobs is None:
            blobs = self._staged.blobs = {}
        return blobs

    def _fetch_one(self, meta: ColdChunkMeta) -> Optional[bytes]:
        """One object body: staged bytes if prefetched on this thread,
        else a live fetch under a deadline-derived timeout.  Returns
        None when the object vanished (aged past a second policy or
        deleted by admin) — the row is simply absent.  BucketTimeout
        propagates: a stalled bucket is a LOUD refusal, never a
        silent gap."""
        from filodb_tpu.utils.observability import coldstore_metrics
        staged = getattr(self._staged, "blobs", None)
        if staged is not None:
            blob = staged.pop(meta.key, None)
            if blob is not None:
                return blob
        m = coldstore_metrics()
        deadline_timeout_s = self._fetch_timeout_s()
        try:
            blob = self.bucket.get_object(meta.key,
                                          timeout_s=deadline_timeout_s)
        except ObjectMissing:
            m["fetch_missing"].inc()
            return None
        except BucketTimeout:
            m["fetch_timeouts"].inc()
            raise
        m["fetches"].inc()
        m["fetch_bytes"].inc(len(blob))
        return blob

    def _verify_blob(self, dataset: str, shard: int, meta: ColdChunkMeta,
                     blob: bytes) -> bool:
        """CRC the fetched body against the key's checksum.  Runs even
        when global verification is off — the bucket hop is untrusted
        by contract (truncation shows up as a length/CRC mismatch)."""
        if integrity.chunk_crc(blob) == meta.crc:
            return True
        from filodb_tpu.utils.observability import coldstore_metrics
        coldstore_metrics()["fetch_corrupt"].inc(dataset=dataset)
        integrity.report_corrupt(CorruptVectorError(
            f"cold object failed CRC on fetch (key={meta.key}, "
            f"expected={meta.crc:#010x}, got "
            f"{integrity.chunk_crc(blob):#010x}, {len(blob)}B body)",
            partkey=meta.partkey, chunk_id=meta.chunk_id, dataset=dataset,
            shard=shard, blob=blob, kind="checksum",
            start_time=meta.start_time, end_time=meta.end_time))
        return False

    def _fetch_rows(self, dataset: str, shard: int, metas: list
                    ) -> list[tuple]:
        """Fetch + verify a meta list into sqlite-shaped 8-tuples
        (partkey, chunk_id, num_rows, start_time, end_time,
        schema_hash, blob, crc).  Corrupt/missing objects are dropped
        (quarantine + partial-results warning flow through the
        standard integrity funnel)."""
        rows: list[tuple] = []
        nbytes = 0
        for meta in metas:
            blob = self._fetch_one(meta)
            if blob is None or not self._verify_blob(dataset, shard,
                                                     meta, blob):
                continue
            nbytes += len(blob)
            rows.append((meta.partkey, meta.chunk_id, meta.num_rows,
                         meta.start_time, meta.end_time, meta.schema_hash,
                         blob, meta.crc))
        if rows:
            key = (dataset, shard)
            self._fetched_bytes[key] = \
                self._fetched_bytes.get(key, 0) + nbytes
            owner = f"coldstore:{dataset}/{shard}"
            if owner not in self._ledger_owners:
                # first cold bytes for this shard: give them their own
                # fmt=cold-page ledger row so dashboards can tell
                # bucket-sourced residency from local page-ins
                self._ledger_owners.add(owner)
                from filodb_tpu.utils.devicewatch import LEDGER
                LEDGER.register_pool(
                    owner, lambda k=key: self._fetched_bytes.get(k, 0),
                    fmt="cold-page")
            from filodb_tpu.query.exec import active_exec_ctx
            ctx = active_exec_ctx()
            if ctx is not None:
                ctx.note_cold(chunks=len(rows), bytes_=nbytes)
        return rows

    def prefetch_cold(self, dataset: str, shard: int,
                      partkeys: Optional[Sequence[bytes]],
                      start_time: int, end_time: int) -> int:
        """Stage the objects a subsequent same-thread read will need —
        called by ODP BEFORE taking its page-in lock, so bucket I/O
        (and bucket stalls) never happen under a held lock.  Returns
        objects staged.  Raises BucketTimeout on a stalled backend —
        aborting the page-in before the lock, never wedging it."""
        staged = self._staging()
        # bound leftovers from aborted/raced page-ins (entries normally
        # pop on consume; re-prefetch of an already-staged key is free)
        if sum(len(b) for b in staged.values()) > self.max_staged_bytes:
            staged.clear()
        n = 0
        for meta in self._select(dataset, shard, partkeys, start_time,
                                 end_time):
            if meta.key in staged:
                n += 1
                continue
            blob = self._fetch_one(meta)
            if blob is not None:
                staged[meta.key] = blob
                n += 1
        return n

    def cold_page_bytes(self, dataset: str, shard: int) -> int:
        """Monotonic bytes fetched from the bucket for one shard (the
        ledger's fmt=cold-page attribution input)."""
        return self._fetched_bytes.get((dataset, shard), 0)

    # ------------------------------------------------------------- sink

    def put_chunk_row(self, dataset: str, shard: int, partkey: bytes,
                      chunk_id: int, num_rows: int, start_time: int,
                      end_time: int, schema_hash: int, ingestion_time: int,
                      blob: bytes, crc: int, verify: bool = True) -> str:
        """Archive one framed chunk row; with ``verify`` (the age-out
        default) the object is read back and CRC-checked before the
        caller may delete the local copy."""
        if not crc:
            crc = integrity.chunk_crc(blob)
        key = object_key(dataset, shard, partkey, chunk_id, num_rows,
                         start_time, end_time, schema_hash,
                         ingestion_time, crc)
        self.bucket.put_object(key, bytes(blob))
        if verify:
            admin_budget_s = self.fetch_timeout_s
            back = self.bucket.get_object(key, timeout_s=admin_budget_s)
            if integrity.chunk_crc(back) != crc:
                raise ColdWriteError(
                    f"read-back CRC mismatch archiving {key} "
                    f"({len(back)}B back vs {len(blob)}B up)")
        self._index_add(dataset, shard, ColdChunkMeta(
            key, bytes(partkey), chunk_id, num_rows, start_time, end_time,
            schema_hash, ingestion_time, crc, len(blob)))
        return key

    def write_chunks(self, dataset, shard, chunksets, ingestion_time=0) -> int:
        for cs in chunksets:
            blob = pack_vectors(cs.vectors)
            self.put_chunk_row(dataset, shard, cs.partkey, cs.info.chunk_id,
                               cs.info.num_rows, cs.info.start_time,
                               cs.info.end_time, cs.schema_hash,
                               ingestion_time, blob,
                               integrity.chunk_crc(blob), verify=False)
        return len(chunksets)

    def write_part_keys(self, dataset, shard, records) -> int:
        return 0  # part keys live in the local tier only

    # ------------------------------------------------------------- source

    def read_raw_rows(self, dataset, shard, partkeys, start_time,
                      end_time, byte_cap: int | None = None,
                      defer_verify: bool = False) -> list[tuple]:
        # defer_verify is ignored on purpose: the bucket hop is always
        # verified (sizes are known from keys, so the cap check runs
        # BEFORE any fetch is paid)
        metas = self._select(dataset, shard, partkeys, start_time, end_time)
        if byte_cap is not None:
            total = 0
            for m in metas:
                total += m.size
                if total > byte_cap:
                    raise ScanBytesExceeded(
                        f"cold raw-row read exceeded {byte_cap} bytes")
        return self._fetch_rows(dataset, shard, metas)

    def read_raw_partitions(self, dataset, shard, partkeys, start_time,
                            end_time) -> Iterator[tuple[bytes, list[ChunkSet]]]:
        metas = self._select(dataset, shard, partkeys, start_time, end_time)
        by_pk: dict = {}
        for pk, cid, nr, st, et, sh, blob, _crc in \
                self._fetch_rows(dataset, shard, metas):
            try:
                vectors = unpack_vectors(blob)
            except Exception as e:  # noqa: BLE001 — corrupt framing
                integrity.report_corrupt(CorruptVectorError(
                    f"bad cold chunk framing: {e}", partkey=pk,
                    chunk_id=cid, dataset=dataset, shard=shard, blob=blob,
                    kind="decode", start_time=st, end_time=et))
                continue
            by_pk.setdefault(pk, []).append(
                ChunkSet(ChunkSetInfo(cid, nr, st, et), pk, vectors,
                         schema_hash=sh))
        order = sorted(by_pk.keys()) if partkeys is None else partkeys
        for pk in order:
            css = by_pk.get(pk)
            if css:
                yield pk, css

    def scan_part_keys(self, dataset, shard) -> Iterator[PartKeyRecord]:
        return iter(())  # series existence is the local tier's job

    def scan_bytes(self, dataset, shard, partkeys, start_time,
                   end_time) -> int:
        # metadata-only: sizes come from the listing, zero fetches
        return sum(m.size for m in self._select(dataset, shard, partkeys,
                                                start_time, end_time))

    def chunksets_with_ingestion_time(self, dataset, shard, start, end
                                      ) -> Iterator[tuple[int, ChunkSet]]:
        metas = self._select(dataset, shard, None, 0, _MAX_TIME,
                             itime_range=(start, end))
        for meta in metas:
            blob = self._fetch_one(meta)
            if blob is None or not self._verify_blob(dataset, shard,
                                                     meta, blob):
                continue
            yield meta.ingestion_time, ChunkSet(
                ChunkSetInfo(meta.chunk_id, meta.num_rows, meta.start_time,
                             meta.end_time), meta.partkey,
                unpack_vectors(blob), schema_hash=meta.schema_hash)

    def delete_part_keys(self, dataset, shard, partkeys) -> int:
        idx = self._shard_index(dataset, shard)
        n = 0
        doomed: list = []
        with self._index_lock:
            for pk in partkeys:
                metas = idx.pop(pk, None)
                if metas:
                    n += 1
                    doomed.extend(metas)
        for meta in doomed:  # bucket I/O outside the index lock
            self.bucket.delete_object(meta.key)
        return n

    # ------------------------------------------------------------- admin

    def num_chunks(self, dataset: str, shard: int) -> int:
        idx = self._shard_index(dataset, shard)
        with self._index_lock:
            return sum(len(v) for v in idx.values())

    def list_shards(self, dataset: str) -> list[int]:
        shards = set()
        for key, _size in self.bucket.list_objects(f"{_KEY_ROOT}/{dataset}/"):
            parts = key.split("/")
            if len(parts) >= 3:
                try:
                    shards.add(int(parts[2]))
                except ValueError:
                    continue
        return sorted(shards)

    def scan_chunk_rows(self, dataset: str, shard: int
                        ) -> Iterator[tuple[bytes, int, bytes, int]]:
        """UNVERIFIED (partkey, chunk_id, body, key-crc) sweep feeding
        the offline ``verify-chunks --tier=cold`` scanner, which must
        see corrupt objects rather than have them dropped."""
        idx = self._shard_index(dataset, shard)
        with self._index_lock:
            metas = [m for lst in idx.values() for m in lst]
        metas.sort(key=lambda m: (m.partkey, m.chunk_id))
        for meta in metas:
            admin_budget_s = self.fetch_timeout_s
            try:
                blob = self.bucket.get_object(meta.key,
                                              timeout_s=admin_budget_s)
            except ObjectMissing:
                continue
            yield meta.partkey, meta.chunk_id, blob, meta.crc

    def shutdown(self) -> None:
        from filodb_tpu.utils.devicewatch import LEDGER
        for owner in self._ledger_owners:
            LEDGER.deregister_pool(owner)
        self._ledger_owners.clear()

    def drop_index_cache(self) -> None:
        """Forget the in-memory listing (tests; external bucket writes)."""
        with self._index_lock:
            self._index.clear()


class TieredColumnStore(ColumnStore):
    """local (sqlite warm tier) over cold (bucket archive), presented
    as ONE ColumnStore: writes land local; reads merge local + cold
    rows deduped by (partkey, chunk_id) with the LOCAL copy winning
    (age-out deletes local only after the upload verified, so during
    the overlap window both tiers hold identical bytes).  Unknown
    attributes delegate to the local tier so sqlite-level admin
    helpers (fault injection, stats) keep working unwrapped."""

    def __init__(self, local: ColumnStore, cold: ColdChunkStore) -> None:
        self.local = local
        self.cold = cold
        # dataset -> raw rows served by read_raw_rows/partitions; the
        # never-scans-raw acceptance test pins its assertions on this
        self.rows_read_by_dataset: dict = {}

    def __getattr__(self, name: str):
        # only fires for attributes Tiered itself lacks (sqlite admin
        # surface: _conn, scan_chunk_rows, list_shards, num_chunks, …)
        return getattr(self.local, name)

    def _note_rows(self, dataset: str, n: int) -> None:
        if n:
            self.rows_read_by_dataset[dataset] = \
                self.rows_read_by_dataset.get(dataset, 0) + n

    # -- sink: local tier owns ingest ---------------------------------------

    def initialize(self, dataset, num_shards) -> None:
        self.local.initialize(dataset, num_shards)

    def write_chunks(self, dataset, shard, chunksets, ingestion_time=0) -> int:
        return self.local.write_chunks(dataset, shard, chunksets,
                                       ingestion_time)

    def write_part_keys(self, dataset, shard, records) -> int:
        return self.local.write_part_keys(dataset, shard, records)

    def merge_part_keys(self, dataset, shard, records) -> int:
        return self.local.merge_part_keys(dataset, shard, records)

    def deferred_commits(self):
        return self.local.deferred_commits()

    # -- source: merged ------------------------------------------------------

    def prefetch_cold(self, dataset, shard, partkeys, start_time,
                      end_time) -> int:
        return self.cold.prefetch_cold(dataset, shard, partkeys,
                                       start_time, end_time)

    def cold_page_bytes(self, dataset: str, shard: int) -> int:
        return self.cold.cold_page_bytes(dataset, shard)

    def read_raw_rows(self, dataset, shard, partkeys, start_time,
                      end_time, byte_cap: int | None = None,
                      defer_verify: bool = False) -> Optional[list[tuple]]:
        lrows = self.local.read_raw_rows(dataset, shard, partkeys,
                                         start_time, end_time,
                                         byte_cap=byte_cap,
                                         defer_verify=defer_verify)
        if lrows is None:
            return None  # local backend has no bulk path; keep contract
        cold_cap = None
        if byte_cap is not None:
            cold_cap = max(byte_cap - sum(len(r[6]) for r in lrows), 0)
        crows = self.cold.read_raw_rows(dataset, shard, partkeys,
                                        start_time, end_time,
                                        byte_cap=cold_cap,
                                        defer_verify=defer_verify)
        if crows:
            seen = {(r[0], r[1]) for r in lrows}
            lrows = lrows + [r for r in crows if (r[0], r[1]) not in seen]
            lrows.sort(key=lambda r: (r[0], r[1]))
        self._note_rows(dataset, len(lrows))
        return lrows

    def read_raw_partitions(self, dataset, shard, partkeys, start_time,
                            end_time) -> Iterator[tuple[bytes, list[ChunkSet]]]:
        local_by_pk = dict(self.local.read_raw_partitions(
            dataset, shard, partkeys, start_time, end_time))
        cold_by_pk = dict(self.cold.read_raw_partitions(
            dataset, shard, partkeys, start_time, end_time))
        n = 0
        for pk in partkeys:
            lcs = local_by_pk.get(pk)
            ccs = cold_by_pk.get(pk)
            if lcs and ccs:
                have = {cs.info.chunk_id for cs in lcs}
                css = sorted(lcs + [cs for cs in ccs
                                    if cs.info.chunk_id not in have],
                             key=lambda cs: cs.info.chunk_id)
            else:
                css = lcs or ccs
            if css:
                n += len(css)
                yield pk, css
        self._note_rows(dataset, n)

    def scan_part_keys(self, dataset, shard) -> Iterator[PartKeyRecord]:
        return self.local.scan_part_keys(dataset, shard)

    def scan_bytes(self, dataset, shard, partkeys, start_time,
                   end_time) -> int:
        return (self.local.scan_bytes(dataset, shard, partkeys, start_time,
                                      end_time)
                + self.cold.scan_bytes(dataset, shard, partkeys, start_time,
                                       end_time))

    def chunksets_with_ingestion_time(self, dataset, shard, start, end
                                      ) -> Iterator[tuple[int, ChunkSet]]:
        yield from self.local.chunksets_with_ingestion_time(dataset, shard,
                                                            start, end)
        yield from self.cold.chunksets_with_ingestion_time(dataset, shard,
                                                           start, end)

    def delete_part_keys(self, dataset, shard, partkeys) -> int:
        n = self.local.delete_part_keys(dataset, shard, partkeys)
        return max(n, self.cold.delete_part_keys(dataset, shard, partkeys))

    def shutdown(self) -> None:
        self.local.shutdown()
        self.cold.shutdown()
