"""HTTP API server: Prometheus-compatible query routes + cluster admin.

Capability match for the reference's HTTP layer (reference:
http/src/main/scala/filodb/http/FiloHttpServer.scala:22 combining
PrometheusApiRoute.scala:24-60 — /promql/<ds>/api/v1/query_range|query:
parse -> LogicalPlan2Query ask -> Prom JSON; ClusterApiRoute.scala:14 —
/api/v1/cluster status/startshards/stopshards; HealthRoute.scala:13 —
__health returning shard statuses).  stdlib ThreadingHTTPServer replaces
akka-http; the planner/memstore stand in for the coordinator ask.
"""

from __future__ import annotations

import contextlib
import functools
import json
import math
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from filodb_tpu.coordinator.planner import QueryPlanner
from filodb_tpu.http.model import (error_response, parse_duration_ms,
                                   parse_time_ms, stats_payload,
                                   to_prom_matrix, to_prom_vector)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.promql.parser import (ParseError,
                                      query_range_to_logical_plan,
                                      query_to_logical_plan)
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import (QueryContext, QueryError,
                                    ShardUnavailable)
from filodb_tpu.utils.observability import (TRACER, insights_metrics,
                                            query_metrics,
                                            workload_metrics)
from filodb_tpu.workload import deadline as wdl

# remote-storage body limits (unauthenticated endpoints; snappy copy
# elements amplify ~21x, so both sides are bounded)
_MAX_REMOTE_COMPRESSED = 16 * 1024 * 1024
_MAX_REMOTE_UNCOMPRESSED = 128 * 1024 * 1024

_METRICS = query_metrics()
_WORKLOAD_M = workload_metrics()
_INSIGHTS_M = insights_metrics()


def _timed(endpoint: str):
    """Route-handler latency decorator: EVERY ``_route`` handler must
    wear one so no endpoint is dark (lint-enforced by
    tests/test_sentinel_lint.py::test_route_handlers_record_latency)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *a, **kw):
            t0 = time.perf_counter()
            code = "error"
            try:
                out = fn(self, *a, **kw)
                code = str(out[0]) if isinstance(out, tuple) else "200"
                return out
            finally:
                _METRICS["request_seconds"].observe(
                    time.perf_counter() - t0, endpoint=endpoint)
                _METRICS["requests"].inc(endpoint=endpoint, code=code)
        wrapper._timed_endpoint = endpoint
        return wrapper

    return deco


def _parse_downsample(v) -> int:
    """``?downsample=<pixels>`` — target horizontal resolution for the
    M4 query-time decimator (doc/coldstore.md).  Absent/empty -> 0
    (off); anything not a positive integer is a client error (400)."""
    if v is None or str(v).strip() == "":
        return 0
    try:
        px = int(str(v).strip())
    except ValueError:
        raise ValueError(f"downsample must be a positive integer pixel "
                         f"count, got {v!r}") from None
    if px <= 0:
        raise ValueError(f"downsample must be > 0, got {px}")
    if px > 1 << 20:
        # more pixels than any display: almost certainly a unit error,
        # and the bin math degenerates to per-sample bins anyway
        raise ValueError(f"downsample {px} exceeds the 1048576-pixel cap")
    return px


@dataclass
class DatasetBinding:
    """Everything the HTTP layer needs to serve one dataset."""

    dataset: str
    memstore: TimeSeriesMemStore
    planner: QueryPlanner
    metric_column: str = "_metric_"  # DatasetOptions.metric_column
    # remote-write ingest hook: (labels, ts_list, val_list) -> None; when
    # None the /api/v1/write endpoint 400s for this dataset
    write_router: Optional[object] = None
    # query admission/scheduling (query/scheduler.py): when set, queries
    # run on its bounded worker pool instead of the HTTP handler thread
    # (reference: QueryActor's priority mailbox + query scheduler)
    scheduler: Optional[object] = None
    # SEPARATE pool for dispatched leaf ExecPlans: coordinator queries
    # block on remote leaves, so sharing one pool across nodes would
    # deadlock under load (all workers waiting on leaves queued behind
    # them).  Leaf plans never re-dispatch, so this pool cannot cycle.
    leaf_scheduler: Optional[object] = None
    # workload management (ISSUE 5, filodb_tpu/workload): cost-based
    # admission controller in front of the scheduler (None = admit all)
    # and the dataset's active-series cardinality quota (admin views +
    # runtime config; enforcement lives on the shards/gateway)
    admission: Optional[object] = None
    quota: Optional[object] = None
    # query-frontend result cache (query/resultcache.py): the
    # ResultCache instance embedded in this dataset's planner wrapper;
    # None = the dataset serves uncached (admin views + runtime config)
    resultcache: Optional[object] = None
    # fleet batching tier (ISSUE 20, filodb_tpu/batching): the
    # QueryBatcher this dataset's shards rendezvous in; None = every
    # dispatch runs the per-query chain (admin views + runtime config)
    batcher: Optional[object] = None


@dataclass
class FiloHttpServer:
    """Route table + server lifecycle (reference: FiloHttpServer.start)."""

    port: int = 0  # 0 = ephemeral
    host: str = "127.0.0.1"
    node_name: Optional[str] = None  # reported in /__health for bootstrap
    shard_manager: Optional[object] = None  # coordinator.cluster.ShardManager
    # dataset -> list of shards this node is actively ingesting; reported
    # in /__health as ground truth for peer status gossip (StatusPoller)
    running_shards: Optional[object] = None
    # a remote /execplan arriving with less deadline budget than this
    # cannot plausibly finish — refuse it outright (workload/deadline.py)
    min_remote_budget_ms: int = wdl.MIN_REMOTE_BUDGET_MS
    # ingest watermark ledger backing /admin/shards (ISSUE 6); the
    # standalone server installs a configured one (broker end offsets,
    # stall window), bare servers get a lazy default over their bindings
    watermarks: Optional[object] = None
    # replica dual-write receiver (ISSUE 7): (dataset, shard, container)
    # -> offset, backing POST /ingest/<ds>/<shard> for queue-transport
    # replication; None = the route 404s (broker transports do not
    # need it — the shared partition log is the replicated stream)
    ingest_sink: Optional[object] = None
    # the rule engine (ISSUE 9, filodb_tpu/rules): backs /api/v1/rules,
    # /api/v1/alerts, and /admin/rules; None = empty payloads (a node
    # with no rules configured still answers the Prometheus API shape)
    rules: Optional[object] = None
    # the rollup engine (ISSUE 11, filodb_tpu/rollup): backs
    # /admin/rollup; None = the route 404s (no rollup on this node)
    rollup: Optional[object] = None
    # the elastic-resharding controller (ISSUE 13, coordinator/split.py):
    # backs /admin/split/<ds> (trigger / status / abort); None = 404
    split: Optional[object] = None
    # callable returning this node's per-dataset split progress (clone /
    # retire markers) for the /__health gossip the controller gates on
    split_progress: Optional[object] = None
    # fleet workload insights (ISSUE 19, filodb_tpu/insights): the
    # per-fingerprint workload ledger behind /admin/insights.  PER
    # SERVER, not process-wide (the WatermarkLedger lesson: in-process
    # multi-node tests must not share one table); the standalone server
    # installs a configured one, bare servers get a lazy default
    insights: Optional[object] = None
    # tenant SLO tracker (insights/slo.py); None = no objectives
    # configured (queries are not matched, /admin/insights omits SLO)
    slo: Optional[object] = None
    # fleet aggregator (insights/fleet.py) behind /admin/fleet; a
    # peerless default is created lazily so single-node /admin/fleet
    # still serves the merged-local view
    fleet: Optional[object] = None
    datasets: dict = field(default_factory=dict)
    _httpd: Optional[ThreadingHTTPServer] = None
    _thread: Optional[threading.Thread] = None
    _wm_lock: threading.Lock = field(default_factory=threading.Lock)
    _ins_lock: threading.Lock = field(default_factory=threading.Lock)

    def bind_dataset(self, binding: DatasetBinding) -> None:
        self.datasets[binding.dataset] = binding

    # ------------------------------------------------------------- lifecycle

    def start(self) -> int:
        """Start serving; returns the bound port."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # silence stdlib logging
                pass

            def do_GET(self):
                server._handle(self, "GET")

            def do_POST(self):
                server._handle(self, "POST")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="filo-http", daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    # --------------------------------------------------------------- routing

    def _handle(self, req: BaseHTTPRequestHandler, method: str) -> None:
        if req.path.split("?")[0] == "/metrics":
            # plain-text route handled entirely outside the JSON error
            # epilogue; generation errors become a 500, write errors on a
            # dead socket are swallowed (no second send_response)
            try:
                from filodb_tpu.utils.observability import REGISTRY
                code, text = 200, REGISTRY.expose_text().encode()
            except Exception as e:  # noqa: BLE001 — bad reporter/gauge fn
                code, text = 500, f"metrics exposition failed: {e}\n".encode()
            try:
                req.send_response(code)
                req.send_header("Content-Type", "text/plain; version=0.0.4")
                req.send_header("Content-Length", str(len(text)))
                req.end_headers()
                req.wfile.write(text)
            except Exception:  # noqa: BLE001 — socket already unusable
                pass
            return
        if req.path.split("?")[0] == "/execplan" and method == "POST":
            self._handle_execplan(req)
            return
        if req.path.split("?")[0].startswith("/ingest/") and method == "POST":
            self._handle_ingest_push(req)
            return
        bare = req.path.split("?")[0]
        if method == "POST" and (bare.endswith("/api/v1/read")
                                 or bare.endswith("/api/v1/write")):
            self._handle_remote(req, bare)
            return
        retry_after = None
        try:
            parsed = urllib.parse.urlparse(req.path)
            multi = urllib.parse.parse_qs(parsed.query)
            if method == "POST":
                ln = int(req.headers.get("Content-Length") or 0)
                if ln:
                    body = req.rfile.read(ln).decode()
                    ctype = req.headers.get("Content-Type", "")
                    if "json" in ctype:
                        decoded = json.loads(body)
                        if not isinstance(decoded, dict):
                            raise ValueError(
                                "request body must be a JSON object")
                        # JSON numbers arrive as int/float; route handlers
                        # expect query-string semantics (everything str)
                        for k, v in decoded.items():
                            if isinstance(v, list):
                                multi.setdefault(k, []).extend(
                                    x if isinstance(x, str) else str(x)
                                    for x in v)
                            else:
                                multi.setdefault(k, []).append(
                                    v if isinstance(v, str) else str(v))
                    else:
                        for k, v in urllib.parse.parse_qs(body).items():
                            multi.setdefault(k, []).extend(v)
            params = {k: v[0] for k, v in multi.items()}
            code, payload = self._route(parsed.path, params, multi)
        except QueryError as e:
            from filodb_tpu.query.scheduler import QueryRejected
            from filodb_tpu.workload.admission import AdmissionRejected
            if isinstance(e, AdmissionRejected):
                # shed by admission control: 429 + a Retry-After hint
                # derived from the estimated drain time, so well-behaved
                # clients back off instead of hammering
                code, payload = 429, error_response("throttled", str(e))
                retry_after = e.retry_after_s
            elif isinstance(e, QueryRejected):
                # queue-level rejection: overloaded, not a bad request
                code, payload = 503, error_response("unavailable", str(e))
            elif isinstance(e, ShardUnavailable):
                # a shard's node is down/unreachable (and the query did
                # not opt into partial results): service, not client
                code, payload = 503, error_response("unavailable", str(e))
            elif isinstance(e, wdl.DeadlineExceeded):
                # budget ran out mid-execution: an overload/timeout
                # outcome (503), never a malformed request (400)
                code, payload = 503, error_response("timeout", str(e))
            else:
                code, payload = 400, error_response("bad_data", str(e))
        except (ParseError, ValueError, KeyError) as e:
            code, payload = 400, error_response("bad_data", str(e))
        except Exception as e:  # noqa: BLE001
            code, payload = 500, error_response("internal", str(e))
        data = json.dumps(payload).encode()
        try:
            req.send_response(code)
            req.send_header("Content-Type", "application/json")
            if retry_after is not None:
                req.send_header("Retry-After",
                                str(int(math.ceil(retry_after))))
            if isinstance(payload, dict) and payload.get("warnings"):
                # partial-data flag as a header too, so load balancers /
                # caches can act on it without parsing the body
                req.send_header("X-FiloDB-Partial-Data", "true")
            trace_id = None
            if isinstance(payload, dict) \
                    and isinstance(payload.get("data"), dict) \
                    and isinstance(payload["data"].get("stats"), dict):
                trace_id = payload["data"]["stats"].get("traceId")
            if trace_id:
                # lets the client jump straight to /admin/traces/<id>
                req.send_header("X-FiloDB-Trace-Id", str(trace_id))
            req.send_header("Content-Length", str(len(data)))
            req.end_headers()
            req.wfile.write(data)
        except Exception:  # noqa: BLE001 — client disconnected mid-response
            pass

    def _handle_execplan(self, req: BaseHTTPRequestHandler) -> None:
        """Cross-node dispatch receiver (reference: remote QueryActor
        executing a serialized ExecPlan, QueryActor.scala:220)."""
        t0 = time.perf_counter()
        try:
            from filodb_tpu.coordinator.dispatch import (PARENT_SPAN_HEADER,
                                                         TRACE_HEADER)
            ln = int(req.headers.get("Content-Length") or 0)
            payload = json.loads(req.rfile.read(ln))
            # trace context propagates via headers AND the execplan-wire
            # qctx field; the handler prefers the wire field
            tp = (req.headers.get(TRACE_HEADER),
                  req.headers.get(PARENT_SPAN_HEADER))
            tp = tp if tp[0] else None
            binding = self.datasets.get(payload.get("dataset"))
            qctx = payload.get("qctx") or {}
            # deadline propagation (ISSUE 5): the wire carries the
            # REMAINING budget; work that cannot plausibly finish in
            # what is left is refused here, before any execution — the
            # coordinator treats the refusal as a transport failure so
            # allow_partial_results can degrade it
            budget_ms = qctx.get("budget_ms")
            if binding is None:
                code, out = 404, error_response(
                    "bad_data", f"unknown dataset {payload.get('dataset')}")
            elif budget_ms is not None \
                    and budget_ms < self.min_remote_budget_ms:
                _WORKLOAD_M["deadline_refused"].inc()
                code, out = 503, error_response(
                    "unavailable",
                    f"refusing /execplan work with {budget_ms}ms deadline "
                    f"budget left (node minimum "
                    f"{self.min_remote_budget_ms}ms)")
            else:
                from filodb_tpu.coordinator.dispatch import execplan_handler
                handler = execplan_handler(binding.memstore)
                if binding.leaf_scheduler is not None:
                    # leaf execution queues with the ORIGINAL query's
                    # submit time and deadline (carried in the plan's
                    # query context) so cross-node priority and
                    # overdue-drop hold (reference: the remote
                    # QueryActor's mailbox orders by submitTime).
                    # Attach the caller's trace BEFORE submit so the
                    # scheduler's capture() sees it and this node's
                    # queue-wait/run spans join the stitched tree.
                    wire_tid = qctx.get("trace_id") or None
                    token = (tp[0], tp[1]) if tp else (wire_tid, None)
                    timeout_ms = qctx.get("timeout_ms") or 30_000
                    deadline_ms = None
                    if budget_ms is not None:
                        # re-anchor the budget on THIS node's clock:
                        # both the scheduler's dequeue drop and the
                        # execution tripwire enforce it locally
                        timeout_ms = min(timeout_ms, budget_ms)
                        deadline_ms = int(time.time() * 1000) + budget_ms
                    with TRACER.attach(token):
                        out = binding.leaf_scheduler.execute(
                            lambda: handler(payload, tp),
                            submit_time_ms=qctx.get("submit_time_ms")
                            or None,
                            timeout_ms=timeout_ms,
                            deadline_ms=deadline_ms)
                else:
                    out = handler(payload, tp)
                code = 200
        except QueryError as e:
            from filodb_tpu.query.scheduler import QueryRejected
            if isinstance(e, QueryRejected):
                code, out = 503, error_response("unavailable", str(e))
            else:
                code, out = 400, error_response("bad_data", str(e))
        except Exception as e:  # noqa: BLE001
            code, out = 500, error_response("internal", str(e))
        _METRICS["execplan_seconds"].observe(time.perf_counter() - t0)
        data = json.dumps(out).encode()
        try:
            req.send_response(code)
            req.send_header("Content-Type", "application/json")
            req.send_header("Content-Length", str(len(data)))
            req.end_headers()
            req.wfile.write(data)
        except Exception:  # noqa: BLE001 — client went away
            pass

    def _handle_ingest_push(self, req: BaseHTTPRequestHandler) -> None:
        """Replica dual-write receiver (ISSUE 7): a peer gateway POSTs a
        raw record container for one shard; it lands on this node's
        ingest stream exactly like a locally-published one."""
        t0 = time.perf_counter()
        try:
            parts = [p for p in req.path.split("?")[0].split("/") if p]
            ln = int(req.headers.get("Content-Length") or 0)
            body = req.rfile.read(ln) if ln else b""
            if self.ingest_sink is None or len(parts) != 3:
                code, out = 404, error_response(
                    "bad_data", "container-push ingest not enabled here")
            elif not body:
                code, out = 400, error_response("bad_data",
                                                "empty container")
            else:
                offset = self.ingest_sink(parts[1], int(parts[2]), body)
                code, out = 200, {"status": "success",
                                  "offset": offset}
        except (ValueError, KeyError) as e:
            code, out = 400, error_response("bad_data", str(e))
        except Exception as e:  # noqa: BLE001
            code, out = 500, error_response("internal", str(e))
        _METRICS["request_seconds"].observe(time.perf_counter() - t0,
                                            endpoint="ingest_push")
        _METRICS["requests"].inc(endpoint="ingest_push", code=str(code))
        data = json.dumps(out).encode()
        try:
            req.send_response(code)
            req.send_header("Content-Type", "application/json")
            req.send_header("Content-Length", str(len(data)))
            req.end_headers()
            req.wfile.write(data)
        except Exception:  # noqa: BLE001 — client went away
            pass

    def _handle_remote(self, req: BaseHTTPRequestHandler, path: str) -> None:
        """Prometheus remote-storage endpoints: snappy'd protobuf over
        POST (reference: PrometheusApiRoute.scala:38-60 `/read` +
        remote-storage.proto wire contract).  `/write` additionally
        accepts remote-write as an ingest edge into the bound memstore."""
        from filodb_tpu.utils import snappy

        try:
            parts = [p for p in path.split("/") if p]
            ds = parts[1] if len(parts) >= 2 and parts[0] == "promql" else ""
            binding = self.datasets.get(ds)
            if binding is None:
                code, body, ctype = 404, json.dumps(error_response(
                    "bad_data", f"unknown dataset {ds}")).encode(), \
                    "application/json"
            else:
                ln = int(req.headers.get("Content-Length") or 0)
                if ln > _MAX_REMOTE_COMPRESSED:
                    raise QueryError(
                        "", f"request body {ln} bytes exceeds limit "
                            f"{_MAX_REMOTE_COMPRESSED}")
                raw = snappy.decompress(req.rfile.read(ln),
                                        max_len=_MAX_REMOTE_UNCOMPRESSED)
                if path.endswith("/read"):
                    body = snappy.compress(self._remote_read(binding, raw))
                    code, ctype = 200, "application/x-protobuf"
                else:
                    n = self._remote_write(binding, raw)
                    body, ctype = json.dumps(
                        {"status": "success", "samples": n}).encode(), \
                        "application/json"
                    code = 200
        except (QueryError, ValueError, KeyError) as e:
            code, ctype = 400, "application/json"
            body = json.dumps(error_response("bad_data", str(e))).encode()
        except Exception as e:  # noqa: BLE001
            code, ctype = 500, "application/json"
            body = json.dumps(error_response("internal", str(e))).encode()
        try:
            req.send_response(code)
            req.send_header("Content-Type", ctype)
            if ctype == "application/x-protobuf":
                req.send_header("Content-Encoding", "snappy")
            req.send_header("Content-Length", str(len(body)))
            req.end_headers()
            req.wfile.write(body)
        except Exception:  # noqa: BLE001 — client went away
            pass

    def _remote_read(self, b: DatasetBinding, raw: bytes) -> bytes:
        """Execute each remote query as a RawSeries plan; stream raw
        samples back as prompb TimeSeries."""
        from filodb_tpu.http import remote as pb
        from filodb_tpu.http.model import public_tags
        from filodb_tpu.query.logical import IntervalSelector, RawSeries
        from filodb_tpu.query.model import RawBatch

        queries = pb.decode_read_request(raw)
        per_query: list[list[bytes]] = []
        for q in queries:
            filters = pb.matchers_to_filters(q.matchers, b.metric_column)
            plan = RawSeries(IntervalSelector(q.start_ms, q.end_ms),
                             tuple(filters))
            result, _tid = self._exec(b, plan, query="remote_read")
            series: list[bytes] = []
            for batch in result.batches:
                if not isinstance(batch, RawBatch) or batch.batch is None:
                    continue
                for i, tags in enumerate(batch.keys):
                    n = int(batch.batch.row_counts[i])
                    ts = batch.batch.timestamps[i][:n]
                    vals = batch.batch.values[i][:n]
                    # clamp to the query range (lookback may widen scans)
                    mask = (ts >= q.start_ms) & (ts <= q.end_ms)
                    if not mask.any():
                        continue
                    series.append(pb.encode_time_series(
                        public_tags(tags, b.metric_column),
                        ts[mask], vals[mask]))
            per_query.append(series)
        return pb.encode_read_response(per_query)

    def _remote_write(self, b: DatasetBinding, raw: bytes) -> int:
        """Remote-write edge: decode WriteRequest and ingest into the
        bound memstore's shards via the gateway sharding rules."""
        from filodb_tpu.http import remote as pb

        if b.write_router is None:
            raise QueryError("remote write not enabled for this dataset")
        series = pb.decode_write_request(raw)
        n = 0
        for labels, ts, vals in series:
            b.write_router(labels, ts, vals)
            n += len(ts)
        return n

    def _route(self, path: str, params: dict,
               multi: Optional[dict] = None) -> tuple[int, dict]:
        multi = multi if multi is not None else {k: [v] for k, v in params.items()}
        parts = [p for p in path.split("/") if p]
        if path == "/__health":
            return self._health()
        if len(parts) >= 4 and parts[0] == "promql" and parts[2] == "api":
            ds = parts[1]
            binding = self.datasets.get(ds)
            if binding is None:
                return 404, error_response("bad_data", f"unknown dataset {ds}")
            endpoint = parts[4] if len(parts) > 4 else ""
            if endpoint == "query_range":
                return self._query_range(binding, params)
            if endpoint == "query":
                return self._query_instant(binding, params)
            if endpoint == "labels":
                return self._labels(binding, params)
            if endpoint == "label" and len(parts) >= 7 and parts[6] == "values":
                return self._label_values(binding, parts[5], params, multi)
            if endpoint == "series":
                return self._series(binding, params, multi)
        if len(parts) == 3 and parts[0] == "api" and parts[1] == "v1" \
                and parts[2] == "rules":
            return self._rules_api()
        if len(parts) == 3 and parts[0] == "api" and parts[1] == "v1" \
                and parts[2] == "alerts":
            return self._alerts_api()
        if len(parts) >= 3 and parts[0] == "api" and parts[2] == "cluster":
            return self._cluster(parts[3:], params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "rules":
            return self._admin_rules()
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "rollup":
            return self._admin_rollup()
        if len(parts) == 3 and parts[0] == "admin" \
                and parts[1] == "chunkmeta":
            return self._chunkmeta(parts[2], params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "integrity":
            return self._integrity()
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "slowlog":
            return self._slowlog(params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "device":
            return self._device()
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "kernels":
            return self._kernels()
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "flightrecorder":
            return self._flightrecorder(params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "config":
            return self._config(params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "workload":
            return self._workload()
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "resultcache":
            return self._resultcache(params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "cardinality":
            return self._cardinality(params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "shards":
            return self._shards(params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "insights":
            return self._insights(params)
        if len(parts) == 2 and parts[0] == "admin" \
                and parts[1] == "fleet":
            return self._fleet(params)
        if len(parts) >= 2 and parts[0] == "admin" and parts[1] == "split":
            return self._split(parts[2:], params)
        if len(parts) == 3 and parts[0] == "admin" and parts[1] == "traces":
            return self._traces(parts[2])
        if len(parts) == 2 and parts[0] == "debug" \
                and parts[1] == "profilez":
            return self._profilez(params)
        if len(parts) == 2 and parts[0] == "debug" \
                and parts[1] == "device_profilez":
            return self._device_profilez(params)
        return 404, error_response("bad_data", f"unknown route {path}")

    # ------------------------------------------------------- rule engine

    @_timed("rules_api")
    def _rules_api(self) -> tuple[int, dict]:
        """Prometheus ``/api/v1/rules``: every group's rules with their
        rendered exprs, health, and live alert instances (doc/rules.md)."""
        data = self.rules.rules_payload() if self.rules is not None \
            else {"groups": []}
        return 200, {"status": "success", "data": data}

    @_timed("alerts_api")
    def _alerts_api(self) -> tuple[int, dict]:
        """Prometheus ``/api/v1/alerts``: live pending/firing alerts."""
        data = self.rules.alerts_payload() if self.rules is not None \
            else {"alerts": []}
        return 200, {"status": "success", "data": data}

    @_timed("admin_rules")
    def _admin_rules(self) -> tuple[int, dict]:
        """The rule engine's live operational state: per-group eval
        timing/miss counts, per-rule health, incremental-window
        residency, and the notifier queue (doc/rules.md)."""
        if self.rules is None:
            return 404, error_response("bad_data",
                                       "no rule engine on this node")
        return 200, {"status": "success", "data": self.rules.admin_state()}

    @_timed("admin_rollup")
    def _admin_rollup(self) -> tuple[int, dict]:
        """The rollup engine's live state (doc/rollup.md): per-dataset
        tier ladder, per-shard cursor positions + lag vs the flush
        watermark, pass timing, rows written, tier errors."""
        if self.rollup is None:
            return 404, error_response("bad_data",
                                       "no rollup engine on this node")
        return 200, {"status": "success", "data": self.rollup.admin_state()}

    @_timed("split")
    def _split(self, parts: list, p: dict) -> tuple[int, dict]:
        """Elastic resharding surface (ISSUE 13, doc/ha.md):

        - ``GET  /admin/split``            — every split record's status
        - ``GET  /admin/split/<ds>``       — one dataset's split status
        - ``POST /admin/split/<ds>?action=start[&grace-s=]`` — trigger a
          live power-of-two split (N -> 2N)
        - ``POST /admin/split/<ds>?action=abort`` — lossless abort back
          to the parent topology
        """
        if self.split is None:
            return 404, error_response("bad_data",
                                       "no split controller on this node")
        if not parts:
            return 200, {"status": "success",
                         "data": self.split.admin_state()}
        ds = parts[0]
        action = str(p.get("action", "status"))
        try:
            if action == "start":
                state = self.split.trigger(
                    ds, grace_s=float(p.get("grace-s", 30.0)))
            elif action == "abort":
                state = self.split.abort(ds, reason=str(
                    p.get("reason", "operator abort")))
            elif action == "status":
                state = self.split.status(ds)
                if state is None:
                    return 404, error_response(
                        "bad_data", f"no split record for {ds!r}")
            else:
                return 400, error_response("bad_data",
                                           f"unknown action {action!r}")
        except ValueError as e:
            return 409, error_response("conflict", str(e))
        except KeyError:
            return 404, error_response("bad_data", f"unknown dataset {ds!r}")
        return 200, {"status": "success", "data": state}

    # ------------------------------------------------------ query forensics

    @_timed("slowlog")
    def _slowlog(self, p: dict) -> tuple[int, dict]:
        """Recent completed queries over the slow threshold, newest
        first, each with its full stitched span tree (doc/observability.md)."""
        from filodb_tpu.utils.forensics import TRACE_STORE
        limit = max(1, min(int(p.get("limit", 50)), 1000))
        entries = TRACE_STORE.slowlog()[-limit:][::-1]
        return 200, {"status": "success", "data": {
            "threshold_s": TRACE_STORE.slow_threshold_s,
            "entries": entries}}

    @_timed("traces")
    def _traces(self, trace_id: str) -> tuple[int, dict]:
        """One recent trace as a span tree (remote shards' spans are
        stitched in by the dispatch layer)."""
        from filodb_tpu.utils.forensics import TRACE_STORE
        tree = TRACE_STORE.tree(trace_id)
        if not tree:
            return 404, error_response("bad_data",
                                       f"unknown trace {trace_id}")
        return 200, {"status": "success",
                     "data": {"traceId": trace_id, "spans": tree}}

    @_timed("profilez")
    def _profilez(self, p: dict) -> tuple[int, dict]:
        """On-demand sampling profile: blocks this handler thread for
        ``seconds`` (bounded, single-flight) and returns hot frames."""
        from filodb_tpu.utils import forensics
        try:
            data = forensics.profile(seconds=float(p.get("seconds", 2.0)))
        except forensics.ProfilerBusy as e:
            return 503, error_response("unavailable", str(e))
        return 200, {"status": "success", "data": data}

    # ------------------------------------------------- device observability

    @_timed("device")
    def _device(self) -> tuple[int, dict]:
        """Device-resource view (ISSUE 4): the HBM residency ledger tree
        (per-owner/format byte totals, watermarks), per-dataset arena
        budgets (device grid caches + ODP page caches), the per-device
        reconciliation vs ``memory_stats()``, and the JIT compile table
        with recompile-storm state (doc/observability.md)."""
        from filodb_tpu.utils import devicewatch
        data = devicewatch.device_summary()
        arenas: dict = {}
        for ds, b in self.datasets.items():
            rows = []
            for sh in b.memstore.shards(ds):
                for _key, cache in sorted(
                        getattr(sh, "device_caches", {}).items()):
                    rows.append({
                        "shard": sh.shard_num, "arena": "device-grid",
                        "owner": cache.owner, "budget": cache.budget,
                        "bytes_resident": cache.bytes_resident,
                        "blocks": len(cache.blocks),
                        "builds": cache.builds, "hits": cache.hits,
                        "evictions": cache.evictions})
                paged = getattr(sh, "paged", None)
                if paged is not None:
                    rows.append({
                        "shard": sh.shard_num, "arena": "odp-page-cache",
                        "owner": getattr(sh, "_ledger_owner", ""),
                        "budget": paged.max_bytes,
                        "bytes_resident": paged._bytes,
                        "partitions": len(paged)})
            arenas[ds] = rows
        data["arenas"] = arenas
        return 200, {"status": "success", "data": data}

    @_timed("kernels")
    def _kernels(self) -> tuple[int, dict]:
        """The kernel flight deck (ISSUE 15): per-program launches,
        compiles, sampled EWMA device time, achieved GB/s vs the
        configured HBM roof, and regression-sentry state — the live
        counterpart of doc/kernel.md's static roofline table."""
        from filodb_tpu.utils import devicewatch
        return 200, {"status": "success",
                     "data": devicewatch.kernel_summary()}

    @_timed("device_profilez")
    def _device_profilez(self, p: dict) -> tuple[int, dict]:
        """On-demand ``jax.profiler`` device trace capture: records for
        ``seconds`` (bounded) into a server-side directory and returns
        the path — the hook a training/inference stack points
        TensorBoard's profile plugin at.  Shares ONE single-flight
        guard with ``/debug/profilez``: a host stack-sampling run and a
        device trace interleaving would attribute each other's
        overhead."""
        from filodb_tpu.utils import forensics
        try:
            data = forensics.device_profile(
                seconds=float(p.get("seconds", 2.0)))
        except forensics.ProfilerBusy as e:
            return 503, error_response("unavailable", str(e))
        except forensics.DeviceProfilerUnavailable as e:
            return 501, error_response("unavailable", str(e))
        return 200, {"status": "success", "data": data}

    @_timed("flightrecorder")
    def _flightrecorder(self, p: dict) -> tuple[int, dict]:
        """The black box on demand: recent structured events (ingest
        batches, flushes, evictions, compiles, page-ins, breaker trips,
        query start/end), oldest first.  ``limit`` / ``kind`` filter."""
        from filodb_tpu.utils.devicewatch import FLIGHT
        limit = max(1, min(int(p.get("limit", 500)), 10_000))
        events = FLIGHT.events(limit=limit, kind=p.get("kind"))
        return 200, {"status": "success", "data": {
            "capacity": FLIGHT.capacity, "events": events}}

    @_timed("config")
    def _config(self, p: dict) -> tuple[int, dict]:
        """Effective configuration dump + runtime-adjustable
        observability knobs.  POST (or params) with
        ``slow-query-threshold-s`` / ``jit-storm-shapes`` /
        ``jit-storm-window-s`` / ``flight-recorder-size`` applies the
        new value immediately (no restart); the response always shows
        the effective values after any change."""
        import dataclasses as _dc
        from filodb_tpu.utils import devicewatch
        from filodb_tpu.utils.forensics import TRACE_STORE
        if "slow-query-threshold-s" in p:
            thr = float(p["slow-query-threshold-s"])
            if thr <= 0:
                return 400, error_response(
                    "bad_data", "slow-query-threshold-s must be > 0")
            TRACE_STORE.slow_threshold_s = thr
        # trace head-sampling (ISSUE 19): fraction of NORMAL
        # (sub-threshold) traces retained in /admin/traces — raising it
        # during an investigation must not require a restart
        if "trace-sample-rate" in p:
            rate = float(p["trace-sample-rate"])
            if not 0.0 <= rate <= 1.0:
                return 400, error_response(
                    "bad_data", "trace-sample-rate must be in [0, 1]")
            TRACE_STORE.sample_rate = rate
        # workload-insights knobs (ISSUE 19): the ledger is killable
        # and the co-arrival window tunable without a restart
        if "insights-enabled" in p:
            self._ensure_insights().enabled = \
                str(p["insights-enabled"]).lower() in ("true", "1")
        if "insights-co-arrival-window-ms" in p:
            window = float(p["insights-co-arrival-window-ms"])
            if window <= 0:
                return 400, error_response(
                    "bad_data",
                    "insights-co-arrival-window-ms must be > 0")
            self._ensure_insights().co_window_ms = window
        devicewatch.COMPILE_WATCH.configure(
            storm_shapes=p.get("jit-storm-shapes"),
            storm_window_s=p.get("jit-storm-window-s"))
        if "flight-recorder-size" in p:
            devicewatch.FLIGHT.resize(int(p["flight-recorder-size"]))
        # kernel flight deck (ISSUE 15): sampling rate, HBM roof, and
        # regression-sentry tuning are runtime-adjustable — raising the
        # sample rate during an incident must not require a restart
        devicewatch.KERNEL_TIMER.configure(
            sample_1_in=p.get("kernel-sample-1-in"),
            hbm_roof_bytes_per_s=p.get("hbm-roof-bytes-per-s"),
            regression_factor=p.get("kernel-regression-factor"),
            regression_window_s=p.get("kernel-regression-window-s"),
            baseline_min_samples=p.get("kernel-baseline-min-samples"))
        # workload knobs (ISSUE 5): admission budgets + quota limits are
        # runtime-adjustable across every bound dataset — overload
        # response must not require a restart
        if any(k in p for k in ("admission-max-inflight-cost",
                                "admission-tenant-max-concurrent",
                                "admission-enabled")):
            enabled = None
            if "admission-enabled" in p:
                enabled = str(p["admission-enabled"]).lower() in ("true",
                                                                  "1")
            for b in self.datasets.values():
                if b.admission is not None:
                    b.admission.configure(
                        max_inflight_cost=p.get(
                            "admission-max-inflight-cost"),
                        tenant_max_concurrent=p.get(
                            "admission-tenant-max-concurrent"),
                        enabled=enabled)
        if "quota-default-max-series" in p:
            for b in self.datasets.values():
                if b.quota is not None:
                    b.quota.configure(
                        default_limit=int(p["quota-default-max-series"]))
        if "min-remote-budget-ms" in p:
            self.min_remote_budget_ms = int(p["min-remote-budget-ms"])
        # result-cache knobs (query/resultcache.py): enable/disable and
        # resize at runtime across every bound dataset — a cache gone
        # wrong must be killable without a restart
        if "result-cache-enabled" in p or "result-cache-max-bytes" in p:
            enabled = None
            if "result-cache-enabled" in p:
                enabled = str(p["result-cache-enabled"]).lower() \
                    in ("true", "1")
            max_bytes = p.get("result-cache-max-bytes")
            for b in self.datasets.values():
                if b.resultcache is not None:
                    b.resultcache.configure(
                        enabled=enabled,
                        max_bytes=int(max_bytes)
                        if max_bytes is not None else None)
        # fleet-batching knobs (ISSUE 20, filodb_tpu/batching): the
        # co-arrival window, group-size cap, and the tier itself are
        # runtime-adjustable across every bound dataset — a batcher
        # gone wrong must be killable without a restart
        if any(k in p for k in ("batch-enabled", "batch-window-ms",
                                "batch-max-size", "batch-hot-ttl-s")):
            enabled = None
            if "batch-enabled" in p:
                enabled = str(p["batch-enabled"]).lower() in ("true", "1")
            window_ms = None
            if "batch-window-ms" in p:
                window_ms = float(p["batch-window-ms"])
                if window_ms <= 0:
                    return 400, error_response(
                        "bad_data", "batch-window-ms must be > 0")
            max_batch = None
            if "batch-max-size" in p:
                max_batch = int(p["batch-max-size"])
                if max_batch < 1:
                    return 400, error_response(
                        "bad_data", "batch-max-size must be >= 1")
            for b in self.datasets.values():
                if b.batcher is not None:
                    b.batcher.configure(
                        enabled=enabled, window_ms=window_ms,
                        max_batch=max_batch,
                        hot_ttl_s=p.get("batch-hot-ttl-s"))
        # data-plane knob (ISSUE 6): how long a lagging shard's ingested
        # offset may sit still before an ingest.stall event fires
        if "ingest-stall-window-s" in p:
            window = float(p["ingest-stall-window-s"])
            if window <= 0:
                return 400, error_response(
                    "bad_data", "ingest-stall-window-s must be > 0")
            self._ensure_watermarks().stall_window_s = window
        stores: dict = {}
        for ds, b in self.datasets.items():
            shards = b.memstore.shards(ds)
            if shards:
                stores[ds] = _dc.asdict(shards[0].config)
        workload: dict = {}
        for ds, b in self.datasets.items():
            row: dict = {}
            if b.admission is not None:
                snap = b.admission.snapshot()
                row["admission"] = {k: snap[k] for k in (
                    "enabled", "max_inflight_cost", "priority_shares",
                    "tenant_max_concurrent", "tenant_max_inflight_cost")}
            if b.quota is not None:
                qs = b.quota.snapshot()
                row["quota"] = {k: qs[k] for k in (
                    "tenant_label", "default_limit", "overrides")}
            workload[ds] = row
        rcache: dict = {}
        for ds, b in self.datasets.items():
            if b.resultcache is not None:
                snap = b.resultcache.snapshot()
                rcache[ds] = {k: snap[k] for k in ("enabled", "max_bytes")}
        batching: dict = {}
        for ds, b in self.datasets.items():
            if b.batcher is not None:
                batching[ds] = b.batcher.snapshot()
        return 200, {"status": "success", "data": {
            "datasets": stores,
            "workload": {"min-remote-budget-ms": self.min_remote_budget_ms,
                         "datasets": workload},
            "result-cache": rcache,
            "batching": batching,
            "dataplane": {
                "ingest-stall-window-s":
                    self._ensure_watermarks().stall_window_s,
            },
            "insights": {
                "enabled": self._ensure_insights().enabled,
                "max-entries": self._ensure_insights().max_entries,
                "co-arrival-window-ms":
                    self._ensure_insights().co_window_ms,
                "fingerprints": self._ensure_insights().fingerprints(),
            },
            "observability": {
                "slow-query-threshold-s": TRACE_STORE.slow_threshold_s,
                "trace-sample-rate": TRACE_STORE.sample_rate,
                "jit-storm-shapes":
                    devicewatch.COMPILE_WATCH.storm_shapes,
                "jit-storm-window-s":
                    devicewatch.COMPILE_WATCH.storm_window_s,
                "flight-recorder-size": devicewatch.FLIGHT.capacity,
                "devicewatch-enabled": devicewatch.enabled(),
                "kernel-sample-1-in":
                    devicewatch.KERNEL_TIMER.sample_1_in,
                "hbm-roof-bytes-per-s":
                    devicewatch.KERNEL_TIMER.hbm_roof_bytes_per_s,
                "kernel-regression-factor":
                    devicewatch.KERNEL_TIMER.regression_factor,
                "kernel-regression-window-s":
                    devicewatch.KERNEL_TIMER.regression_window_s,
                "kernel-baseline-min-samples":
                    devicewatch.KERNEL_TIMER.baseline_min_samples,
            }}}

    @_timed("workload")
    def _workload(self) -> tuple[int, dict]:
        """Operational view of the workload-management subsystem
        (ISSUE 5): per-dataset admission state (inflight cost, tenant
        budgets, calibration), cardinality-quota occupancy, and the
        query schedulers' depth (doc/workload.md)."""
        out: dict = {}
        for ds, b in self.datasets.items():
            row: dict = {}
            if b.admission is not None:
                row["admission"] = b.admission.snapshot()
            if b.quota is not None:
                row["quota"] = b.quota.snapshot()
            if b.scheduler is not None:
                row["queue_depth"] = b.scheduler.queue_depth()
            if b.leaf_scheduler is not None:
                row["leaf_queue_depth"] = b.leaf_scheduler.queue_depth()
            out[ds] = row
        return 200, {"status": "success", "data": {
            "min_remote_budget_ms": self.min_remote_budget_ms,
            "datasets": out}}

    @_timed("resultcache")
    def _resultcache(self, p: dict) -> tuple[int, dict]:
        """The query-frontend result cache's live state
        (doc/query-engine.md): per-dataset entry/byte residency with
        the exact-reconciliation proof, hit/miss/eviction/invalidation
        counters, and the resident instant windows.  ``clear=true``
        flushes every dataset's cache (operator action)."""
        clear = str(p.get("clear", "")).lower() in ("true", "1")
        out: dict = {}
        for ds, b in self.datasets.items():
            if b.resultcache is None:
                continue
            if clear:
                b.resultcache.clear()
            snap = b.resultcache.snapshot()
            accounted, walked = b.resultcache.reconcile()
            snap["reconcile"] = {"accounted_bytes": accounted,
                                 "walked_bytes": walked,
                                 "exact": accounted == walked}
            out[ds] = snap
        if not out:
            return 404, error_response("bad_data",
                                       "no result cache on this node")
        return 200, {"status": "success", "data": {"datasets": out}}

    # ------------------------------------------------- data-plane routes

    @_timed("cardinality")
    def _cardinality(self, p: dict) -> tuple[int, dict]:
        """The cardinality explorer (ISSUE 6): per-shard top-k label
        names x values by active-series count, per-tenant breakdown,
        and churn rates — every number derived from one atomic index
        snapshot per shard, so totals reconcile exactly with a full
        index walk even under concurrent create/evict/purge
        (doc/observability.md)."""
        from filodb_tpu.memstore.cardinality import build_report
        ds = p.get("dataset")
        if ds is None and len(self.datasets) == 1:
            ds = next(iter(self.datasets))
        binding = self.datasets.get(ds)
        if binding is None:
            return 404, error_response("bad_data",
                                       f"unknown dataset {ds}")
        topk = max(1, min(int(p.get("topk", 10)), 100))
        shard_num = int(p["shard"]) if "shard" in p else None
        tenant_label = binding.quota.tenant_label \
            if binding.quota is not None else "_ns_"
        report = build_report(ds, binding.memstore.shards(ds), topk=topk,
                              tenant_label=tenant_label,
                              shard_num=shard_num)
        return 200, {"status": "success", "data": report}

    @_timed("shards")
    def _shards(self, p: dict) -> tuple[int, dict]:
        """The ingest-plane health tree (ISSUE 6): per-shard watermark
        chain (broker_end -> ingested -> flushed -> checkpoint), lag in
        rows/seconds, flush-queue depth/age, mapper status + recovery
        progress, and stall flags.  Sampling here also advances stall
        detection, so polling the endpoint IS monitoring."""
        return 200, {"status": "success",
                     "data": self._ensure_watermarks().sample()}

    def _ensure_watermarks(self):
        """Lazy default ledger over the bound datasets (bare servers in
        tests); the standalone server installs a configured one before
        start().  Locked: two concurrent first requests must not each
        build a ledger and silently discard one's stall state."""
        with self._wm_lock:
            if self.watermarks is None:
                from filodb_tpu.memstore.watermarks import WatermarkLedger
                self.watermarks = WatermarkLedger(node=self.node_name or "")
            # sync datasets bound AFTER the ledger was built — without
            # touching already-configured watches (the standalone ledger
            # carries broker end-offset sources a re-watch would lose)
            wm = self.watermarks
            watched = set(wm.watching())
            for ds, b in self.datasets.items():
                if ds in watched:
                    continue
                mapper = None
                if self.shard_manager is not None:
                    try:
                        mapper = self.shard_manager.mapper(ds)
                    except KeyError:
                        mapper = None
                wm.watch(ds, b.memstore, mapper=mapper)
            return wm

    # -------------------------------------------- fleet workload insights

    def _ensure_insights(self):
        """Lazy default workload ledger (bare servers in tests); the
        standalone server installs a configured one before start().
        Same double-create discipline as :meth:`_ensure_watermarks`."""
        ins = self.insights
        if ins is not None:
            return ins
        with self._ins_lock:
            if self.insights is None:
                from filodb_tpu.insights.ledger import WorkloadLedger
                self.insights = WorkloadLedger(node=self.node_name or "")
            return self.insights

    def _insights_raw(self) -> dict:
        """The raw MERGEABLE bundle behind ``/admin/insights?raw=true``
        — also what FleetAggregator peers fetch.  Every section is
        either exactly mergeable (insights, slo: fixed bucket bounds,
        int counters) or summable/per-node (watermarks, replicas,
        kernels); nothing here derives from the wall clock, so two
        snapshots of a quiesced node are bit-identical (the fleet-merge
        e2e contract)."""
        ins = self._ensure_insights()
        bundle: dict = {"node": self.node_name or "",
                        "insights": ins.snapshot(),
                        "slo": self.slo.snapshot()
                        if self.slo is not None else None}
        try:
            wm = self._ensure_watermarks().sample()
            bundle["watermarks"] = {
                ds: dict(d.get("totals") or {})
                for ds, d in (wm.get("datasets") or {}).items()}
        except Exception:  # noqa: BLE001 — store mid-shutdown
            bundle["watermarks"] = {}
        replicas: dict = {}
        if self.shard_manager is not None:
            for ds in self.shard_manager.datasets():
                try:
                    m = self.shard_manager.mapper(ds)
                except KeyError:
                    continue
                statuses = [m.best_status(s).value
                            for s in range(m.num_shards)]
                replicas[ds] = {
                    "shards": m.num_shards,
                    "active": sum(1 for s in statuses if s == "Active"),
                    "down": sum(1 for s in statuses
                                if s not in ("Active", "Recovery",
                                             "Assigned"))}
        else:
            for ds, b in self.datasets.items():
                n = len(b.memstore.shards(ds))
                replicas[ds] = {"shards": n, "active": n, "down": 0}
        bundle["replicas"] = replicas
        try:
            from filodb_tpu.utils import devicewatch
            ks = devicewatch.kernel_summary()
            rows = ks.get("programs") or []
            bundle["kernels"] = {
                "enabled": bool(ks.get("enabled")),
                "programs": len(rows),
                "launches": sum(int(r.get("launches") or 0)
                                for r in rows),
                "regressed": sum(1 for r in rows if r.get("regressed"))}
        except Exception:  # noqa: BLE001 — devicewatch unavailable
            bundle["kernels"] = {"enabled": False, "programs": 0,
                                 "launches": 0, "regressed": 0}
        return bundle

    @_timed("insights")
    def _insights(self, p: dict) -> tuple[int, dict]:
        """Per-fingerprint workload analytics (ISSUE 19 pillar 1).
        Default: the human view — top-k fingerprints by ``sort``
        (cost|latency|count|qps|errors), per-tenant rollup, batching
        headroom, SLO rows.  ``raw=true``: the mergeable bundle the
        fleet console aggregates."""
        if str(p.get("raw", "")).lower() in ("true", "1", "yes"):
            return 200, {"status": "success", "data": self._insights_raw()}
        from filodb_tpu.insights import ledger as _il
        try:
            top = int(p.get("top", 20))
            if top <= 0:
                raise ValueError
        except (TypeError, ValueError):
            return 400, error_response("bad_data",
                                       "top must be a positive integer")
        sort = str(p.get("sort", "cost"))
        if sort not in ("cost", "latency", "count", "qps", "errors"):
            return 400, error_response(
                "bad_data", f"unknown sort {sort!r} (want cost|latency"
                            f"|count|qps|errors)")
        ins = self._ensure_insights()
        data = _il.view(ins.snapshot(), top=top, sort=sort)
        data["node"] = self.node_name or ""
        data["enabled"] = ins.enabled
        if self.slo is not None:
            data["slo"] = self.slo.rows()
        return 200, {"status": "success", "data": data}

    @_timed("fleet")
    def _fleet(self, p: dict) -> tuple[int, dict]:
        """The one-pane cluster console (ISSUE 19 pillar 3): the merged
        fleet tree from this node's aggregator.  ``refresh=true`` forces
        a synchronous peer poll first.  A node without peers serves the
        merged-local view through the same shape."""
        if self.fleet is None:
            # peerless aggregator: single-node deployments and bare
            # test servers still get the /admin/fleet tree shape
            from filodb_tpu.insights.fleet import FleetAggregator
            with self._ins_lock:
                if self.fleet is None:
                    self.fleet = FleetAggregator(
                        self.node_name or "", {}, self._insights_raw)
        refresh = str(p.get("refresh", "")).lower() in ("true", "1", "yes")
        return 200, {"status": "success",
                     "data": self.fleet.tree(refresh=refresh)}

    @_timed("integrity")
    def _integrity(self) -> tuple[int, dict]:
        """Operational view of the data-integrity subsystem: global
        counters, the quarantine registry, and per-shard corruption /
        invariant state (doc/integrity.md)."""
        from filodb_tpu.integrity import QUARANTINE
        from filodb_tpu.utils.observability import integrity_metrics
        m = integrity_metrics()
        shards: dict = {}
        for ds, b in self.datasets.items():
            rows = []
            for sh in b.memstore.shards(ds):
                st = sh.stats
                paged = getattr(sh, "paged", None)
                row = {"shard": sh.shard_num,
                       "chunks_corrupt": st.chunks_corrupt,
                       "chunks_quarantined": st.chunks_quarantined,
                       "page_decode_corrupt":
                           getattr(st, "page_decode_corrupt", 0),
                       "integrity_failed": sh.integrity_failed}
                if paged is not None:
                    try:
                        paged.check_invariants()
                        row["paged_cache_invariants"] = "ok"
                    except Exception as e:  # noqa: BLE001 — report, not raise
                        row["paged_cache_invariants"] = str(e)
                rows.append(row)
            shards[ds] = rows
        return 200, {"status": "success", "data": {
            "counters": {name: metric.total()
                         for name, metric in m.items()},
            "quarantine": QUARANTINE.summary(),
            "quarantined": QUARANTINE.items(),
            "shards": shards}}

    @_timed("chunkmeta")
    def _chunkmeta(self, ds: str, p: dict) -> tuple[int, dict]:
        """Chunk-level metadata for matching series (reference: the
        RawChunkMeta logical plan + CLI decodeChunkInfo debugging)."""
        from filodb_tpu.promql.parser import parse_selector
        from filodb_tpu.query.logical import RawChunkMeta

        binding = self.datasets.get(ds)
        if binding is None:
            return 404, error_response("bad_data", f"unknown dataset {ds}")
        if "match[]" not in p:
            return 400, error_response("bad_data", "match[] required")
        filters = parse_selector(p["match[]"])
        start = parse_time_ms(p.get("start", "0"))
        end = parse_time_ms(p.get("end", str(2**62 // 1000)))
        plan = RawChunkMeta(filters=tuple(filters), start_ms=start,
                            end_ms=end)
        result, _tid = self._exec(binding, plan, query=p["match[]"])
        data = [row for b in result.batches for row in b]
        return 200, {"status": "success", "data": data}

    # ---------------------------------------------------------- query routes

    @staticmethod
    def _stats_wanted(p: dict) -> bool:
        return str(p.get("stats", "")).lower() in ("true", "1", "all")

    def _finish_query(self, result, trace_id: str, body: dict, p: dict,
                      ser_s: float) -> dict:
        """Attach data.stats (Prometheus stats=true shape) to a query
        response and round off the serialize bucket."""
        if self._stats_wanted(p):
            result.stats.add_timing("serialize", ser_s)
            body["data"]["stats"] = stats_payload(result.stats, trace_id)
        return body

    @_timed("query_range")
    def _query_range(self, b: DatasetBinding, p: dict) -> tuple[int, dict]:
        query = p["query"]
        start = parse_time_ms(p["start"])
        end = parse_time_ms(p["end"])
        step = parse_duration_ms(p.get("step", "15s"))
        plan = query_range_to_logical_plan(query, start, step, end)
        result, trace_id = self._exec(b, plan, query=query, params=p)
        t0 = time.perf_counter()
        body = to_prom_matrix(result, b.metric_column)
        return 200, self._finish_query(result, trace_id, body, p,
                                       time.perf_counter() - t0)

    @_timed("query")
    def _query_instant(self, b: DatasetBinding, p: dict) -> tuple[int, dict]:
        import time as _time
        query = p["query"]
        # Prometheus default: evaluate at current server time when omitted
        time_ms = parse_time_ms(p["time"]) if "time" in p \
            else int(_time.time() * 1000)
        plan = query_to_logical_plan(query, time_ms)
        result, trace_id = self._exec(b, plan, query=query, params=p)
        t0 = time.perf_counter()
        body = to_prom_vector(result, time_ms, b.metric_column)
        return 200, self._finish_query(result, trace_id, body, p,
                                       time.perf_counter() - t0)

    @staticmethod
    def _query_context(p: dict) -> QueryContext:
        """Per-query context from request params: timeout (caps the
        end-to-end deadline budget), tenant/priority admission identity,
        and the partial-results opt-in.  The absolute deadline is minted
        HERE — every downstream wait, dispatch, and remote hop only ever
        decrements it (workload/deadline.py)."""
        import time as _time
        timeout_ms = parse_duration_ms(p["timeout"]) if "timeout" in p \
            else 30_000
        qctx = QueryContext(
            submit_time_ms=int(_time.time() * 1000),
            trace_id=TRACER.new_trace_id(),
            timeout_ms=timeout_ms,
            tenant=str(p.get("tenant", "")),
            priority=str(p.get("priority", "default")),
            allow_partial_results=str(
                p.get("allow_partial_results", "")).lower()
            in ("true", "1"),
            # tiered-resolution serving (doc/rollup.md): let clients
            # pin raw / a specific tier; default lets the router pick
            resolution_pref=str(p.get("resolution", "")),
            # ?downsample=<pixels>: visualization-grade M4 decimation
            # applied query-time at the exec root (doc/coldstore.md)
            downsample_pixels=_parse_downsample(p.get("downsample")))
        return wdl.mint(qctx)

    def _admit(self, b: DatasetBinding, ep, qctx: QueryContext):
        """The admission front door: every query handler reaches
        execution through ``_exec`` -> ``_admit`` (lint-enforced by
        tests/test_sentinel_lint.py::test_query_handlers_route_through_
        admission).  Estimates the plan's cost from the part-key index
        and asks the controller for a permit; sheds with
        AdmissionRejected (HTTP 429 + Retry-After) instead of queueing
        work that would rot."""
        if b.admission is None or not b.admission.enabled:
            # the runtime kill switch (admission-enabled=false) must
            # remove the COST MODEL from the hot path too — disabling
            # admission during an incident is exactly when a
            # misbehaving estimator must stop being consulted
            return contextlib.nullcontext()
        cost = b.admission.cost_model.estimate(ep, b.memstore)
        return b.admission.admit(qctx, cost)

    def _exec(self, b: DatasetBinding, plan, query: str = "",
              params: Optional[dict] = None):
        """Plan + admit + execute with a fresh per-query trace: mints
        the trace_id every downstream span (and remote dispatch) joins,
        splits plan/queue wall-time into the stats buckets, and feeds
        the slow-query log on completion.  Returns (result, trace_id).

        Planning happens on the ENTRY thread so the admission
        controller can price the materialized plan before any queueing;
        only execution rides the scheduler pool."""
        import time as _time
        from filodb_tpu.utils.forensics import TRACE_STORE
        qctx = self._query_context(params or {})
        t0 = _time.perf_counter()

        # workload insights (ISSUE 19): key the query ONCE on the entry
        # thread — (fingerprint, batch key) are pure functions of the
        # plan, and the co-arrival window must see arrivals, not
        # completions
        ins = self._ensure_insights()
        ins_keys = None
        if ins.enabled:
            try:
                from filodb_tpu.insights.ledger import plan_keys
                ins_keys = plan_keys(b.dataset, plan, query)
                ins.note_arrival(ins_keys[1])
                # fleet batching (ISSUE 20): carry the batch key on the
                # query context so the batcher's realized group sizes
                # land next to this key's co-arrival headroom estimate
                qctx.batch_key = ins_keys[1]
            except Exception:  # noqa: BLE001 — insights never fail a query
                ins_keys = None

        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("query.start", trace_id=qctx.trace_id,
                      dataset=b.dataset, query=query[:200])
        try:
            # ONE root span per query on the entry thread: the
            # scheduler's queue-wait/run spans and the exec tree all
            # parent under it, so /admin/traces shows a single tree
            with TRACER.attach((qctx.trace_id, None)), \
                    TRACER.span("query", dataset=b.dataset, query=query):
                t_plan = _time.perf_counter()
                with TRACER.span("query.plan"):
                    ep = b.planner.materialize(plan, qctx)
                if qctx.downsample_pixels:
                    # ?downsample=<pixels>: M4 decimation at the exec
                    # ROOT — after aggregation/functions, so the pixel
                    # budget applies to what the client actually plots
                    from filodb_tpu.query.transformers import \
                        DownsampleMapper
                    from filodb_tpu.utils.observability import \
                        downsample_metrics
                    ep.add_transformer(
                        DownsampleMapper(pixels=qctx.downsample_pixels))
                    downsample_metrics()["queries"].inc(dataset=b.dataset)
                plan_s = _time.perf_counter() - t_plan
                if not qctx.tenant:
                    from filodb_tpu.workload.admission import plan_tenant
                    qctx.tenant = plan_tenant(ep)

                def run():
                    t_run = _time.perf_counter()
                    # parent onto wherever this runs: the scheduler
                    # worker's span when queued, the root span inline
                    tok = TRACER.capture()
                    if tok[0] is None:
                        tok = (qctx.trace_id, None)
                    with TRACER.attach(tok):
                        with TRACER.span("query.execute",
                                         dataset=b.dataset,
                                         query=query) as sp:
                            res = ep.execute(ExecContext(b.memstore, qctx))
                            if res.stats.hbm_resident_delta_bytes:
                                # devicewatch: residency this query
                                # committed/released, on the trace too
                                sp.tag(hbm_delta_bytes=res.stats
                                       .hbm_resident_delta_bytes)
                            if res.stats.device_programs:
                                # kernel flight deck: the per-program
                                # device-time split, so a slow-query
                                # trace names the offending kernel
                                sp.tag(device_programs=";".join(
                                    f"{k}={v * 1e3:.3f}ms" for k, v in
                                    sorted(res.stats
                                           .device_programs.items())))
                            if qctx.rollup_resolution_ms \
                                    or qctx.rollup_routed:
                                # tiered serving: the router's decision
                                # (0 = it chose raw) on the span; the
                                # stats keep reporting only real tiers
                                if qctx.rollup_resolution_ms:
                                    res.stats.resolution_ms = \
                                        qctx.rollup_resolution_ms
                                sp.tag(resolution_ms=qctx
                                       .rollup_resolution_ms)
                            if qctx.rollup_tiers:
                                # storage-tier attribution (ISSUE 16):
                                # which stitched legs actually served —
                                # raw / rolled-local / rolled-cold —
                                # in canonical oldest-first order
                                from filodb_tpu.rollup.planner import \
                                    canonical_tiers
                                res.stats.tiers = canonical_tiers(
                                    qctx.rollup_tiers)
                                sp.tag(tiers=res.stats.tiers)
                            rc_c = res.stats.resultcache_cached_samples
                            rc_r = res.stats \
                                .resultcache_recomputed_samples
                            if rc_c or rc_r:
                                # result cache: hit (all from memoized
                                # partials) / partial / miss, on the
                                # span so slowlog shows cache behavior
                                sp.tag(resultcache="hit" if not rc_r
                                       else ("partial" if rc_c
                                             else "miss"))
                    res.stats.add_timing("plan", plan_s)
                    # queue = scheduler wait ONLY (t_submit is stamped
                    # right before submission below): planning and
                    # admission run on the entry thread and must not
                    # inflate this bucket, or sum(buckets) > total
                    res.stats.add_timing("queue", t_run - t_submit)
                    return res

                with self._admit(b, ep, qctx):
                    t_submit = _time.perf_counter()
                    if b.scheduler is not None:
                        result = b.scheduler.execute(
                            run, qctx.submit_time_ms, qctx.timeout_ms,
                            deadline_ms=qctx.deadline_ms)
                    else:
                        result = run()
        except BaseException as e:
            fail_s = _time.perf_counter() - t0
            FLIGHT.record("query.end", trace_id=qctx.trace_id,
                          dataset=b.dataset, error=repr(e)[:200],
                          seconds=round(fail_s, 6))
            TRACE_STORE.note_complete(qctx.trace_id, fail_s,
                                      query=query, dataset=b.dataset,
                                      error=repr(e))
            self._note_insight(b, ins, ins_keys, qctx, query, fail_s,
                               error=e)
            raise
        total_s = _time.perf_counter() - t0
        result.stats.timings.setdefault("total", total_s)
        FLIGHT.record("query.end", trace_id=qctx.trace_id,
                      dataset=b.dataset, seconds=round(total_s, 6))
        TRACE_STORE.note_complete(qctx.trace_id, total_s, query=query,
                                  dataset=b.dataset)
        self._note_insight(b, ins, ins_keys, qctx, query, total_s,
                           stats=result.stats)
        return result, qctx.trace_id

    def _note_insight(self, b: DatasetBinding, ins, keys, qctx,
                      query: str, total_s: float, stats=None,
                      error=None) -> None:
        """Fold one finished query into the workload ledger + SLO
        tracker.  Sheds (admission refusals, expired deadlines) are
        classified by reason; everything here is best-effort and never
        fails the query."""
        if keys is None or not ins.enabled:
            return
        try:
            shed = ""
            outcome = "ok"
            if error is not None:
                outcome = "error"
                from filodb_tpu.workload.admission import AdmissionRejected
                if isinstance(error, AdmissionRejected):
                    shed = getattr(error, "reason", "") or "overload"
                    outcome = "shed"
                elif isinstance(error, wdl.DeadlineExceeded):
                    shed = "deadline_exceeded"
                    outcome = "shed"
            rc = ""
            samples = dev_n = hbm = 0
            dev_s = 0.0
            if stats is not None:
                samples = int(stats.samples_scanned)
                hbm = sum(stats.hbm_read_bytes.values())
                dev_n = len(stats.device_programs)
                dev_s = sum(stats.device_programs.values())
                rc_c = stats.resultcache_cached_samples
                rc_r = stats.resultcache_recomputed_samples
                if rc_c or rc_r:
                    rc = "hit" if not rc_r else ("partial" if rc_c
                                                 else "miss")
            dropped = ins.note(
                keys[0], query=query, dataset=b.dataset,
                tenant=qctx.tenant or "", latency_s=total_s,
                error=error is not None, samples=samples,
                resultcache=rc, device_programs=dev_n, device_s=dev_s,
                hbm_bytes=hbm, shed_reason=shed, batch_key=keys[1])
            _INSIGHTS_M["noted"].inc(dataset=b.dataset, outcome=outcome)
            if dropped:
                _INSIGHTS_M["dropped"].inc(dropped,
                                           node=self.node_name or "")
            if self.slo is not None:
                self.slo.observe(qctx.tenant or "", qctx.priority,
                                 total_s, error=error is not None)
        except Exception:  # noqa: BLE001 — insights never fail a query
            pass

    # ------------------------------------------------------- metadata routes

    def _time_range(self, p: dict) -> tuple[int, int]:
        start = parse_time_ms(p["start"]) if "start" in p else 0
        end = parse_time_ms(p["end"]) if "end" in p else np.iinfo(np.int64).max
        return start, end

    @_timed("labels")
    def _labels(self, b: DatasetBinding, p: dict) -> tuple[int, dict]:
        start, end = self._time_range(p)
        names: set[str] = set()
        for sh in b.memstore.shards(b.dataset):
            names.update(sh.label_names(start=start, end=end))
        return 200, {"status": "success", "data": sorted(names)}

    @_timed("label_values")
    def _label_values(self, b: DatasetBinding, label: str, p: dict,
                      multi: Optional[dict] = None) -> tuple[int, dict]:
        start, end = self._time_range(p)
        matches = (multi or {}).get("match[]") or \
            (multi or {}).get("match") or []
        if matches:
            # Prometheus API: match[] restricts the series the values
            # come from (union over selectors); the remote metadata
            # exec relies on this for filtered failover routing
            from filodb_tpu.promql.parser import parse_selector
            vals: set = set()
            for match in matches:
                filters = parse_selector(match)
                for sh in b.memstore.shards(b.dataset):
                    vals.update(sh.label_values(label, filters, start,
                                                end))
            return 200, {"status": "success", "data": sorted(vals)}
        vals = b.memstore.label_values(b.dataset, label, start=start, end=end)
        return 200, {"status": "success", "data": vals}

    @_timed("series")
    def _series(self, b: DatasetBinding, p: dict,
                multi: dict) -> tuple[int, dict]:
        from filodb_tpu.core.record import parse_partkey
        from filodb_tpu.http.model import public_tags
        from filodb_tpu.promql.parser import parse_selector
        start, end = self._time_range(p)
        matches = multi.get("match[]") or multi.get("match") or []
        if not matches:
            return 400, error_response("bad_data", "match[] required")
        seen: set[tuple] = set()
        out = []
        for match in matches:  # union over all selectors (Prometheus API)
            filters = parse_selector(match)
            for sh in b.memstore.shards(b.dataset):
                res = sh.lookup_partitions(filters, start, end)
                for pid in res.part_ids:
                    part = sh._partition_for_scan(int(pid))
                    tags = part.tags if part is not None \
                        else parse_partkey(sh.index.partkey(int(pid)))
                    key = tuple(sorted(tags.items()))
                    if key not in seen:
                        seen.add(key)
                        out.append(public_tags(tags, b.metric_column))
                # evicted/on-disk series surface as missing partkeys on
                # the in-memory-only shard
                for pk in res.missing_partkeys:
                    tags = parse_partkey(pk)
                    key = tuple(sorted(tags.items()))
                    if key not in seen:
                        seen.add(key)
                        out.append(public_tags(tags, b.metric_column))
        return 200, {"status": "success", "data": out}

    # --------------------------------------------------------- admin routes

    @_timed("health")
    def _health(self) -> tuple[int, dict]:
        """Shard statuses per dataset (reference: HealthRoute returning
        ShardStatus list).  Each row carries the full replica group
        (ISSUE 7) — the status poller gossips membership, per-replica
        status, and ingest watermarks from this payload."""
        out = {}
        topology = {}
        if self.shard_manager is not None:
            for ds in self.shard_manager.datasets():
                m = self.shard_manager.mapper(ds)
                # SERVING view at the shard level (best replica): one
                # dead copy of an otherwise fully-served shard must not
                # flip healthy:false and let a load balancer drain a
                # cluster that serves 100% of the data.  Per-replica
                # truth rides in the "replicas" rows, which is what the
                # gossip consumers read on replicated payloads.
                # total_shards: in-flight split children gossip their
                # Recovery groups + watermarks here too (ISSUE 13)
                out[ds] = [
                    {"shard": s, "status": m.best_status(s).value,
                     "node": m.coord_for_shard(s),
                     "replicas": [
                         {"node": r.node, "status": r.status.value,
                          "progress": r.recovery_progress,
                          "watermark": r.watermark}
                         for r in m.replicas(s)]}
                    for s in range(m.total_shards)]
                if m.total_shards > m.num_shards:
                    # catching-up split children must not flip the node
                    # unhealthy (they are not serving yet); the healthy
                    # flag judges the SERVING shards only
                    for row in out[ds][m.num_shards:]:
                        row["in_flight_child"] = True
                topology[ds] = m.topology.as_payload()
        else:
            for ds, b in self.datasets.items():
                out[ds] = [{"shard": sh.shard_num, "status": "Active",
                            "node": "local"}
                           for sh in b.memstore.shards(ds)]
        healthy = all(st["status"] in ("Active", "Recovery", "Assigned")
                      for sts in out.values() for st in sts
                      if not st.get("in_flight_child")) if out else True
        body = {"healthy": healthy, "shards": out}
        if topology:
            body["topology"] = topology
        if self.split_progress is not None:
            try:
                body["split_progress"] = self.split_progress()
            except Exception:  # noqa: BLE001 — controller mid-shutdown
                pass
        if self.running_shards is not None:
            body["running"] = {ds: self.running_shards(ds)
                               for ds in (out or self.datasets)}
        # per-shard ingested offsets: the peer-side source for replica
        # watermarks (group head = max across the group)
        wms: dict = {}
        for ds, b in self.datasets.items():
            try:
                wms[ds] = {sh.shard_num: sh.latest_offset
                           for sh in b.memstore.shards(ds)}
            except Exception:  # noqa: BLE001 — store mid-shutdown
                continue
        if wms:
            body["watermarks"] = wms
        # rollup tier closure watermarks for the shards THIS node rolls
        # (ROADMAP 2b): peers fold them into their TierWatermarks store
        # so a multi-node coordinator stitches raw/rolled at the
        # CLUSTER-wide boundary instead of its local engine's
        if self.rollup is not None:
            try:
                rolled = self.rollup.rolled_snapshot()
            except Exception:  # noqa: BLE001 — engine mid-shutdown
                rolled = {}
            if rolled:
                body["rollup"] = rolled
        if self.node_name:
            body["node"] = self.node_name
        return (200 if healthy else 503), body

    @_timed("cluster")
    def _cluster(self, parts: list[str], params: dict) -> tuple[int, dict]:
        """/api/v1/cluster/<ds>/status|startshards|stopshards (reference:
        ClusterApiRoute)."""
        if self.shard_manager is None:
            return 404, error_response("bad_data", "no cluster manager")
        if not parts:
            return 200, {"status": "success",
                         "data": self.shard_manager.datasets()}
        ds = parts[0]
        action = parts[1] if len(parts) > 1 else "status"
        m = self.shard_manager.mapper(ds)
        if action == "status":
            # SERVING view (ISSUE 7): a shard with any queryable
            # replica reports that status — a dead primary must not
            # show a served shard as down; the replicas list carries
            # each copy's own truth
            rows = []
            for s in range(m.num_shards):
                st = m.state(s)
                best = st.best_status
                serving = st.serving_replica()
                rows.append({
                    "shard": s, "status": best.value,
                    "node": serving.node if serving is not None
                    else st.node,
                    "replicas": [{"node": r.node,
                                  "status": r.status.value,
                                  "watermark": r.watermark}
                                 for r in st.replicas]})
            return 200, {"status": "success", "data": rows}
        shards = [int(s) for s in str(params.get("shards", "")).split(",") if s]
        if action == "startshards":
            done = self.shard_manager.start_shards(ds, shards,
                                                   params["node"])
            return 200, {"status": "success", "data": done}
        if action == "stopshards":
            done = self.shard_manager.stop_shards(ds, shards)
            return 200, {"status": "success", "data": done}
        return 404, error_response("bad_data", f"unknown action {action}")
