"""Prometheus HTTP API data model: results -> Prometheus JSON.

Capability match for the reference's PrometheusModel (reference:
prometheus/src/main/scala/filodb/prometheus/query/PrometheusModel.scala:12
— QueryResult -> matrix/vector JSON; histogram -> bucket series) and the
PromQueryResponse shapes (query/.../PromQueryResponse.scala).
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from filodb_tpu.query.model import (PeriodicBatch, QueryResult, RawBatch,
                                    ScalarResult)


def _fmt(v: float) -> str:
    """Prometheus value formatting: shortest repr, NaN as \"NaN\"."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def public_tags(tags: dict, metric_column: str = "_metric_") -> dict:
    """Internal metric column -> Prometheus ``__name__`` on the way out
    (reference: PrometheusModel metric-name conversion)."""
    if metric_column in tags:
        out = {k: v for k, v in tags.items() if k != metric_column}
        out["__name__"] = tags[metric_column]
        return out
    return dict(tags)


def _matrix_entry(tags: dict, ts_ms: np.ndarray, vals: np.ndarray,
                  metric_column: str = "_metric_") -> Optional[dict]:
    fin = ~np.isnan(vals)
    if not fin.any():
        return None
    return {"metric": public_tags(tags, metric_column),
            "values": [[ts_ms[i] / 1000.0, _fmt(float(vals[i]))]
                       for i in np.flatnonzero(fin)]}


def _attach_warnings(resp: dict, result: QueryResult) -> dict:
    """Prometheus-style ``warnings`` for partial results: quarantined
    (corrupt) chunks were excluded from the scan, or a shard's node was
    unreachable and the query opted into ``allow_partial_results`` —
    the caller gets real data plus a loud flag, never wrong values and
    never silence.  The HTTP server mirrors this as an
    X-FiloDB-Partial-Data header."""
    warnings = []
    n = result.stats.corrupt_chunks_excluded
    if n:
        warnings.append(
            f"partial data: {n} corrupt chunk(s) quarantined and "
            f"excluded from results (see /admin/integrity)")
        from filodb_tpu.utils.observability import integrity_metrics
        integrity_metrics()["partial_queries"].inc()
    down = result.stats.shards_down
    if down:
        warnings.append(
            f"partial data: {down} shard(s) unreachable; their series "
            f"are missing from results (allow_partial_results)")
        from filodb_tpu.utils.observability import workload_metrics
        workload_metrics()["partial_shards"].inc()
    if warnings:
        resp["warnings"] = warnings
    return resp


def to_prom_matrix(result: QueryResult,
                   metric_column: str = "_metric_") -> dict:
    """Range-query response (resultType=matrix)."""
    out = []
    for b in result.batches:
        if isinstance(b, PeriodicBatch):
            for tags, ts, vals in b.to_series():
                e = _matrix_entry(tags, ts, vals, metric_column)
                if e is not None:
                    out.append(e)
        elif isinstance(b, ScalarResult):
            ts = np.asarray(b.steps.timestamps())
            e = _matrix_entry({}, ts, np.asarray(b.values))
            if e is not None:
                out.append(e)
        elif isinstance(b, RawBatch) and b.batch is not None:
            for i, tags in enumerate(b.keys):
                n = int(b.batch.row_counts[i])
                e = _matrix_entry(tags,
                                  np.asarray(b.batch.timestamps[i][:n]),
                                  np.asarray(b.batch.values[i][:n]))
                if e is not None:
                    out.append(e)
    return _attach_warnings(
        {"status": "success",
         "data": {"resultType": "matrix", "result": out}}, result)


def to_prom_vector(result: QueryResult, time_ms: int,
                   metric_column: str = "_metric_") -> dict:
    """Instant-query response (resultType=vector): last value at/before
    the evaluation timestamp."""
    out = []
    for b in result.batches:
        if isinstance(b, PeriodicBatch):
            for tags, ts, vals in b.to_series():
                fin = np.flatnonzero(~np.isnan(vals) & (ts <= time_ms))
                if len(fin):
                    i = fin[-1]
                    out.append({"metric": public_tags(tags, metric_column),
                                "value": [time_ms / 1000.0,
                                          _fmt(float(vals[i]))]})
        elif isinstance(b, ScalarResult):
            vals = np.asarray(b.values)
            if len(vals):
                return _attach_warnings(
                    {"status": "success",
                     "data": {"resultType": "scalar",
                              "value": [time_ms / 1000.0,
                                        _fmt(float(vals[-1]))]}}, result)
    return _attach_warnings(
        {"status": "success",
         "data": {"resultType": "vector", "result": out}}, result)


def error_response(error_type: str, message: str) -> dict:
    return {"status": "error", "errorType": error_type, "error": message}


def stats_payload(stats, trace_id: str = "") -> dict:
    """``stats=true`` response block (Prometheus-compatible placement:
    ``data.stats.timings`` / ``data.stats.samples``).  Timings are the
    per-stage wall-time buckets in seconds (plan/queue/scan/decode/
    device_compute/serialize/total); samples are the scan-volume
    counters merged up the exec tree, remote shards included."""
    return {
        "timings": {k: round(float(v), 6)
                    for k, v in sorted(stats.timings.items())},
        "samples": {
            "samplesScanned": int(stats.samples_scanned),
            "seriesScanned": int(stats.series_scanned),
            "chunksScanned": int(stats.chunks_scanned),
            "bytesScanned": int(stats.bytes_scanned),
            "pagesIn": int(stats.pages_in),
            "corruptChunksExcluded": int(stats.corrupt_chunks_excluded),
            # shards degraded to empty results under
            # allow_partial_results (workload subsystem)
            "shardsDown": int(stats.shards_down),
            # device-grid HBM reads under device_compute, by resident
            # format — shows whether compressed residents serve traffic
            "hbmReadBytes": {k: int(v)
                             for k, v in sorted(
                                 stats.hbm_read_bytes.items())},
            # net ledger-tracked HBM residency change this query caused
            # (devicewatch: blocks committed minus freed; 0 when warm)
            "hbmResidentDeltaBytes": int(stats.hbm_resident_delta_bytes),
        },
        # tiered-resolution serving (doc/rollup.md): the coarsest rolled
        # tier that served (part of) this query; 0 = raw only
        "resolutionMs": int(getattr(stats, "resolution_ms", 0)),
        # storage tiers the stitched plan actually materialized legs
        # for, oldest first ("rolled-cold+rolled-local+raw"); '' when
        # the dataset has no router (doc/coldstore.md)
        "tiers": str(getattr(stats, "tiers", "")),
        # cold tier (doc/coldstore.md): chunks/bytes paged back from
        # the object bucket for this query; 0/0 = cold-miss-free
        "coldTier": {
            "chunksPaged": int(getattr(stats, "cold_chunks_paged", 0)),
            "bytesRead": int(getattr(stats, "cold_bytes_read", 0)),
        },
        # ?downsample=<pixels> M4 decimation: finite points entering
        # the mapper vs pixel-exact points kept (<= ~4x pixels/series)
        "downsample": {
            "pointsIn": int(getattr(stats, "downsample_points_in", 0)),
            "pointsOut": int(getattr(stats, "downsample_points_out", 0)),
        },
        # kernel flight deck (ISSUE 15, doc/observability.md): measured
        # device seconds per wrapped program from the launches SAMPLED
        # during this query — the per-program split of the
        # device_compute timing bucket (names the offending kernel)
        "devicePrograms": {k: round(float(v), 6)
                           for k, v in sorted(getattr(
                               stats, "device_programs", {}).items())},
        # query-frontend result cache (doc/query-engine.md): result
        # samples served from memoized immutable-chunk partials vs
        # samples re-scanned fresh this evaluation
        "resultCache": {
            "cachedSamples": int(getattr(
                stats, "resultcache_cached_samples", 0)),
            "recomputedSamples": int(getattr(
                stats, "resultcache_recomputed_samples", 0)),
        },
        "traceId": trace_id,
    }


# ---------------------------------------------------------------------------
# Parameter parsing (Prometheus API conventions)
# ---------------------------------------------------------------------------

_DUR_UNITS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
              "d": 86_400_000, "w": 7 * 86_400_000, "y": 365 * 86_400_000}


def parse_time_ms(v: str) -> int:
    """Unix seconds (possibly fractional) -> epoch millis."""
    return int(float(v) * 1000)


def parse_duration_ms(v: str) -> int:
    """'15s' / '1m' / '250ms' / plain seconds -> millis."""
    s = v.strip()
    for unit in ("ms", "y", "w", "d", "h", "m", "s"):
        if s.endswith(unit):
            return int(float(s[:-len(unit)]) * _DUR_UNITS[unit])
    return int(float(s) * 1000)
