"""HTTP API (reference: http/ module)."""

from filodb_tpu.http.model import to_prom_matrix, to_prom_vector  # noqa: F401
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer  # noqa: F401
