"""Prometheus remote-storage protocol: protobuf wire codec + /read logic.

Capability match for the reference's remote-read endpoint (reference:
prometheus/src/main/proto/remote-storage.proto — the wire contract;
prometheus/src/main/scala/filodb/prometheus/query/PrometheusModel.scala:12
ReadRequest/ReadResponse conversions; http/.../PrometheusApiRoute.scala:38-60
`/promql/<ds>/api/v1/read` route).  The reference ships 6.9k lines of
protoc-generated Java; the schema is five tiny messages, so here the
wire codec is hand-rolled (~100 lines) against the same .proto:

    Sample{1:double value, 2:int64 timestamp_ms}
    LabelPair{1:string name, 2:string value}
    TimeSeries{1:rep LabelPair, 2:rep Sample}
    ReadRequest{1:rep Query} / ReadResponse{1:rep QueryResult}
    Query{1:int64 start, 2:int64 end, 3:rep LabelMatcher}
    LabelMatcher{1:enum type(EQ/NEQ/RE/NRE), 2:name, 3:value}
    QueryResult{1:rep TimeSeries}
    WriteRequest{1:rep TimeSeries}

Payloads are snappy-block-compressed (filodb_tpu/utils/snappy.py), as
Prometheus remote read/write requires.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Iterator, Sequence

MATCH_EQUAL = 0
MATCH_NOT_EQUAL = 1
MATCH_REGEX = 2
MATCH_NOT_REGEX = 3


# ---------------------------------------------------------------------------
# protobuf wire primitives
# ---------------------------------------------------------------------------

from filodb_tpu.utils.leb128 import decode as _read_uvarint
from filodb_tpu.utils.leb128 import encode as _uvarint


def _zig64(n: int) -> int:
    return n & 0xFFFFFFFFFFFFFFFF  # int64 as two's-complement varint


def _as_int64(u: int) -> int:
    return u - (1 << 64) if u >= 1 << 63 else u


def _field(tag: int, wire: int) -> bytes:
    return _uvarint((tag << 3) | wire)


def _len_field(tag: int, payload: bytes) -> bytes:
    return _field(tag, 2) + _uvarint(len(payload)) + payload


def _iter_fields(buf: bytes) -> Iterator[tuple[int, int, object]]:
    """Yield (tag, wire_type, value); value is int for varint/fixed,
    bytes for length-delimited."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_uvarint(buf, pos)
        tag, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_uvarint(buf, pos)
            yield tag, wire, val
        elif wire == 1:
            if pos + 8 > len(buf):
                raise ValueError("truncated fixed64")
            yield tag, wire, int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wire == 2:
            ln, pos = _read_uvarint(buf, pos)
            if pos + ln > len(buf):
                raise ValueError("truncated bytes field")
            yield tag, wire, buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            if pos + 4 > len(buf):
                raise ValueError("truncated fixed32")
            yield tag, wire, int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LabelMatcher:
    type: int
    name: str
    value: str


@dataclasses.dataclass
class RemoteQuery:
    start_ms: int
    end_ms: int
    matchers: list[LabelMatcher]


def decode_read_request(buf: bytes) -> list[RemoteQuery]:
    queries = []
    for tag, wire, val in _iter_fields(buf):
        if tag == 1 and wire == 2:
            queries.append(_decode_query(val))
    return queries


def _decode_query(buf: bytes) -> RemoteQuery:
    start = end = 0
    matchers: list[LabelMatcher] = []
    for tag, wire, val in _iter_fields(buf):
        if tag == 1 and wire == 0:
            start = _as_int64(val)
        elif tag == 2 and wire == 0:
            end = _as_int64(val)
        elif tag == 3 and wire == 2:
            matchers.append(_decode_matcher(val))
    return RemoteQuery(start, end, matchers)


def _decode_matcher(buf: bytes) -> LabelMatcher:
    mtype = MATCH_EQUAL
    name = value = ""
    for tag, wire, val in _iter_fields(buf):
        if tag == 1 and wire == 0:
            mtype = val
        elif tag == 2 and wire == 2:
            name = val.decode()
        elif tag == 3 and wire == 2:
            value = val.decode()
    return LabelMatcher(mtype, name, value)


def encode_read_request(queries: Sequence[RemoteQuery]) -> bytes:
    out = bytearray()
    for q in queries:
        body = bytearray()
        body += _field(1, 0) + _uvarint(_zig64(q.start_ms))
        body += _field(2, 0) + _uvarint(_zig64(q.end_ms))
        for m in q.matchers:
            mb = bytearray()
            if m.type:
                mb += _field(1, 0) + _uvarint(m.type)
            mb += _len_field(2, m.name.encode())
            mb += _len_field(3, m.value.encode())
            body += _len_field(3, bytes(mb))
        out += _len_field(1, bytes(body))
    return bytes(out)


def encode_time_series(labels: dict, ts, vals) -> bytes:
    body = bytearray()
    for k in sorted(labels):
        pair = _len_field(1, k.encode()) + _len_field(2, str(labels[k]).encode())
        body += _len_field(1, pair)
    for t, v in zip(ts, vals):
        sample = (_field(1, 1) + struct.pack("<d", float(v))
                  + _field(2, 0) + _uvarint(_zig64(int(t))))
        body += _len_field(2, sample)
    return bytes(body)


def encode_read_response(per_query_series: Sequence[Sequence[bytes]]) -> bytes:
    """per_query_series[i] = encoded TimeSeries blobs for request query i."""
    out = bytearray()
    for series_list in per_query_series:
        qr = bytearray()
        for ts_blob in series_list:
            qr += _len_field(1, ts_blob)
        out += _len_field(1, bytes(qr))
    return bytes(out)


def decode_read_response(buf: bytes) -> list[list[tuple[dict, list, list]]]:
    """Inverse of encode_read_response: [[(labels, ts, vals), ...], ...].
    Used by tests and by PromQlRemoteExec-style clients."""
    results = []
    for tag, wire, val in _iter_fields(buf):
        if tag == 1 and wire == 2:
            series = []
            for t2, w2, v2 in _iter_fields(val):
                if t2 == 1 and w2 == 2:
                    series.append(_decode_time_series(v2))
            results.append(series)
    return results


def _decode_time_series(buf: bytes) -> tuple[dict, list, list]:
    labels: dict[str, str] = {}
    ts: list[int] = []
    vals: list[float] = []
    for tag, wire, val in _iter_fields(buf):
        if tag == 1 and wire == 2:
            name = value = ""
            for t2, w2, v2 in _iter_fields(val):
                if t2 == 1 and w2 == 2:
                    name = v2.decode()
                elif t2 == 2 and w2 == 2:
                    value = v2.decode()
            labels[name] = value
        elif tag == 2 and wire == 2:
            v = 0.0
            t = 0
            for t2, w2, v2 in _iter_fields(val):
                if t2 == 1 and w2 == 1:
                    v = struct.unpack("<d", v2.to_bytes(8, "little"))[0]
                elif t2 == 2 and w2 == 0:
                    t = _as_int64(v2)
            ts.append(t)
            vals.append(v)
    return labels, ts, vals


def decode_write_request(buf: bytes) -> list[tuple[dict, list, list]]:
    """WriteRequest -> [(labels, ts_list, val_list)] (remote-write edge)."""
    out = []
    for tag, wire, val in _iter_fields(buf):
        if tag == 1 and wire == 2:
            out.append(_decode_time_series(val))
    return out


def encode_write_request(series: Sequence[tuple[dict, Sequence, Sequence]]
                         ) -> bytes:
    out = bytearray()
    for labels, ts, vals in series:
        out += _len_field(1, encode_time_series(labels, ts, vals))
    return bytes(out)


# ---------------------------------------------------------------------------
# matcher -> ColumnFilter conversion
# ---------------------------------------------------------------------------

def matchers_to_filters(matchers: Sequence[LabelMatcher],
                        metric_column: str = "_metric_"):
    """Remote-read matchers to the engine's ColumnFilters; ``__name__``
    maps onto the dataset's metric column (reference: PrometheusModel
    conversions)."""
    from filodb_tpu.core.filters import (ColumnFilter, Equals, EqualsRegex,
                                         NotEquals, NotEqualsRegex)
    out = []
    ctor = {MATCH_EQUAL: Equals, MATCH_NOT_EQUAL: NotEquals,
            MATCH_REGEX: EqualsRegex, MATCH_NOT_REGEX: NotEqualsRegex}
    for m in matchers:
        col = metric_column if m.name == "__name__" else m.name
        c = ctor.get(m.type)
        if c is None:
            raise ValueError(f"unknown matcher type {m.type}")
        out.append(ColumnFilter(col, c(m.value)))
    return out
