"""Kafka-capability ingest transport: a partitioned, offset-faithful
message broker + client + per-shard ingestion streams.

Capability match for the reference's kafka/ module (reference:
kafka/src/main/scala/filodb.kafka/KafkaIngestionStream.scala:24-63 — one
consumer per shard = one topic partition, messages are RecordContainer
bytes, offsets are the checkpointable positions;
KafkaDownsamplePublisher.scala:17 — downsample output re-published to
per-resolution topics).  The broker speaks a compact length-prefixed
binary protocol over TCP and keeps one append-only log per (topic,
partition), optionally durable on disk, so recovery genuinely replays
from broker offsets after a process restart — the property the
reference's Kafka integration exists to provide.

Wire protocol (all little-endian):

    request  := u32 frame_len, u8 cmd, payload
    response := u32 frame_len, u8 status (0=ok), payload
    str      := u16 len, utf-8 bytes
    blob     := u32 len, bytes

    PRODUCE (1): str topic, u32 partition, blob message -> i64 offset
    FETCH   (2): str topic, u32 partition, i64 offset, u32 max_bytes,
                 u32 wait_ms -> u32 count, count * (i64 offset, blob)
    END     (3): str topic, u32 partition -> i64 log_end_offset
    CREATE  (4): str topic, u32 n_partitions -> u32 n_partitions
    META    (5): str topic -> u32 n_partitions (0 = unknown topic)

This is intentionally not the Kafka wire protocol (no client library may
be installed in this environment); it is the same *capability*:
partitioned durable logs addressed by monotonic offsets with long-poll
consumption.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
import time
from typing import Iterator, Optional

from filodb_tpu.ingest.stream import (IngestionStream, IngestionStreamFactory,
                                      StreamElement, register_source_factory)

CMD_PRODUCE = 1
CMD_FETCH = 2
CMD_END = 3
CMD_CREATE = 4
CMD_META = 5

STATUS_OK = 0
STATUS_ERR = 1

_MAX_FRAME = 64 * 1024 * 1024


class BrokerError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# server-side log
# ---------------------------------------------------------------------------

class PartitionLog:
    """One (topic, partition) append-only log.  Offsets are dense from 0.
    With ``path`` set, every record is appended to disk as
    ``u32 len + bytes`` and recovered on restart (the Kafka durability
    contract checkpoints rely on)."""

    def __init__(self, path: Optional[str] = None):
        self._messages: list[bytes] = []
        self._cond = threading.Condition()
        self._path = path
        self._file = None
        if path is not None:
            if os.path.exists(path):
                self._recover(path)
            self._file = open(path, "ab")

    def _recover(self, path: str) -> None:
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + 4 <= len(data):
            (ln,) = struct.unpack_from("<I", data, pos)
            if pos + 4 + ln > len(data):
                break  # torn tail write: drop it (Kafka truncates too)
            self._messages.append(data[pos + 4:pos + 4 + ln])
            pos += 4 + ln

    def append(self, message: bytes) -> int:
        with self._cond:
            off = len(self._messages)
            if self._file is not None:
                self._file.write(struct.pack("<I", len(message)) + message)
                self._file.flush()
            self._messages.append(message)
            self._cond.notify_all()
            return off

    def end_offset(self) -> int:
        with self._cond:
            return len(self._messages)

    def fetch(self, offset: int, max_bytes: int,
              wait_ms: int) -> list[tuple[int, bytes]]:
        deadline = time.monotonic() + wait_ms / 1000.0
        with self._cond:
            while offset >= len(self._messages):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)
            out = []
            total = 0
            off = max(offset, 0)
            while off < len(self._messages):
                m = self._messages[off]
                if out and total + len(m) > max_bytes:
                    break
                out.append((off, m))
                total += len(m)
                off += 1
            return out

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class _BrokerState:
    def __init__(self, data_dir: Optional[str] = None):
        self.data_dir = data_dir
        self.topics: dict[str, list[PartitionLog]] = {}
        self.lock = threading.Lock()
        if data_dir is not None:
            os.makedirs(data_dir, exist_ok=True)
            self._recover_topics()

    def _recover_topics(self) -> None:
        by_topic: dict[str, int] = {}
        for name in os.listdir(self.data_dir):
            if not name.endswith(".log") or "-p" not in name:
                continue
            base = name[:-4]
            topic, _, pstr = base.rpartition("-p")
            try:
                p = int(pstr)
            except ValueError:
                continue
            by_topic[topic] = max(by_topic.get(topic, 0), p + 1)
        for topic, nparts in by_topic.items():
            self.create(topic, nparts)

    def _log_path(self, topic: str, partition: int) -> Optional[str]:
        if self.data_dir is None:
            return None
        return os.path.join(self.data_dir, f"{topic}-p{partition}.log")

    def create(self, topic: str, n_partitions: int) -> int:
        if n_partitions <= 0 or n_partitions > 4096:
            raise BrokerError(f"bad partition count {n_partitions}")
        with self.lock:
            logs = self.topics.get(topic)
            if logs is None:
                self.topics[topic] = [
                    PartitionLog(self._log_path(topic, p))
                    for p in range(n_partitions)]
            elif len(logs) < n_partitions:
                logs.extend(PartitionLog(self._log_path(topic, p))
                            for p in range(len(logs), n_partitions))
            return len(self.topics[topic])

    def log(self, topic: str, partition: int) -> PartitionLog:
        with self.lock:
            logs = self.topics.get(topic)
            if logs is None or partition >= len(logs) or partition < 0:
                raise BrokerError(f"unknown {topic}[{partition}]")
            return logs[partition]

    def close(self) -> None:
        with self.lock:
            for logs in self.topics.values():
                for lg in logs:
                    lg.close()


# ---------------------------------------------------------------------------
# framing helpers
# ---------------------------------------------------------------------------

def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(n - got)
        if not b:
            raise ConnectionError("peer closed")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def _read_frame(sock) -> bytes:
    (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
    if ln > _MAX_FRAME:
        raise BrokerError(f"frame too large: {ln}")
    return _recv_exact(sock, ln)


def _write_frame(sock, payload: bytes) -> None:
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<H", len(b)) + b


def _unpack_str(buf: bytes, pos: int) -> tuple[str, int]:
    (ln,) = struct.unpack_from("<H", buf, pos)
    pos += 2
    return buf[pos:pos + ln].decode(), pos + ln


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        state: _BrokerState = self.server.state  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                frame = _read_frame(sock)
                try:
                    resp = self._dispatch(state, frame)
                    _write_frame(sock, bytes([STATUS_OK]) + resp)
                except (BrokerError, struct.error, IndexError,
                        UnicodeDecodeError, ValueError) as e:
                    # malformed frames get an error response, not a dead
                    # connection (the client would otherwise stall until
                    # timeout and re-send the same bad frame forever)
                    _write_frame(sock, bytes([STATUS_ERR]) + str(e).encode())
        except (ConnectionError, OSError):
            return

    def _dispatch(self, state: _BrokerState, frame: bytes) -> bytes:
        if not frame:
            raise BrokerError("empty frame")
        cmd = frame[0]
        pos = 1
        if cmd == CMD_PRODUCE:
            topic, pos = _unpack_str(frame, pos)
            (partition,) = struct.unpack_from("<I", frame, pos)
            pos += 4
            (mlen,) = struct.unpack_from("<I", frame, pos)
            pos += 4
            message = frame[pos:pos + mlen]
            if len(message) != mlen:
                raise BrokerError("truncated message")
            off = state.log(topic, partition).append(message)
            return struct.pack("<q", off)
        if cmd == CMD_FETCH:
            topic, pos = _unpack_str(frame, pos)
            partition, = struct.unpack_from("<I", frame, pos); pos += 4
            offset, = struct.unpack_from("<q", frame, pos); pos += 8
            max_bytes, = struct.unpack_from("<I", frame, pos); pos += 4
            wait_ms, = struct.unpack_from("<I", frame, pos); pos += 4
            batch = state.log(topic, partition).fetch(
                offset, min(max_bytes, _MAX_FRAME // 2), min(wait_ms, 30_000))
            out = [struct.pack("<I", len(batch))]
            for off, m in batch:
                out.append(struct.pack("<qI", off, len(m)))
                out.append(m)
            return b"".join(out)
        if cmd == CMD_END:
            topic, pos = _unpack_str(frame, pos)
            (partition,) = struct.unpack_from("<I", frame, pos)
            return struct.pack("<q", state.log(topic, partition).end_offset())
        if cmd == CMD_CREATE:
            topic, pos = _unpack_str(frame, pos)
            (nparts,) = struct.unpack_from("<I", frame, pos)
            return struct.pack("<I", state.create(topic, nparts))
        if cmd == CMD_META:
            topic, pos = _unpack_str(frame, pos)
            with state.lock:
                logs = state.topics.get(topic)
            return struct.pack("<I", 0 if logs is None else len(logs))
        raise BrokerError(f"unknown command {cmd}")


class _TCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class BrokerServer:
    """Standalone broker process core: ``start()`` returns the bound port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir: Optional[str] = None):
        self.state = _BrokerState(data_dir)
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.state = self.state  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="broker", daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self.state.close()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class BrokerClient:
    """Blocking client; safe for use from multiple threads (one in-flight
    request at a time, like a single Kafka connection)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9092,
                 timeout_s: float = 35.0):
        self.host, self.port = host, port
        self._timeout = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self._timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _call(self, payload: bytes) -> bytes:
        with self._lock:
            try:
                sock = self._connect()
                _write_frame(sock, payload)
                resp = _read_frame(sock)
            except (ConnectionError, OSError):
                # one transparent reconnect (broker restarts are normal)
                self.close()
                sock = self._connect()
                _write_frame(sock, payload)
                resp = _read_frame(sock)
        if not resp or resp[0] != STATUS_OK:
            raise BrokerError(resp[1:].decode(errors="replace")
                              if len(resp) > 1 else "broker error")
        return resp[1:]

    def create_topic(self, topic: str, n_partitions: int) -> int:
        out = self._call(bytes([CMD_CREATE]) + _pack_str(topic)
                         + struct.pack("<I", n_partitions))
        return struct.unpack("<I", out)[0]

    def num_partitions(self, topic: str) -> int:
        out = self._call(bytes([CMD_META]) + _pack_str(topic))
        return struct.unpack("<I", out)[0]

    def produce(self, topic: str, partition: int, message: bytes) -> int:
        out = self._call(bytes([CMD_PRODUCE]) + _pack_str(topic)
                         + struct.pack("<I", partition)
                         + struct.pack("<I", len(message)) + message)
        return struct.unpack("<q", out)[0]

    def end_offset(self, topic: str, partition: int) -> int:
        out = self._call(bytes([CMD_END]) + _pack_str(topic)
                         + struct.pack("<I", partition))
        return struct.unpack("<q", out)[0]

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 4 * 1024 * 1024,
              wait_ms: int = 100) -> list[tuple[int, bytes]]:
        out = self._call(bytes([CMD_FETCH]) + _pack_str(topic)
                         + struct.pack("<IqII", partition, offset,
                                       max_bytes, wait_ms))
        (count,) = struct.unpack_from("<I", out, 0)
        pos = 4
        batch = []
        for _ in range(count):
            off, mlen = struct.unpack_from("<qI", out, pos)
            pos += 12
            batch.append((off, out[pos:pos + mlen]))
            pos += mlen
        return batch

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


# ---------------------------------------------------------------------------
# ingestion stream + producer + downsample publisher
# ---------------------------------------------------------------------------

class BrokerIngestionStream(IngestionStream):
    """One shard's consumer: shard N reads topic partition N from
    ``offset`` onward, long-polling; ``teardown()`` ends the iterator
    (reference: KafkaIngestionStream.scala:24-63 — Consumer assigned to
    TopicPartition(shard), seek(offset))."""

    def __init__(self, client: BrokerClient, topic: str, shard: int,
                 offset: int = 0, poll_wait_ms: int = 200,
                 stop_at_end: bool = False):
        self._client = client
        self._topic = topic
        self._shard = shard
        self._offset = max(offset, 0)
        self._wait = poll_wait_ms
        self._stop_at_end = stop_at_end
        self._stopped = threading.Event()

    def get(self) -> Iterator[StreamElement]:
        while not self._stopped.is_set():
            batch = self._client.fetch(self._topic, self._shard,
                                       self._offset, wait_ms=self._wait)
            if not batch:
                if self._stop_at_end:
                    return
                continue
            for off, message in batch:
                self._offset = off + 1
                yield off, message
        return

    def teardown(self) -> None:
        self._stopped.set()


class BrokerIngestionStreamFactory(IngestionStreamFactory):
    """``sourcefactory: "broker"`` — config gives host/port/topic; topic
    defaults to the dataset name, partitions = shards (reference:
    KafkaIngestionStream.Factory + sourceconfig topic mapping)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9092,
                 topic: Optional[str] = None, poll_wait_ms: int = 200,
                 stop_at_end: bool = False):
        self.host, self.port = host, port
        self.topic = topic
        self.poll_wait_ms = poll_wait_ms
        self.stop_at_end = stop_at_end
        # elastic resharding (ISSUE 13): the topic's partition count is
        # fixed at dataset creation, but the SERVING shard count can
        # double live — shard s and its split child s + N both consume
        # partition s (the child filters to its half), keeping every
        # replica's offsets in one comparable domain.  Set by the
        # standalone wiring; 0 = 1:1 legacy mapping.
        self.base_partitions = 0

    def create(self, dataset: str, shard: int,
               offset: Optional[int] = None) -> BrokerIngestionStream:
        client = BrokerClient(self.host, self.port)
        partition = shard % self.base_partitions if self.base_partitions \
            else shard
        return BrokerIngestionStream(client, self.topic or dataset,
                                     partition, offset or 0,
                                     self.poll_wait_ms, self.stop_at_end)


class BrokerProducer:
    """Shard-addressed container producer (the gateway's publish side)."""

    def __init__(self, client: BrokerClient, topic: str,
                 num_shards: Optional[int] = None):
        self._client = client
        self.topic = topic
        # partition mapping base (ISSUE 13): a post-split publisher
        # computes shards in the doubled space, but the topic keeps its
        # creation-time partitions — child s + N folds onto partition s,
        # which both halves' consumers read with their own filters
        self.base_partitions = num_shards or 0
        if num_shards is not None:
            client.create_topic(topic, num_shards)

    def publish(self, shard: int, container: bytes) -> int:
        partition = shard % self.base_partitions if self.base_partitions \
            else shard
        return self._client.produce(self.topic, partition, container)


class BrokerDownsamplePublisher:
    """Flush-time downsample records go to per-resolution topics
    ``<dataset>-ds-<resolution_ms>`` with partition = shard (reference:
    KafkaDownsamplePublisher.scala:17).  Implements the
    DownsamplePublisher protocol (downsample/sharddown.py)."""

    def __init__(self, client: BrokerClient, dataset: str,
                 resolutions_ms, num_shards: int):
        self._client = client
        self.dataset = dataset
        self.topics = {int(res): f"{dataset}-ds-{int(res)}"
                       for res in resolutions_ms}
        for t in self.topics.values():
            client.create_topic(t, num_shards)

    def topic_for(self, resolution_ms: int) -> str:
        return self.topics[int(resolution_ms)]

    def publish(self, resolution_ms: int, shard: int, containers) -> None:
        topic = self.topics[int(resolution_ms)]
        for c in containers:
            self._client.produce(topic, shard, bytes(c))


def _broker_factory(**kwargs) -> BrokerIngestionStreamFactory:
    return BrokerIngestionStreamFactory(**kwargs)


register_source_factory("broker", _broker_factory)
register_source_factory("kafka", _broker_factory)  # capability alias
