"""Ingestion stream abstraction: per-shard streams of record containers.

Capability match for the reference's IngestionStream/Factory (reference:
coordinator/src/main/scala/filodb.coordinator/IngestionStream.scala:14,43
— one stream per shard, messages are RecordContainer bytes; Kafka binds a
shard to one topic partition, KafkaIngestionStream.scala:24-63).  The
factory is resolved by name from the ingestion config's ``sourcefactory``
(reflection in the reference; a registry here).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

# A stream element is (offset, container_bytes) — offsets are the
# checkpointable positions (Kafka offsets in the reference).
StreamElement = tuple[int, bytes]


class IngestionStream:
    """One shard's container stream."""

    def get(self) -> Iterator[StreamElement]:
        raise NotImplementedError

    def teardown(self) -> None:
        pass


class IngestionStreamFactory:
    def create(self, dataset: str, shard: int,
               offset: Optional[int] = None) -> IngestionStream:
        """``offset``: resume position — elements below it may be skipped
        by the source (recovery replays handle the rest via watermarks)."""
        raise NotImplementedError


class ListStream(IngestionStream):
    """Deterministic in-memory stream (tests / CSV-style sources)."""

    def __init__(self, elements: Iterable[StreamElement],
                 start_offset: Optional[int] = None):
        self._elements = list(elements)
        self._start = start_offset

    def get(self) -> Iterator[StreamElement]:
        for off, c in self._elements:
            if self._start is None or off >= self._start:
                yield off, c


class ListStreamFactory(IngestionStreamFactory):
    """shard -> predefined element list (reference: CsvStream used by
    multi-jvm recovery specs for deterministic streams)."""

    def __init__(self, by_shard: dict[int, list[StreamElement]]):
        self.by_shard = by_shard

    def create(self, dataset, shard, offset=None) -> IngestionStream:
        return ListStream(self.by_shard.get(shard, []), offset)


class QueueStream(IngestionStream):
    """Live push stream: producers enqueue, the ingestion loop drains.
    The in-process stand-in for one Kafka topic partition.  ``close()``
    wakes the current consumer (one sentinel ends one ``get`` iterator);
    pushes keep working across consumer generations, like a Kafka
    partition outliving any one consumer."""

    _SENTINEL = (None, None)

    def __init__(self, maxsize: int = 0, start_offset: int = 0):
        # unbounded by default: push must never block while holding the
        # offset lock (a bounded queue + stopped consumer would deadlock
        # ensure_offset/other producers against a blocked put)
        self._q: queue.Queue = queue.Queue(maxsize)
        self._next_offset = start_offset
        self._lock = threading.Lock()
        self._close_pending = False

    def push(self, container: bytes) -> int:
        # assign AND enqueue under the lock: out-of-order offsets would turn
        # into silent data loss at the checkpoint/watermark layer
        with self._lock:
            off = self._next_offset
            self._next_offset += 1
            self._q.put((off, container))
        return off

    def ensure_offset(self, offset: int) -> None:
        """Fast-forward numbering so post-restart pushes land above the
        recovery checkpoints (a real Kafka partition's offsets are durable;
        an in-process queue's must be bumped explicitly)."""
        with self._lock:
            self._next_offset = max(self._next_offset, offset)

    def end_offset(self) -> int:
        """The next offset to be assigned — the broker ``end_offset``
        analog the watermark ledger reads for lag (ISSUE 6)."""
        with self._lock:
            return self._next_offset

    def close(self) -> None:
        """Wake the current consumer.  Idempotent until delivered: closing
        twice before a consumer sees the sentinel enqueues it once, so a
        restarted consumer never dies on a stale sentinel."""
        with self._lock:
            if self._close_pending:
                return
            self._close_pending = True
            self._q.put(self._SENTINEL)

    def get(self) -> Iterator[StreamElement]:
        while True:
            item = self._q.get()
            if item == self._SENTINEL:
                with self._lock:
                    self._close_pending = False
                return
            yield item

    def teardown(self) -> None:
        self.close()


class QueueStreamFactory(IngestionStreamFactory):
    """Lazily creates one QueueStream per (dataset, shard); producers fetch
    the same stream by key to push into it."""

    def __init__(self) -> None:
        self._streams: dict[tuple[str, int], QueueStream] = {}
        self._lock = threading.Lock()

    def stream_for(self, dataset: str, shard: int) -> QueueStream:
        with self._lock:
            key = (dataset, shard)
            st = self._streams.get(key)
            if st is None:
                st = self._streams[key] = QueueStream()
            return st

    def create(self, dataset, shard, offset=None) -> IngestionStream:
        st = self.stream_for(dataset, shard)
        if offset is not None:
            st.ensure_offset(offset)
        return st


_FACTORIES: dict[str, Callable[..., IngestionStreamFactory]] = {}


def register_source_factory(name: str,
                            ctor: Callable[..., IngestionStreamFactory]) -> None:
    """Registry keyed like the reference's ``sourcefactory`` class names."""
    _FACTORIES[name] = ctor


def source_factory(name: str, **kwargs) -> IngestionStreamFactory:
    if name not in _FACTORIES:
        raise ValueError(f"unknown sourcefactory {name!r}; "
                         f"known: {sorted(_FACTORIES)}")
    return _FACTORIES[name](**kwargs)


register_source_factory("queue", QueueStreamFactory)
