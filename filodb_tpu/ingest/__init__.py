"""Ingest edge: stream abstraction + sources (reference: kafka/, gateway/)."""

from filodb_tpu.ingest.stream import (  # noqa: F401
    IngestionStream, IngestionStreamFactory, ListStream, ListStreamFactory,
    QueueStream, QueueStreamFactory, register_source_factory, source_factory)
