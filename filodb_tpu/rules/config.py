"""Rule-file model + promtool-style offline validation.

Capability match for Prometheus rule files (prometheus/docs/
configuration/recording_rules.md) in the repo's JSON config dialect::

    {
      "groups": [{
        "name": "node-health",
        "interval": "15s",              # evaluation cadence
        "dataset": "_system",           # dataset the exprs query (and
                                        # recorded series write back to)
        "rules": [
          {"record": "node:ingest_lag:max",
           "expr": "max(filodb_ingest_lag_rows)",
           "labels": {"source": "rules"}},
          {"alert": "FiloIngestStalled",
           "expr": "increase(filodb_ingest_stalls_total[2m]) > 0",
           "for": "30s",
           "labels": {"severity": "page"},
           "annotations": {"summary": "shard stalled ({{ $value }})"}}
        ]
      }]
    }

``validate_rule_config`` is the promtool ``check rules`` analog the
``rules-check`` CLI verb runs: every expr goes through the real PromQL
parser, group/rule names must be unique, ``for:``/``interval`` must be
valid durations, and unknown fields are errors (a typo'd ``fro:`` must
not silently disable an alert hold).  Exprs are additionally rendered
through :func:`logical_plan_to_promql` when possible — the canonical
form ``/api/v1/rules`` exposes, protected by the generative round-trip
sweep (tests/test_promql_roundtrip_gen.py).
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Optional

from filodb_tpu.promql.parser import ParseError, duration_ms, parse_query

# any fixed range works for validation parses: exprs are re-anchored at
# every evaluation timestamp
_VALIDATE_BASE_MS = 1_700_000_000_000
_VALIDATE_STEP_MS = 15_000

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

_GROUP_FIELDS = {"name", "interval", "dataset", "timeout", "rules"}
_RULE_FIELDS = {"record", "alert", "expr", "for", "labels", "annotations"}


class RuleConfigError(ValueError):
    """The rule config failed validation; ``errors`` lists every
    problem (promtool reports all findings, not just the first)."""

    def __init__(self, errors: list):
        super().__init__("; ".join(errors))
        self.errors = list(errors)


@dataclasses.dataclass
class RuleDef:
    """One recording or alerting rule."""

    name: str
    expr: str
    kind: str                       # "recording" | "alerting"
    labels: dict = dataclasses.field(default_factory=dict)
    annotations: dict = dataclasses.field(default_factory=dict)
    for_ms: int = 0                 # alerting only: pending hold
    rendered: str = ""              # canonical renderer form (API view)


@dataclasses.dataclass
class RuleGroup:
    """A named group: one evaluation cadence, rules run in order."""

    name: str
    interval_ms: int
    rules: list
    dataset: str = ""               # "" = the engine's default dataset
    timeout_ms: int = 0             # 0 = min(interval, 30s)
    source: str = ""                # file/origin, for the API view


def _duration(value, field: str, errors: list, where: str) -> int:
    """Accept PromQL duration strings ("30s", "1h30m") or bare numbers
    (seconds); collect an error and return 0 on anything else."""
    try:
        if isinstance(value, bool):
            raise ValueError(value)
        if isinstance(value, (int, float)):
            if value < 0:
                raise ValueError(value)
            return int(value * 1000)
        return duration_ms(str(value))
    except (ParseError, ValueError, TypeError):
        errors.append(f"{where}: bad {field} duration {value!r}")
        return 0


def _render(expr: str) -> str:
    """Canonical renderer form, falling back to the source text for
    parseable-but-unrenderable constructs (the API must still show
    SOMETHING; the round-trip sweep keeps the renderable set honest)."""
    from filodb_tpu.coordinator.planners import logical_plan_to_promql
    try:
        plan = parse_query(expr, _VALIDATE_BASE_MS, _VALIDATE_STEP_MS,
                           _VALIDATE_BASE_MS)
        return logical_plan_to_promql(plan)
    except (ParseError, ValueError):
        return expr


def _parse_rule(raw: dict, where: str, errors: list,
                seen_names: set) -> Optional[RuleDef]:
    if not isinstance(raw, dict):
        errors.append(f"{where}: rule must be an object, got "
                      f"{type(raw).__name__}")
        return None
    unknown = set(raw) - _RULE_FIELDS
    if unknown:
        errors.append(f"{where}: unknown field(s) {sorted(unknown)}")
    has_record = "record" in raw
    has_alert = "alert" in raw
    if has_record == has_alert:
        errors.append(f"{where}: exactly one of 'record'/'alert' required")
        return None
    raw_name = raw.get("record") if has_record else raw.get("alert")
    kind = "recording" if has_record else "alerting"
    if not isinstance(raw_name, str):
        # str(None) would mint a rule literally named "None" that
        # passes the metric-name regex — a typo'd `"record": null`
        # must fail, not record a series called None
        errors.append(f"{where}: '{'record' if has_record else 'alert'}'"
                      f" must be a string, got {type(raw_name).__name__}")
        return None
    name = raw_name
    if has_record and not _METRIC_NAME_RE.match(name):
        errors.append(f"{where}: invalid recorded metric name {name!r}")
    if has_alert and not name:
        errors.append(f"{where}: empty alert name")
    if (kind, name) in seen_names:
        errors.append(f"{where}: duplicate {kind} rule name {name!r} "
                      f"in this group")
    seen_names.add((kind, name))
    expr = raw.get("expr")
    if not isinstance(expr, str) or not expr.strip():
        errors.append(f"{where}: missing expr")
        expr = ""
    else:
        try:
            parse_query(expr, _VALIDATE_BASE_MS, _VALIDATE_STEP_MS,
                        _VALIDATE_BASE_MS)
        except ParseError as e:
            errors.append(f"{where}: expr does not parse: {e}")
    for_ms = 0
    if "for" in raw:
        if has_record:
            errors.append(f"{where}: 'for' is only valid on alerting rules")
        else:
            for_ms = _duration(raw["for"], "for", errors, where)
    if has_record and raw.get("annotations"):
        errors.append(f"{where}: 'annotations' is only valid on alerting "
                      f"rules")
    labels = raw.get("labels") or {}
    annotations = raw.get("annotations") or {}
    for field, mapping in (("labels", labels), ("annotations", annotations)):
        if not isinstance(mapping, dict):
            errors.append(f"{where}: {field} must be an object")
            mapping = {}
        for k in mapping:
            if not _LABEL_NAME_RE.match(str(k)):
                errors.append(f"{where}: invalid {field} name {k!r}")
    return RuleDef(name=name, expr=expr, kind=kind,
                   labels={str(k): str(v) for k, v in dict(labels).items()},
                   annotations={str(k): str(v)
                                for k, v in dict(annotations).items()},
                   for_ms=for_ms, rendered=_render(expr) if expr else "")


def parse_rule_config(config: dict,
                      source: str = "") -> tuple[list, list]:
    """Parse a rule config dict -> ``(groups, errors)``.  Every problem
    is collected (not fail-fast); callers that need hard failure use
    :func:`load_rule_config`."""
    errors: list[str] = []
    groups: list[RuleGroup] = []
    if not isinstance(config, dict):
        return [], [f"{source or 'config'}: rule config must be an object"]
    unknown = set(config) - {"groups"}
    if unknown:
        errors.append(f"{source or 'config'}: unknown top-level field(s) "
                      f"{sorted(unknown)}")
    raw_groups = config.get("groups")
    if not isinstance(raw_groups, list):
        errors.append(f"{source or 'config'}: 'groups' must be a list")
        raw_groups = []
    seen_groups: set[str] = set()
    for gi, raw in enumerate(raw_groups):
        gwhere = f"{source + ': ' if source else ''}groups[{gi}]"
        if not isinstance(raw, dict):
            errors.append(f"{gwhere}: group must be an object")
            continue
        unknown = set(raw) - _GROUP_FIELDS
        if unknown:
            errors.append(f"{gwhere}: unknown field(s) {sorted(unknown)}")
        name = str(raw.get("name") or "")
        if not name:
            errors.append(f"{gwhere}: missing group name")
        if name in seen_groups:
            errors.append(f"{gwhere}: duplicate group name {name!r}")
        seen_groups.add(name)
        interval_ms = _duration(raw.get("interval", "1m"), "interval",
                                errors, gwhere)
        if interval_ms <= 0:
            errors.append(f"{gwhere}: interval must be > 0")
        timeout_ms = 0
        if "timeout" in raw:
            timeout_ms = _duration(raw["timeout"], "timeout", errors,
                                   gwhere)
        raw_rules = raw.get("rules")
        if not isinstance(raw_rules, list) or not raw_rules:
            errors.append(f"{gwhere}: 'rules' must be a non-empty list")
            raw_rules = []
        rules: list[RuleDef] = []
        seen_names: set = set()
        for ri, rr in enumerate(raw_rules):
            r = _parse_rule(rr, f"{gwhere}.rules[{ri}]", errors, seen_names)
            if r is not None:
                rules.append(r)
        groups.append(RuleGroup(name=name, interval_ms=max(interval_ms, 1),
                                rules=rules,
                                dataset=str(raw.get("dataset") or ""),
                                timeout_ms=timeout_ms, source=source))
    return groups, errors


def validate_rule_config(config: dict, source: str = "") -> list:
    """Errors only (the ``rules-check`` CLI verb)."""
    _groups, errors = parse_rule_config(config, source)
    return errors


def load_rule_config(config: dict, source: str = "") -> list:
    """Parse or raise :class:`RuleConfigError` — the standalone server's
    loading path: a node must refuse to start on a broken rule file
    rather than silently run a subset."""
    groups, errors = parse_rule_config(config, source)
    if errors:
        raise RuleConfigError(errors)
    return groups


def load_rule_file(path: str) -> list:
    """Load + validate one JSON rule file."""
    with open(path) as f:
        try:
            config = json.load(f)
        except json.JSONDecodeError as e:
            raise RuleConfigError([f"{path}: not valid JSON: {e}"]) from e
    return load_rule_config(config, source=path)
