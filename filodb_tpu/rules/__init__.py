"""Continuous rule evaluation: recording rules, alerting rules, and the
self-monitoring pack (ROADMAP item 3a, doc/rules.md).

- :mod:`filodb_tpu.rules.config` — rule-file model + promtool-style
  offline validation (the ``rules-check`` CLI verb);
- :mod:`filodb_tpu.rules.incremental` — per-rule window state that
  consumes only newly-arrived samples yet stays bit-equal to a cold
  full-range evaluation;
- :mod:`filodb_tpu.rules.engine` — group scheduling, the alert state
  machine, write-back through the gateway publisher, and the
  ``/api/v1/rules`` / ``/api/v1/alerts`` / ``/admin/rules`` payloads;
- :mod:`filodb_tpu.rules.notifier` — webhook delivery with bounded
  retry/backoff;
- :mod:`filodb_tpu.rules.selfmon` — the shipped self-monitoring rule
  pack over the ``_system`` dataset.
"""

from filodb_tpu.rules.config import (RuleConfigError, RuleDef, RuleGroup,
                                     parse_rule_config, validate_rule_config)
from filodb_tpu.rules.engine import RuleEngine

__all__ = ["RuleConfigError", "RuleDef", "RuleGroup", "RuleEngine",
           "parse_rule_config", "validate_rule_config"]
