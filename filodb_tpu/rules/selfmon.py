"""The shipped self-monitoring rule pack over the ``_system`` dataset.

PR 11's self-scrape turned the node's own ``/metrics`` exposition into
a queryable Prometheus-schema dataset; this pack turns that inert
telemetry into the node's own alerting substrate.  Loaded by the
standalone server whenever self-scrape is enabled (``rules.
self-monitoring`` config block opts out / tunes cadence), validated by
``rules-check --builtin`` in tier-1.

Every expr reads the ``filodb_*`` families the self-scraper publishes
(doc/observability.md) — the alerts cover the four operational
failure classes PRs 6-12 made visible but nothing acted on:

- **ingest stalls** — a lagging shard whose ingested offset stopped
  moving (`filodb_ingest_stalls_total`, watermark ledger);
- **recompile storms** — a program minting distinct XLA shapes fast
  enough to wedge serving (`filodb_jit_recompile_storms_total`);
- **replica publish failures** — the dual-write fanout dropping a
  peer's containers (`filodb_ingest_replica_publish_failures_total`);
- **integrity quarantines** — corrupt chunks excluded from serving
  (`filodb_integrity_quarantined_chunks`);
- **rollup lag / stalled tiers** (ISSUE 11) — a resolution tier whose
  emission stopped advancing (`filodb_rollup_stalled`, a LEVEL gauge
  for the same reason as `filodb_ingest_stalled`: a counter's label
  set is born at 1 and never shows an `increase()` edge) or whose lag
  behind the raw flush watermark grew past the threshold
  (`filodb_rollup_lag_seconds`) — stale tiers silently serve stale
  long-range dashboards;
- **kernel regressions** (ISSUE 15) — a serving program's sampled
  EWMA device time sustained above its learned baseline
  (`filodb_kernel_regressed`, a LEVEL gauge for the same
  counters-born-at-1 reason) — a half-tripped breaker, shape churn, or
  a bad pack stride silently degrading the roofline position every
  query pays for (see `/admin/kernels`).
"""

from __future__ import annotations

GROUP_NAME = "filodb-self-monitoring"


def selfmon_pack(interval: str = "15s", for_: str = "30s",
                 dataset: str = "_system", window: str = "2m",
                 rollup_lag_s: int = 7200) -> dict:
    """The pack as a rule config dict (``parse_rule_config`` input).
    ``interval``/``for_``/``window`` are tunable so fast test cadences
    and production defaults share one definition; ``rollup_lag_s`` is
    the lag threshold the FiloRollupLagging alert pages on (default:
    two hours — two 1h periods behind)."""
    return {"groups": [{
        "name": GROUP_NAME,
        "interval": interval,
        "dataset": dataset,
        "rules": [
            # recorded convenience series dashboards read directly
            {"record": "node:ingest_lag_rows:sum",
             "expr": "sum(filodb_ingest_lag_rows)",
             "labels": {"source": "selfmon"}},
            {"record": "node:selfscrape_samples:rate1m",
             "expr": "rate(filodb_selfscrape_samples_total[1m])",
             "labels": {"source": "selfmon"}},
            {"alert": "FiloIngestStalled",
             # the LEVEL gauge, not increase(stalls_total): the
             # counter's label set is born at 1 (first episode creates
             # it), so a scrape of it never shows the 0->1 edge
             "expr": "filodb_ingest_stalled > 0",
             "for": for_,
             "labels": {"severity": "page", "source": "selfmon"},
             "annotations": {
                 "summary": "ingest stalled on dataset "
                            "{{ $labels.dataset }} shard "
                            "{{ $labels.shard }}",
                 "description": "a lagging shard's ingested offset made "
                                "no progress for the stall window "
                                "({{ $value }} episodes)"}},
            {"alert": "FiloRecompileStorm",
             "expr": "increase("
                     f"filodb_jit_recompile_storms_total[{window}]) > 0",
             "for": for_,
             "labels": {"severity": "warn", "source": "selfmon"},
             "annotations": {
                 "summary": "recompile storm on program "
                            "{{ $labels.program }}",
                 "description": "a program compiled enough distinct "
                                "shapes to wedge serving; check "
                                "/admin/device"}},
            {"alert": "FiloKernelRegression",
             # the LEVEL gauge (the filodb_ingest_stalled lesson):
             # the regressions_total counter's label set is born at 1
             "expr": "filodb_kernel_regressed > 0",
             "for": for_,
             "labels": {"severity": "page", "source": "selfmon"},
             "annotations": {
                 "summary": "kernel {{ $labels.program }} regressed "
                            "vs its learned device-time baseline",
                 "description": "the program's sampled EWMA device "
                                "time is sustained above the learned "
                                "baseline; check /admin/kernels for "
                                "the live roofline position and "
                                "/admin/device for recompile storms "
                                "or breaker trips"}},
            {"alert": "FiloReplicaPublishFailing",
             "expr": "increase("
                     "filodb_ingest_replica_publish_failures_total"
                     f"[{window}]) > 0",
             "for": for_,
             "labels": {"severity": "page", "source": "selfmon"},
             "annotations": {
                 "summary": "replica deliveries failing toward "
                            "{{ $labels.node }}",
                 "description": "the dual-write fanout is dropping "
                                "containers ({{ $value }}); the "
                                "replica lags until it recovers"}},
            {"record": "node:rollup_lag_seconds:max",
             "expr": "max(filodb_rollup_lag_seconds)",
             "labels": {"source": "selfmon"}},
            {"alert": "FiloRollupStalled",
             # the LEVEL gauge (the filodb_ingest_stalled lesson):
             # counters born at 1 never show increase() edges
             "expr": "filodb_rollup_stalled > 0",
             "for": for_,
             "labels": {"severity": "page", "source": "selfmon"},
             "annotations": {
                 "summary": "rollup tier {{ $labels.resolution }}ms "
                            "stalled on dataset {{ $labels.dataset }}",
                 "description": "the tier made no emission progress "
                                "past the stall window; long-range "
                                "queries serve stale rolled data "
                                "(see /admin/rollup)"}},
            {"alert": "FiloRollupLagging",
             "expr": f"max(filodb_rollup_lag_seconds) > {rollup_lag_s}",
             "for": for_,
             "labels": {"severity": "warn", "source": "selfmon"},
             "annotations": {
                 "summary": "rollup lag {{ $value }}s behind the "
                            "flush watermark",
                 "description": "a resolution tier is falling behind "
                                "raw ingest; check admission "
                                "deferrals and tier errors in "
                                "/admin/rollup"}},
            {"alert": "FiloChunksQuarantined",
             "expr": "filodb_integrity_quarantined_chunks > 0",
             "for": for_,
             "labels": {"severity": "warn", "source": "selfmon"},
             "annotations": {
                 "summary": "{{ $value }} corrupt chunks quarantined",
                 "description": "queries over the affected series are "
                                "partial; see /admin/integrity"}},
        ],
    }]}


SLO_GROUP_NAME = "filodb-slo-burn"


def slo_pack(interval: str = "15s", for_: str = "30s",
             dataset: str = "_system", fast_burn: float = 14.4,
             slow_burn: float = 6.0) -> dict:
    """Tenant SLO burn-rate alerts (ISSUE 19) over the ``filodb_slo_*``
    families the SLO tracker exports — the standard multi-window
    multi-burn-rate policy: the FAST window pages (budget gone in
    hours), the SLOW window warns (budget gone in days).  Both exprs
    read LEVEL gauges the tracker registers up-front (the
    filodb_ingest_stalled lesson: rules must see the 0 -> burning
    edge, which a counter label set born at 1 never shows)."""
    return {"groups": [{
        "name": SLO_GROUP_NAME,
        "interval": interval,
        "dataset": dataset,
        "rules": [
            {"alert": "FiloTenantSLOFastBurn",
             "expr": f"filodb_slo_fast_burn > {fast_burn}",
             "for": for_,
             "labels": {"severity": "page", "source": "selfmon"},
             "annotations": {
                 "summary": "SLO {{ $labels.objective }} fast-burning "
                            "for tenant {{ $labels.tenant }}",
                 "description": "error budget burning at {{ $value }}x "
                                "over the fast window — at this rate "
                                "the whole budget is gone within "
                                "hours; see /admin/insights"}},
            {"alert": "FiloTenantSLOSlowBurn",
             "expr": f"filodb_slo_slow_burn > {slow_burn}",
             "for": for_,
             "labels": {"severity": "warn", "source": "selfmon"},
             "annotations": {
                 "summary": "SLO {{ $labels.objective }} slow-burning "
                            "for tenant {{ $labels.tenant }}",
                 "description": "sustained burn at {{ $value }}x over "
                                "the slow window eats the budget in "
                                "days; see /admin/insights"}},
        ],
    }]}
