"""Webhook alert notifier: bounded queue, bounded retry, flight-recorded.

Capability match for Prometheus' notifier (prometheus/notifier/
notifier.go — a queue drained by a sender with capacity shedding),
scoped to one webhook endpoint.  Transitions enqueue an
Alertmanager-shaped payload; a daemon worker POSTs each with bounded
retry + exponential backoff.  A wedged receiver fills the queue and
further sends are DROPPED (counted, flight-recorded) — alert delivery
must never stall rule evaluation, the same isolation discipline as the
replica delivery lanes (gateway/server.py).
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.request
from typing import Callable, Optional

_STOP = object()


def _metrics():
    from filodb_tpu.utils.observability import rule_metrics
    return rule_metrics()


class WebhookNotifier:
    """POSTs alert transition payloads to one webhook URL.

    ``send_fn`` overrides the HTTP POST for tests (called with the
    JSON-encoded body; raising marks the attempt failed).
    """

    def __init__(self, url: str, timeout_s: float = 5.0, retries: int = 3,
                 backoff_s: float = 0.25, max_queued: int = 256,
                 send_fn: Optional[Callable[[bytes], None]] = None):
        self.url = url
        self.timeout_s = float(timeout_s)
        self.retries = max(int(retries), 0)
        self.backoff_s = float(backoff_s)
        self.send_fn = send_fn
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queued)
        self._m = _metrics()
        self._stopped = False
        self._thread = threading.Thread(target=self._run,
                                        name="rule-notifier", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- enqueue

    def notify(self, payload: dict) -> bool:
        """Queue one transition for delivery; False = dropped (full)."""
        try:
            self._q.put_nowait(payload)
            return True
        except queue.Full:
            self._m["notifications"].inc(outcome="dropped")
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("rules.notify_dropped",
                          alertname=payload.get("labels", {})
                          .get("alertname", ""),
                          status=payload.get("status", ""))
            return False

    # -------------------------------------------------------------- worker

    def _post(self, body: bytes) -> None:
        if self.send_fn is not None:
            self.send_fn(body)
            return
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s):
            pass

    def _run(self) -> None:
        from filodb_tpu.utils.devicewatch import FLIGHT
        while True:
            try:
                item = self._q.get(timeout=0.25)
            except queue.Empty:
                if self._stopped:
                    return
                continue
            if item is _STOP:
                self._q.task_done()
                return
            body = json.dumps([item]).encode()
            alertname = item.get("labels", {}).get("alertname", "")
            err = ""
            attempts = 0
            delivered = False
            for attempt in range(self.retries + 1):
                attempts = attempt + 1
                try:
                    self._post(body)
                    delivered = True
                    break
                except Exception as e:  # noqa: BLE001 — retry, then give up
                    err = str(e)
                    if attempt < self.retries:
                        self._m["notify_retries"].inc()
                        time.sleep(self.backoff_s * (2 ** attempt))
            self._m["notifications"].inc(
                outcome="delivered" if delivered else "failed")
            # every send is flight-recorded: alert delivery is exactly
            # the traffic an operator replays after an incident
            FLIGHT.record("rules.notify", alertname=alertname,
                          status=item.get("status", ""),
                          outcome="delivered" if delivered else "failed",
                          attempts=attempts,
                          **({"error": err[:200]} if err else {}))
            self._q.task_done()

    # ----------------------------------------------------------- lifecycle

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Best-effort wait until the queue empties (tests/shutdown)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.unfinished_tasks == 0:
                return True
            time.sleep(0.005)
        return False

    def queue_depth(self) -> int:
        return self._q.qsize()

    def close(self) -> None:
        self._stopped = True
        try:
            self._q.put_nowait(_STOP)
        except queue.Full:
            pass  # worker notices _stopped within its poll interval
        self._thread.join(timeout=2.0)
