"""Incremental window state for recording rules over windowed functions.

A recording rule like ``rate(m[5m])`` evaluated every 10s re-reads the
same 5m of raw samples 30 times over the window's lifetime.  This
module keeps the window RESIDENT instead: per input series, raw samples
live in blocks keyed on chunk-aligned time boundaries; each tick
fetches only the slice of raw data that arrived since the previous tick
(``(fetched_through, eval_ts]`` — O(new samples), the constant-state
streaming formulation of arXiv:2603.09555 mapped onto
``rate``/``increase``/``*_over_time`` windows), appends it, evicts
whole blocks that fell out of the window, and recomputes the window
function over the buffered samples.

The load-bearing invariant (asserted generatively in
tests/test_rules.py): the value produced from warm incremental state is
**bit-equal** to a cold full-range evaluation, which in turn is
bit-equal to the normal query path's answer for the same expression at
the same timestamp.  That holds by construction, not by tolerance:

- the raw fetch goes through the SAME planner -> leaf-scan path a
  full query uses, so sample sets agree;
- the buffered rows presented to the kernel are exactly the rows a
  direct query's ``read_range(t - window, t)`` clamp would return
  (inclusive both ends; the kernel itself applies the Prometheus
  ``(t - window, t]`` exclusivity);
- the window value comes from the very same
  :func:`filodb_tpu.query.rangefns.apply_range_function` kernel the
  query path dispatches — not a host reimplementation that would drift
  in float association.

Late-arriving samples (timestamp at or below an already-consumed slice
boundary) are invisible to warm state until :meth:`WindowState.reset`;
doc/rules.md documents the invariant.  The engine resets state whenever
an evaluation fails, so a transient fetch error cannot leave a silent
gap in the window.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

from filodb_tpu.core.chunk import build_batch
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query import logical as lp
from filodb_tpu.query.rangefns import apply_range_function, supported

# row padding for the buffered batches: the same default the shard
# store config uses, so incremental and cold batches land in the same
# jit shape buckets (values are padding-independent either way)
_ROW_PAD = 64


@dataclasses.dataclass
class WindowSpec:
    """The recognized incremental shape: ``fn(selector[w])``."""

    filters: tuple
    window_ms: int
    function: object                # RangeFunctionId
    args: tuple = ()


def window_spec(plan) -> Optional[WindowSpec]:
    """Return the :class:`WindowSpec` when ``plan`` is a bare windowed
    range function the incremental path supports; ``None`` falls back
    to full evaluation (aggregations, joins, offsets, histograms...).

    ``offset`` is excluded on purpose: an offset window reads the past,
    where "newly-arrived samples" no longer describes the delta.
    """
    if not isinstance(plan, lp.PeriodicSeriesWithWindowing):
        return None
    if plan.offset_ms:
        return None
    if not isinstance(plan.series, lp.RawSeries) or plan.series.columns:
        return None
    if not supported(plan.function, hist=False):
        return None
    return WindowSpec(tuple(plan.series.filters), int(plan.window_ms),
                      plan.function, tuple(plan.function_args))


class _SeriesBuffer:
    """One input series' resident window: samples grouped into blocks
    keyed on chunk-aligned boundaries (``ts // block_ms``), so eviction
    drops whole immutable blocks instead of scanning sample-by-sample."""

    __slots__ = ("tags", "blocks", "last_ts")

    def __init__(self, tags: dict):
        self.tags = tags
        self.blocks: dict[int, list] = {}   # block idx -> [(ts, val)...]
        self.last_ts = -(1 << 62)           # newest buffered timestamp

    def append(self, ts: np.ndarray, vals: np.ndarray,
               block_ms: int) -> None:
        for t, v in zip(ts.tolist(), vals.tolist()):
            self.blocks.setdefault(int(t) // block_ms, []).append(
                (int(t), float(v)))
        if len(ts):
            self.last_ts = max(self.last_ts, int(ts[-1]))

    def evict_before(self, cutoff_ms: int, block_ms: int) -> None:
        """Drop blocks wholly below ``cutoff_ms`` (a block containing
        the cutoff stays; compute-time clamping handles its head)."""
        dead = [b for b in self.blocks if (b + 1) * block_ms <= cutoff_ms]
        for b in dead:
            del self.blocks[b]

    def window_rows(self, start_ms: int,
                    end_ms: int) -> tuple[np.ndarray, np.ndarray]:
        """Samples with ``start <= ts <= end`` in timestamp order — the
        same inclusive clamp a leaf scan's ``read_range`` applies."""
        ts_out: list[int] = []
        val_out: list[float] = []
        for b in sorted(self.blocks):
            for t, v in self.blocks[b]:
                if start_ms <= t <= end_ms:
                    ts_out.append(t)
                    val_out.append(v)
        return (np.asarray(ts_out, dtype=np.int64),
                np.asarray(val_out, dtype=np.float64))

    @property
    def sample_count(self) -> int:
        return sum(len(rows) for rows in self.blocks.values())


class WindowState:
    """Incremental evaluator for one recording rule.

    ``fetch`` is the engine's raw-series reader — it issues a
    ``RawSeries`` plan through the normal planner -> admission ->
    scheduler path and returns ``[(tags, ts, vals)]`` clamped to the
    requested interval.
    """

    def __init__(self, spec: WindowSpec, block_ms: Optional[int] = None):
        self.spec = spec
        # chunk-aligned block boundary: the window itself (>= 1s), so a
        # live window spans at most 2 resident blocks + the open one
        self.block_ms = int(block_ms or max(spec.window_ms, 1000))
        self.fetched_through_ms: Optional[int] = None
        self.series: dict[tuple, _SeriesBuffer] = {}
        self.samples_consumed = 0      # lifetime, for telemetry

    # --------------------------------------------------------------- state

    def reset(self) -> None:
        """Forget everything: the next tick re-reads the full window
        (cold).  Called by the engine after any failed evaluation so a
        missed slice cannot leave a silent hole in the window."""
        self.fetched_through_ms = None
        self.series.clear()

    @property
    def resident_series(self) -> int:
        return len(self.series)

    @property
    def resident_samples(self) -> int:
        return sum(b.sample_count for b in self.series.values())

    # ---------------------------------------------------------------- tick

    def tick(self, eval_ms: int,
             fetch: Callable[[tuple, int, int], list]
             ) -> list[tuple[dict, float]]:
        """Consume newly-arrived samples and produce ``[(tags, value)]``
        for every series with a non-NaN window value at ``eval_ms``."""
        window_start = eval_ms - self.spec.window_ms
        warm = self.fetched_through_ms is not None \
            and self.fetched_through_ms <= eval_ms
        fetch_from = self.fetched_through_ms if warm else window_start
        new = 0
        for tags, ts, vals in fetch(self.spec.filters, fetch_from, eval_ms):
            key = tuple(sorted(tags.items()))
            buf = self.series.get(key)
            if buf is not None:
                # dedupe against THIS series' newest buffered row, not
                # the global fetch boundary: a sample stamped exactly at
                # the boundary but ingested after the boundary fetch ran
                # would otherwise vanish from warm state (and break the
                # bit-equality invariant vs a cold pass)
                keep = ts > buf.last_ts
            else:
                keep = ts >= (fetch_from if warm else window_start)
            ts, vals = ts[keep], vals[keep]
            if not len(ts):
                continue
            if buf is None:
                buf = self.series[key] = _SeriesBuffer(dict(tags))
            buf.append(ts, vals, self.block_ms)
            new += len(ts)
        self.samples_consumed += new
        self.fetched_through_ms = eval_ms
        # evict aged blocks; a series whose whole window emptied is
        # dropped outright — the stale-series discipline (doc/rules.md):
        # state for a vanished series must not survive it
        for key in list(self.series):
            buf = self.series[key]
            buf.evict_before(window_start, self.block_ms)
            if not buf.blocks:
                del self.series[key]
        if not self.series:
            return []
        keys, ts_list, val_list = [], [], []
        for buf in self.series.values():
            ts, vals = buf.window_rows(window_start, eval_ms)
            if not len(ts):
                continue
            keys.append(buf.tags)
            ts_list.append(ts)
            val_list.append(vals)
        if not keys:
            return []
        batch = build_batch(ts_list, val_list, pad_to=_ROW_PAD)
        values = np.asarray(apply_range_function(
            batch, StepRange(eval_ms, eval_ms, 1000),
            self.spec.window_ms, self.spec.function, self.spec.args))
        out = []
        for i, tags in enumerate(keys):
            v = float(values[i, 0])
            if not np.isnan(v):
                out.append((tags, v))
        return out
