"""Incremental window state for recording rules — now a shim.

The window-state core this module introduced (PR 14) moved to
:mod:`filodb_tpu.query.windowstate` so the query-frontend result cache
(``filodb_tpu/query/resultcache``) and the rule engine share ONE
implementation of the constant-state streaming formulation
(arXiv:2603.09555), including the new aggregation-over-window shapes
(``sum by (le)(rate(...))``) via :class:`AggWindowState`.  Everything
documented here before — the bit-equality invariant, the late-arrival
semantics, the reset-on-failure discipline — lives there now; this
module re-exports the public names so existing imports keep working.
"""

from filodb_tpu.query.windowstate import (  # noqa: F401
    _ROW_PAD, _SeriesBuffer, AggWindowSpec, AggWindowState,
    WindowSpec, WindowState, WindowUnsupported, agg_window_spec,
    window_spec,
)

__all__ = [
    "AggWindowSpec", "AggWindowState", "WindowSpec", "WindowState",
    "WindowUnsupported", "agg_window_spec", "window_spec",
]
