"""The rule engine: continuous recording & alerting rule evaluation.

Capability match for Prometheus' rules manager (prometheus/rules/
manager.go — groups on independent tickers, recording rules appending
to storage, alerting rules running the inactive -> pending -> firing ->
resolved state machine with ``ALERTS``/``ALERTS_FOR_STATE`` synthetic
series) built on this repo's serving fabric:

- every evaluation goes through the NORMAL query path — planner ->
  admission -> scheduler — under the dedicated low-priority ``rules``
  workload class with a per-evaluation deadline, so a pathological
  rule group saturates at its admission share and can never starve
  user traffic (workload/admission.py);
- recorded series write back through the dataset's existing
  ``ShardingPublisher``, so they are sharded, replicated (PR 12), and
  queryable like any ingested series;
- recording rules over windowed functions keep incremental window
  state (:mod:`filodb_tpu.query.windowstate`, shared with the query
  result cache) — each tick consumes only newly-arrived samples,
  bit-equal to a cold full-range pass.  Both bare ``fn(sel[w])`` and
  moment aggregations ``agg by (..)(fn(sel[w]))`` are incremental; the
  aggregated shape merges per-shard partials through the normal
  ``AggPartialBatch`` reduce;
- the engine is itself observable: ``filodb_rule_*`` metrics, a span
  tree per group pass, flight events on firing/resolve, and the
  ``/api/v1/rules`` / ``/api/v1/alerts`` / ``/admin/rules`` payloads.
"""

from __future__ import annotations

import contextlib
import dataclasses
import datetime
import re
import threading
import time
from typing import Optional

import numpy as np

from filodb_tpu.promql.parser import query_to_logical_plan
from filodb_tpu.query.logical import IntervalSelector, RawSeries
from filodb_tpu.query.model import (PeriodicBatch, QueryContext,
                                    QueryError)
from filodb_tpu.rules.config import RuleDef, RuleGroup
from filodb_tpu.rules.incremental import (AggWindowState, WindowState,
                                          WindowUnsupported,
                                          agg_window_spec, window_spec)
from filodb_tpu.utils.observability import (TRACER, PeriodicThread,
                                            rule_metrics)
from filodb_tpu.workload import deadline as wdl

# the engine's admission identity: a dedicated low-priority class (its
# share lives in workload/admission.py DEFAULT_PRIORITY_SHARES) and a
# reserved tenant so rule traffic is attributable in /admin/workload
RULE_PRIORITY = "rules"
RULE_TENANT = "_rules"

# synthetic series names (Prometheus: rules/alerting.go)
ALERTS_METRIC = "ALERTS"
ALERTS_FOR_STATE_METRIC = "ALERTS_FOR_STATE"

_TEMPLATE_RE = re.compile(r"\{\{\s*\$(value|labels\.([a-zA-Z_][\w]*))\s*\}\}")


def _iso(ms: int) -> str:
    return datetime.datetime.fromtimestamp(
        ms / 1000.0, tz=datetime.timezone.utc).isoformat()


def render_template(text: str, labels: dict, value: float) -> str:
    """Minimal Prometheus annotation templating: ``{{ $value }}`` and
    ``{{ $labels.<name> }}``."""
    import math

    def repl(m: "re.Match[str]") -> str:
        if m.group(1) == "value":
            # int() on inf raises — and an alert value CAN be inf
            # (a zero-denominator rate ratio), exactly when the
            # annotation matters most
            if math.isfinite(value) and value == int(value):
                return str(int(value))
            return repr(value)
        return str(labels.get(m.group(2), ""))
    return _TEMPLATE_RE.sub(repl, text)


class RuleEvaluator:
    """Issues one rule expression's queries through the normal serving
    path: planner -> admission (``rules`` priority class, per-eval
    deadline) -> scheduler.  One evaluator per dataset binding."""

    def __init__(self, binding):
        self.binding = binding       # http.server.DatasetBinding shape

    def _qctx(self, timeout_ms: int) -> QueryContext:
        qctx = QueryContext(
            submit_time_ms=int(time.time() * 1000),
            trace_id=TRACER.current_trace_id() or TRACER.new_trace_id(),
            timeout_ms=int(timeout_ms),
            tenant=RULE_TENANT,
            priority=RULE_PRIORITY)
        return wdl.mint(qctx)

    def _admit(self, ep, qctx: QueryContext):
        adm = getattr(self.binding, "admission", None)
        if adm is None or not adm.enabled:
            return contextlib.nullcontext()
        cost = adm.cost_model.estimate(ep, self.binding.memstore)
        return adm.admit(qctx, cost)

    def run_plan(self, plan, timeout_ms: int):
        """Materialize + admit + execute one logical plan; the rule
        engine's only doorway to data."""
        from filodb_tpu.query.exec import ExecContext
        qctx = self._qctx(timeout_ms)
        with TRACER.span("rules.query", dataset=self.binding.dataset):
            ep = self.binding.planner.materialize(plan, qctx)

            def run():
                tok = TRACER.capture()
                if tok[0] is None:
                    tok = (qctx.trace_id, None)
                with TRACER.attach(tok):
                    return ep.execute(
                        ExecContext(self.binding.memstore, qctx))

            with self._admit(ep, qctx):
                if self.binding.scheduler is not None:
                    return self.binding.scheduler.execute(
                        run, qctx.submit_time_ms, qctx.timeout_ms,
                        deadline_ms=qctx.deadline_ms)
                return run()

    def instant_vector(self, expr: str, eval_ms: int,
                       timeout_ms: int) -> list[tuple[dict, float]]:
        """Evaluate ``expr`` at one instant -> ``[(tags, value)]`` (the
        numeric core of ``to_prom_vector``; tags still carry the
        internal metric column)."""
        plan = query_to_logical_plan(expr, eval_ms)
        result = self.run_plan(plan, timeout_ms)
        out: list[tuple[dict, float]] = []
        for b in result.batches:
            if not isinstance(b, PeriodicBatch):
                continue
            for tags, ts, vals in b.to_series():
                fin = np.flatnonzero(~np.isnan(vals) & (ts <= eval_ms))
                if len(fin):
                    out.append((tags, float(vals[fin[-1]])))
        return out

    def raw_series(self, filters: tuple, start_ms: int,
                   end_ms: int, timeout_ms: int) -> list:
        """Raw samples clamped to ``[start, end]`` -> ``[(tags, ts,
        vals)]`` — the incremental window state's delta fetch."""
        return [row for bucket in self.raw_series_sharded(
            filters, start_ms, end_ms, timeout_ms) for row in bucket]

    def raw_series_sharded(self, filters: tuple, start_ms: int,
                           end_ms: int, timeout_ms: int) -> list:
        """Raw samples grouped per shard batch, in the scatter-gather
        child order — the aggregated window state's delta fetch (its
        per-bucket partials must reduce in the same order the query
        path's ReduceAggregateExec would).  The unpack lives in the
        shared window-state module so the result cache's instant path
        can never drift from it."""
        from filodb_tpu.query.windowstate import batches_to_buckets
        plan = RawSeries(IntervalSelector(int(start_ms), int(end_ms)),
                         tuple(filters))
        result = self.run_plan(plan, timeout_ms)
        return batches_to_buckets(result.batches)


@dataclasses.dataclass
class AlertInstance:
    """One active alert (rule x label set)."""

    labels: dict                    # includes alertname + rule labels
    annotations: dict               # templated at activation
    state: str                      # pending | firing | resolved
    active_at_ms: int
    value: float = 0.0
    resolved_at_ms: int = 0

    def payload(self) -> dict:
        return {"labels": dict(self.labels),
                "annotations": dict(self.annotations),
                "state": self.state,
                "activeAt": _iso(self.active_at_ms),
                "value": str(self.value)}


@dataclasses.dataclass
class _RuleState:
    """Per-rule runtime bookkeeping the API views read."""

    rule: RuleDef
    health: str = "unknown"         # ok | err | unknown
    last_error: str = ""
    last_duration_s: float = 0.0
    last_eval_ms: int = 0
    # WindowState | AggWindowState | None (full evaluation)
    incremental: Optional[object] = None
    incr_seen: int = 0              # samples_consumed already counted
    # alerting: key -> AlertInstance (pending/firing, plus resolved
    # instances retained for the API until _RESOLVED_RETENTION_MS)
    alerts: dict = dataclasses.field(default_factory=dict)
    # recording: output series written last tick (stale-series fence)
    out_series: set = dataclasses.field(default_factory=set)


class _GroupState:
    def __init__(self, group: RuleGroup, evaluator: RuleEvaluator,
                 publisher):
        self.group = group
        self.evaluator = evaluator
        self.publisher = publisher
        self.rules = [_RuleState(r) for r in group.rules]
        self.loop: Optional[PeriodicThread] = None
        self.last_start_s: Optional[float] = None
        self.last_duration_s = 0.0
        self.evals = 0
        self.missed = 0
        self.timeout_ms = group.timeout_ms or min(group.interval_ms,
                                                  30_000)


_RESOLVED_RETENTION_MS = 15 * 60_000


class RuleEngine:
    """Owns every rule group: scheduling, evaluation, state, payloads.

    ``binding_for(dataset)`` resolves a dataset to its serving binding
    (planner/memstore/scheduler/admission); ``publisher_for(dataset)``
    to its gateway write publisher.  Groups naming no dataset evaluate
    against ``default_dataset``.
    """

    def __init__(self, groups: list, binding_for, publisher_for,
                 default_dataset: str = "", notifier=None,
                 node: str = "", incremental: bool = True):
        self._m = rule_metrics()
        self.node = node
        self.notifier = notifier
        self.incremental = incremental
        self._lock = threading.Lock()
        # the group LIST is fixed at construction; _lock guards the
        # mutable per-group/per-rule state inside it (alerts, timings)
        self._groups: list[_GroupState] = []
        self._started = False
        for g in groups:
            ds = g.dataset or default_dataset
            binding = binding_for(ds)
            publisher = publisher_for(ds)
            if binding is None:
                raise ValueError(
                    f"rule group {g.name!r} targets unknown dataset "
                    f"{ds!r}")
            g = dataclasses.replace(g, dataset=ds)
            gs = _GroupState(g, RuleEvaluator(binding), publisher)
            if incremental:
                for rs in gs.rules:
                    if rs.rule.kind != "recording":
                        continue
                    rs.incremental = self._window_state(rs.rule)
            self._groups.append(gs)

    @staticmethod
    def _window_state(rule: RuleDef):
        """An incremental window state for the rule's expression shape,
        or None (full evaluation): bare ``fn(sel[w])`` keeps per-series
        state, ``agg by (..)(fn(sel[w]))`` — the shape recorded
        dashboards use most — keeps per-shard aggregation state."""
        from filodb_tpu.promql.parser import ParseError
        try:
            base = 1_700_000_000_000
            plan = query_to_logical_plan(rule.expr, base)
        except (ParseError, ValueError):
            return None
        spec = window_spec(plan)
        if spec is not None:
            return WindowState(spec)
        aspec = agg_window_spec(plan)
        if aspec is not None:
            return AggWindowState(aspec)
        return None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            for gs in self._groups:
                gs.loop = PeriodicThread(
                    lambda _gs=gs: self._tick(_gs),
                    gs.group.interval_ms / 1000.0,
                    f"rules-{gs.group.name}")
                gs.loop.start()

    def stop(self) -> None:
        with self._lock:
            loops = [gs.loop for gs in self._groups if gs.loop is not None]
            self._started = False
        for loop in loops:
            loop.stop()
        if self.notifier is not None:
            self.notifier.close()
        for gs in self._groups:
            self._m["alerts_active"].remove(group=gs.group.name,
                                            state="pending")
            self._m["alerts_active"].remove(group=gs.group.name,
                                            state="firing")
            self._m["incr_series"].remove(group=gs.group.name)
            self._m["lag"].remove(group=gs.group.name)
            self._m["last_eval"].remove(group=gs.group.name)

    # ----------------------------------------------------------- evaluation

    def run_group_once(self, name: str,
                       eval_ms: Optional[int] = None) -> None:
        """Evaluate one group synchronously (tests, warm-up)."""
        for gs in self._groups:
            if gs.group.name == name:
                self._tick(gs, eval_ms=eval_ms)
                return
        raise KeyError(f"unknown rule group {name!r}")

    def _tick(self, gs: _GroupState, eval_ms: Optional[int] = None) -> None:
        t0 = time.perf_counter()
        now_s = time.time()
        gname = gs.group.name
        interval_s = gs.group.interval_ms / 1000.0
        if gs.last_start_s is not None:
            gap = now_s - gs.last_start_s
            overrun = max(0, int(round(gap / interval_s)) - 1)
            if overrun:
                self._m["missed"].inc(overrun, group=gname)
                gs.missed += overrun
            self._m["lag"].set(max(0.0, gap - interval_s), group=gname)
        gs.last_start_s = now_s
        eval_ms = eval_ms if eval_ms is not None else int(now_s * 1000)
        trace_id = TRACER.new_trace_id()
        failed = False
        with TRACER.attach((trace_id, None)), \
                TRACER.span("rules.group", group=gname,
                            dataset=gs.group.dataset):
            for rs in gs.rules:
                rt0 = time.perf_counter()
                try:
                    with TRACER.span("rules.eval", rule=rs.rule.name,
                                     kind=rs.rule.kind):
                        if rs.rule.kind == "recording":
                            self._eval_recording(gs, rs, eval_ms)
                        else:
                            self._eval_alerting(gs, rs, eval_ms)
                    rs.health, rs.last_error = "ok", ""
                except Exception as e:  # noqa: BLE001 — one bad rule must
                    # not block the rest of the group
                    failed = True
                    rs.health, rs.last_error = "err", str(e)
                    if rs.incremental is not None:
                        # a failed fetch may have holes: next tick is cold
                        rs.incremental.reset()
                finally:
                    rs.last_duration_s = time.perf_counter() - rt0
                    rs.last_eval_ms = eval_ms
            if gs.publisher is not None:
                gs.publisher.flush()
        dur = time.perf_counter() - t0
        with self._lock:
            gs.last_duration_s = dur
            gs.evals += 1
        self._m["eval_seconds"].observe(dur, group=gname)
        self._m["evals"].inc(group=gname,
                             outcome="failed" if failed else "ok")
        self._m["last_eval"].set(eval_ms / 1000.0, group=gname)

    # --------------------------------------------------------- recording

    @staticmethod
    def _output_labels(tags: dict, rule: RuleDef) -> dict:
        """Query-output tags -> the recorded series' labels: drop the
        metric name (Prometheus semantics for recorded outputs), apply
        the rule's label overrides."""
        out = {k: v for k, v in tags.items()
               if k not in ("_metric_", "__name__")}
        out.update(rule.labels)
        return out

    def _tick_incremental(self, gs: _GroupState, rs: _RuleState,
                          eval_ms: int) -> list:
        """One incremental tick -> ``[(tags, value)]`` for either state
        shape.  The aggregated shape's PeriodicBatch unpacks through
        the same NaN-drop the bare shape applies."""
        if isinstance(rs.incremental, AggWindowState):
            batch = rs.incremental.tick(
                eval_ms,
                lambda filters, s, e: gs.evaluator.raw_series_sharded(
                    filters, s, e, gs.timeout_ms))
            if batch is None:
                return []
            vals = batch.np_values()
            return [(batch.keys[i], float(vals[i, 0]))
                    for i in range(len(batch.keys))
                    if not np.isnan(vals[i, 0])]
        return rs.incremental.tick(
            eval_ms,
            lambda filters, s, e: gs.evaluator.raw_series(
                filters, s, e, gs.timeout_ms))

    def _eval_recording(self, gs: _GroupState, rs: _RuleState,
                        eval_ms: int) -> None:
        rule = rs.rule
        if rs.incremental is not None:
            try:
                series = self._tick_incremental(gs, rs, eval_ms)
            except WindowUnsupported:
                # the DATA refused the shape (histogram schema, shard
                # fan-out past the flat-reduce limit): permanent full
                # evaluation for this rule — retrying every tick would
                # re-fetch the window just to fail again
                rs.incremental = None
                series = gs.evaluator.instant_vector(rule.expr, eval_ms,
                                                     gs.timeout_ms)
            else:
                self._m["incr_samples"].inc(
                    rs.incremental.samples_consumed - rs.incr_seen,
                    group=gs.group.name)
                rs.incr_seen = rs.incremental.samples_consumed
                self._m["incr_series"].set(rs.incremental.resident_series,
                                           group=gs.group.name)
        else:
            series = gs.evaluator.instant_vector(rule.expr, eval_ms,
                                                 gs.timeout_ms)
        written: set = set()
        n = 0
        for tags, value in series:
            out = self._output_labels(tags, rule)
            key = tuple(sorted(out.items()))
            if key in written:
                # two input series collapsing onto one output label set
                # is a conflict Prometheus rejects; first writer wins
                continue
            written.add(key)
            if gs.publisher is not None:
                gs.publisher.add_sample(rule.name, out, eval_ms, value)
                n += 1
        # stale-series fence (the PR 11 tenant-gauge lesson): an output
        # series absent this tick gets NO sample — never a re-exported
        # last value — and its bookkeeping is dropped with it
        gone = rs.out_series - written
        if gone:
            self._m["stale"].inc(len(gone), group=gs.group.name)
        rs.out_series = written
        if n:
            self._m["samples"].inc(n, group=gs.group.name)

    # ---------------------------------------------------------- alerting

    def _eval_alerting(self, gs: _GroupState, rs: _RuleState,
                       eval_ms: int) -> None:
        rule = rs.rule
        series = gs.evaluator.instant_vector(rule.expr, eval_ms,
                                             gs.timeout_ms)
        current: dict[tuple, tuple[dict, float]] = {}
        for tags, value in series:
            labels = {k: v for k, v in tags.items()
                      if k not in ("_metric_", "__name__")}
            labels.update(rule.labels)
            labels["alertname"] = rule.name
            current[tuple(sorted(labels.items()))] = (labels, value)

        with self._lock:
            alerts = rs.alerts
            for key, (labels, value) in current.items():
                inst = alerts.get(key)
                if inst is None or inst.state == "resolved":
                    state = "pending" if rule.for_ms else "firing"
                    inst = alerts[key] = AlertInstance(
                        labels=labels,
                        annotations={k: render_template(v, labels, value)
                                     for k, v in rule.annotations.items()},
                        state=state, active_at_ms=eval_ms, value=value)
                    self._transition(gs, rule, inst, state)
                    continue
                inst.value = value
                if inst.state == "pending" \
                        and eval_ms - inst.active_at_ms >= rule.for_ms:
                    inst.state = "firing"
                    inst.annotations = {
                        k: render_template(v, labels, value)
                        for k, v in rule.annotations.items()}
                    self._transition(gs, rule, inst, "firing")
            for key in list(alerts):
                inst = alerts[key]
                if key in current:
                    continue
                if inst.state == "pending":
                    # never fired: silently back to inactive
                    del alerts[key]
                    self._m["transitions"].inc(group=gs.group.name,
                                               state="inactive")
                elif inst.state == "firing":
                    inst.state = "resolved"
                    inst.resolved_at_ms = eval_ms
                    self._transition(gs, rule, inst, "resolved")
                elif eval_ms - inst.resolved_at_ms \
                        > _RESOLVED_RETENTION_MS:
                    del alerts[key]
            pending = sum(1 for a in alerts.values()
                          if a.state == "pending")
            firing = sum(1 for a in alerts.values()
                         if a.state == "firing")
            live = [a for a in alerts.values()
                    if a.state in ("pending", "firing")]
        self._m["alerts_active"].set(pending, group=gs.group.name,
                                     state="pending")
        self._m["alerts_active"].set(firing, group=gs.group.name,
                                     state="firing")
        # ALERTS / ALERTS_FOR_STATE synthetic series ride the same
        # write-back path as recorded series (queryable, replicated)
        if gs.publisher is not None and live:
            n = 0
            for inst in live:
                tags = dict(inst.labels)
                tags["alertstate"] = inst.state
                gs.publisher.add_sample(ALERTS_METRIC, tags, eval_ms, 1.0)
                gs.publisher.add_sample(ALERTS_FOR_STATE_METRIC,
                                        dict(inst.labels), eval_ms,
                                        inst.active_at_ms / 1000.0)
                n += 2
            self._m["samples"].inc(n, group=gs.group.name)

    def _transition(self, gs: _GroupState, rule: RuleDef,
                    inst: AlertInstance, state: str) -> None:
        self._m["transitions"].inc(group=gs.group.name, state=state)
        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("rules.alert", alertname=rule.name, state=state,
                      group=gs.group.name, node=self.node,
                      value=inst.value)
        # Prometheus notifies on firing and resolution; pending is an
        # internal hold state
        if self.notifier is not None and state in ("firing", "resolved"):
            payload = inst.payload()
            payload["status"] = "firing" if state == "firing" \
                else "resolved"
            payload["startsAt"] = _iso(inst.active_at_ms)
            payload["endsAt"] = _iso(inst.resolved_at_ms) \
                if inst.resolved_at_ms else ""
            self.notifier.notify(payload)

    # -------------------------------------------------------------- views

    def rules_payload(self) -> dict:
        """``GET /api/v1/rules`` (Prometheus RulesAPI shape)."""
        groups = []
        with self._lock:
            for gs in self._groups:
                rows = []
                for rs in gs.rules:
                    r = rs.rule
                    row = {"name": r.name,
                           "query": r.rendered or r.expr,
                           "health": rs.health,
                           "lastError": rs.last_error,
                           "evaluationTime": round(rs.last_duration_s, 6),
                           "lastEvaluation": _iso(rs.last_eval_ms)
                           if rs.last_eval_ms else "",
                           "labels": dict(r.labels),
                           "type": r.kind}
                    if r.kind == "alerting":
                        live = [a for a in rs.alerts.values()
                                if a.state in ("pending", "firing")]
                        row["duration"] = r.for_ms / 1000.0
                        row["annotations"] = dict(r.annotations)
                        row["state"] = ("firing" if any(
                            a.state == "firing" for a in live)
                            else "pending" if live else "inactive")
                        row["alerts"] = [a.payload() for a in live]
                    rows.append(row)
                groups.append({"name": gs.group.name,
                               "file": gs.group.source,
                               "dataset": gs.group.dataset,
                               "interval": gs.group.interval_ms / 1000.0,
                               "rules": rows})
        return {"groups": groups}

    def alerts_payload(self) -> dict:
        """``GET /api/v1/alerts``: every live alert instance."""
        out = []
        with self._lock:
            for gs in self._groups:
                for rs in gs.rules:
                    out.extend(a.payload() for a in rs.alerts.values()
                               if a.state in ("pending", "firing"))
        return {"alerts": out}

    def admin_state(self) -> dict:
        """``GET /admin/rules``: the engine's live operational state."""
        groups = []
        with self._lock:
            for gs in self._groups:
                incr = [{"rule": rs.rule.name,
                         "series": rs.incremental.resident_series,
                         "samples": rs.incremental.resident_samples,
                         "fetched_through_ms":
                             rs.incremental.fetched_through_ms}
                        for rs in gs.rules if rs.incremental is not None]
                groups.append({
                    "name": gs.group.name,
                    "dataset": gs.group.dataset,
                    "interval_s": gs.group.interval_ms / 1000.0,
                    "timeout_ms": gs.timeout_ms,
                    "evals": gs.evals,
                    "missed": gs.missed,
                    "last_duration_s": round(gs.last_duration_s, 6),
                    "rules": [{"name": rs.rule.name,
                               "kind": rs.rule.kind,
                               "health": rs.health,
                               "lastError": rs.last_error,
                               "alerts": {
                                   s: n for s in ("pending", "firing",
                                                  "resolved")
                                   if (n := sum(
                                       1 for x in rs.alerts.values()
                                       if x.state == s))},
                               "outputSeries": len(rs.out_series)}
                              for rs in gs.rules],
                    "incremental": incr})
        state = {"priority_class": RULE_PRIORITY, "tenant": RULE_TENANT,
                 "groups": groups}
        if self.notifier is not None:
            state["notifier"] = {"url": self.notifier.url,
                                 "queue_depth":
                                     self.notifier.queue_depth()}
        return state
