"""Data-integrity subsystem: checksums, corruption tripwires, quarantine.

The reference ships a ``BlockDetective`` and a reclaim meta-size check
that halts the process rather than serve corrupt data (reference:
memory/src/main/scala/filodb.memory/BlockDetective.scala:41,
core/.../TimeSeriesShard.scala:279-301) because an in-memory columnar
store serving from raw buffers can return *wrong* data, not just slow
data.  This package makes corruption loud and contained instead of
silent:

- :func:`chunk_crc` — CRC32C per chunk blob, computed at flush/encode
  time, persisted next to the chunk (store/persistence.py ``crc``
  column) and re-verified on every ODP page-in and bulk read-back.
- :class:`CorruptVectorError` — the structured error raised from
  native/numpy decode ``-1`` sentinels, carrying part-key context, the
  chunk id, the codec (wire type) and a bounded hexdump window.
- :data:`QUARANTINE` — process-wide registry of corrupt chunks; a
  quarantined chunk is excluded from serving (queries return a
  partial-data warning, never wrong values or silence).
- :mod:`filodb_tpu.integrity.faultinject` — deterministic fault
  injection (byte flips, truncation, checksum corruption) used by
  tests/test_integrity.py.
- :mod:`filodb_tpu.integrity.scan` — the offline ``verify-chunks``
  scanner behind the CLI subcommand.

Counters surface through utils/observability.py (``integrity_metrics``)
and the ``/admin/integrity`` HTTP endpoint.
"""

from __future__ import annotations

import logging
import os
import threading
import weakref
from typing import Optional

from filodb_tpu.integrity.quarantine import QuarantineRegistry

_LOG = logging.getLogger("filodb.integrity")

#: Process-wide quarantine registry (keyed by (partkey, chunk_id)).
QUARANTINE = QuarantineRegistry()

# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------

_CRC_TABLE: Optional[list] = None
_CRC_LOCK = threading.Lock()


def _crc_table() -> list:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        with _CRC_LOCK:
            if _CRC_TABLE is None:
                tab = []
                for i in range(256):
                    c = i
                    for _ in range(8):
                        c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
                    tab.append(c)
                _CRC_TABLE = tab
    return _CRC_TABLE


def crc32c_py(data, seed: int = 0) -> int:
    """Pure-Python CRC32C (Castagnoli), bit-identical to the C kernel
    (``crc32c_buf`` in native/src/codecs.cpp).  Table-driven byte loop:
    slow, but only the fallback when the native library is absent —
    checksums must never change value with the codec hooks toggled."""
    tab = _crc_table()
    crc = ~seed & 0xFFFFFFFF
    for b in bytes(data):
        crc = (crc >> 8) ^ tab[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def chunk_crc(data) -> int:
    """CRC32C of one framed chunk blob — THE chunk checksum.  Never 0
    for any input: 0 is the 'no checksum recorded' marker in the store,
    so a real 0 is mapped to 1 (one in 4e9 chunks pays a one-bit-weaker
    check instead of silently skipping verification forever)."""
    from filodb_tpu import native
    got = native.crc32c(data)
    if got is None:
        got = crc32c_py(data)
    return got or 1


# ---------------------------------------------------------------------------
# Structured corruption errors
# ---------------------------------------------------------------------------


def hexdump_window(buf, offset: int = 0, width: int = 64) -> str:
    """Bounded hex window of ``buf`` around ``offset`` for forensics
    (the BlockDetective analog: enough bytes to diagnose, never the
    whole chunk in a log line)."""
    try:
        b = bytes(buf)
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return "<unreadable>"
    lo = max(0, min(offset, len(b)) - width // 2)
    hi = min(len(b), lo + width)
    body = b[lo:hi].hex()
    pre = "..." if lo > 0 else ""
    post = "..." if hi < len(b) else ""
    return f"[{lo}:{hi}/{len(b)}] {pre}{body}{post}"


class CorruptVectorError(ValueError):
    """A chunk vector failed its checksum or decode.

    Subclasses ValueError so pre-existing ``except ValueError`` decode
    guards keep working; carries the forensic context the reference's
    BlockDetective would print: part-key, chunk id, codec (wire type),
    and a bounded hexdump window of the offending bytes.  ``kind`` is
    the explicit counter class ("checksum" or "decode") — never
    inferred from free text.
    """

    def __init__(self, reason: str, *, partkey: Optional[bytes] = None,
                 chunk_id: Optional[int] = None,
                 codec: Optional[int] = None,
                 dataset: Optional[str] = None,
                 shard: Optional[int] = None,
                 blob=None, kind: str = "decode",
                 start_time: Optional[int] = None,
                 end_time: Optional[int] = None):
        self.reason = reason
        self.partkey = bytes(partkey) if partkey is not None else None
        self.chunk_id = chunk_id
        self.codec = codec
        self.dataset = dataset
        self.shard = shard
        self.kind = kind
        self.start_time = start_time
        self.end_time = end_time
        self.window = hexdump_window(blob) if blob is not None else None
        parts = [reason]
        if dataset is not None:
            parts.append(f"dataset={dataset}")
        if shard is not None:
            parts.append(f"shard={shard}")
        if self.partkey is not None:
            pk = self.partkey.hex()
            parts.append(f"partkey={pk[:64]}{'...' if len(pk) > 64 else ''}")
        if chunk_id is not None:
            parts.append(f"chunk_id={chunk_id}")
        if codec is not None:
            parts.append(f"codec={_codec_name(codec)}")
        if self.window is not None:
            parts.append(f"bytes={self.window}")
        super().__init__(" ".join(parts))


def corrupt_chunk_error(cs, cause, dataset: Optional[str] = None,
                        shard: Optional[int] = None) -> CorruptVectorError:
    """Build the structured error for a ChunkSet whose decode hit a -1
    sentinel: re-probe vector by vector to pin down the failing codec
    and grab its hexdump window (the slow path runs once per corrupt
    chunk, never on healthy data)."""
    from filodb_tpu.integrity.scan import _decode_vector
    codec = None
    blob = None
    for vec in cs.vectors:
        try:
            _decode_vector(vec)
        except Exception:  # noqa: BLE001 — any decode failure pins the vector
            b = bytes(vec)
            codec = b[0] if b else None
            blob = b
            break
    return CorruptVectorError(f"chunk decode failed: {cause}",
                              partkey=cs.partkey, chunk_id=cs.info.chunk_id,
                              codec=codec, dataset=dataset, shard=shard,
                              blob=blob, kind="decode",
                              start_time=cs.info.start_time,
                              end_time=cs.info.end_time)


def _codec_name(codec: int) -> str:
    try:
        from filodb_tpu.codecs.wire import WireType
        return f"{WireType(codec).name}({codec})"
    except ValueError:
        return str(codec)


# ---------------------------------------------------------------------------
# Verification switch + reporting
# ---------------------------------------------------------------------------

_VERIFY = os.environ.get("FILODB_INTEGRITY_VERIFY", "1") != "0"


def verify_enabled() -> bool:
    """Read-side checksum verification switch (on by default; set
    FILODB_INTEGRITY_VERIFY=0 for A/B overhead measurement only)."""
    return _VERIFY


def set_verify(on: bool) -> None:
    global _VERIFY
    _VERIFY = bool(on)


#: live shards by (dataset, shard) so store-level detections (which
#: know only the ids, not the object) still reach per-shard stats and
#: grid-plan invalidation; weak values — a dropped shard unregisters
#: itself by garbage collection
_SHARD_HOOKS = weakref.WeakValueDictionary()


def register_shard(shard) -> None:
    """Called from TimeSeriesShard.__init__: routes corruption reports
    carrying this (dataset, shard) identity to shard.note_corrupt_chunk.
    Latest registration wins (a fresh memstore over the same data is
    the one actually serving)."""
    _SHARD_HOOKS[(shard.dataset, shard.shard_num)] = shard


def report_corrupt(err: CorruptVectorError) -> bool:
    """Funnel for every detected corruption: quarantine the chunk,
    bump the observability counters, notify the owning shard (when the
    error names one), and log — ONCE per chunk (repeat hits on a
    quarantined chunk count but do not re-log).  Returns True when the
    chunk is newly quarantined."""
    from filodb_tpu.utils.observability import integrity_metrics
    m = integrity_metrics()
    labels = {}
    if err.dataset is not None:
        labels["dataset"] = err.dataset
    if err.shard is not None:
        labels["shard"] = str(err.shard)
    m["checksum_failures" if err.kind == "checksum"
      else "decode_failures"].inc(**labels)
    new = False
    if err.partkey is not None and err.chunk_id is not None:
        new = QUARANTINE.quarantine(err.partkey, err.chunk_id,
                                    reason=err.reason, detail=str(err),
                                    dataset=err.dataset, shard=err.shard,
                                    start_time=err.start_time,
                                    end_time=err.end_time)
        m["chunks_quarantined"].set(QUARANTINE.total())
    if err.dataset is not None and err.shard is not None:
        # store-level detection: reach the shard's stats + grid-plan
        # invalidation.  Partition-level detections carry NO
        # dataset/shard (the partition doesn't know them) and route via
        # their own on_corrupt hook instead — never both.
        sh = _SHARD_HOOKS.get((err.dataset, err.shard))
        if sh is not None:
            sh.note_corrupt_chunk(err, new)
    if new or err.partkey is None or err.chunk_id is None:
        _LOG.error("corrupt chunk detected: %s", err)
    return new


class IntegrityInvariantError(RuntimeError):
    """Eviction/reclaim bookkeeping broke a hard invariant.  The owning
    shard fails rather than serve stale buffers (the reference kills
    the process on the reclaim meta-size check; we fail the shard)."""


def note_invariant_failure(dataset: str, shard: int, detail: str) -> None:
    from filodb_tpu.utils.observability import integrity_metrics
    integrity_metrics()["invariant_failures"].inc(dataset=dataset,
                                                  shard=str(shard))
    _LOG.critical("integrity invariant failed: dataset=%s shard=%s %s",
                  dataset, shard, detail)
    # the black box hits the ground: an integrity failure fails the
    # shard, so the events leading up to it are the postmortem
    from filodb_tpu.utils.devicewatch import FLIGHT
    FLIGHT.record("integrity.fail", dataset=dataset, shard=shard,
                  detail=detail[:200])
    FLIGHT.dump_to_log(f"integrity failure {dataset}/{shard}")
