"""Offline chunk verification: the ``verify-chunks`` CLI subcommand.

Scans a dataset's persisted chunks shard by shard, recomputing the
CRC32C of every framed blob against the stored checksum and (with
``deep=True``) decoding every vector through the same codec paths the
query engine uses.  Reports per-shard pass/fail counts so an operator
can audit a store at rest without starting a server (the offline analog
of verify-on-page-in).
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

from filodb_tpu.integrity import CorruptVectorError, chunk_crc

_U16 = struct.Struct("<H")


def _decode_vector(blob) -> None:
    """Decode one encoded vector by its wire-type byte, raising
    ValueError on corruption.  Covers every family the codecs emit."""
    from filodb_tpu.codecs import (deltadelta, doublecodec, histcodec,
                                   strcodec)
    from filodb_tpu.codecs.wire import WireType
    b = bytes(blob)
    if not b:
        raise ValueError("empty vector")
    wire = b[0]
    if wire < WireType.DELTA2_DOUBLE:
        deltadelta.decode(b)
    elif wire < WireType.HIST_2D_DELTA:
        doublecodec.decode(b)
    elif wire < WireType.UTF8_DENSE:
        histcodec.decode(b)
    elif wire < WireType.INT_NBIT:
        strcodec.decode_utf8(b)
    elif wire == WireType.INT_NBIT:
        strcodec.decode_nbit(b)
    else:
        raise ValueError(f"unknown wire type {wire}")


def verify_chunk_row(partkey: bytes, chunk_id: int, blob, crc: int,
                     deep: bool = False, dataset: Optional[str] = None,
                     shard: Optional[int] = None) -> None:
    """Verify one persisted chunk row; raises CorruptVectorError on any
    checksum or (deep) framing/decode failure."""
    if crc:
        got = chunk_crc(blob)
        if got != crc:
            raise CorruptVectorError(
                f"checksum mismatch (stored={crc:#010x} "
                f"computed={got:#010x})", partkey=partkey,
                chunk_id=chunk_id, dataset=dataset, shard=shard,
                blob=blob, kind="checksum")
    if not deep:
        return
    try:
        from filodb_tpu.store.persistence import unpack_vectors
        vectors = unpack_vectors(bytes(blob))
    except Exception as e:  # noqa: BLE001 — framing corruption
        raise CorruptVectorError(f"bad chunk framing: {e}",
                                 partkey=partkey, chunk_id=chunk_id,
                                 dataset=dataset, shard=shard,
                                 blob=blob) from e
    for j, vec in enumerate(vectors):
        try:
            _decode_vector(vec)
        except ValueError as e:
            codec = bytes(vec)[0] if len(bytes(vec)) else None
            raise CorruptVectorError(
                f"vector {j} decode failed: {e}", partkey=partkey,
                chunk_id=chunk_id, codec=codec, dataset=dataset,
                shard=shard, blob=vec) from e


def verify_chunks(store, dataset: str,
                  shards: Optional[Sequence[int]] = None,
                  deep: bool = False, max_failures: int = 100) -> dict:
    """Scan a dataset's persisted chunks and report per-shard counts.

    Returns ``{"dataset", "shards": {shard: {"chunks", "passed",
    "failed", "unchecksummed", "failures": [...]}}, "total_failed"}``.
    ``failures`` is bounded at ``max_failures`` per shard."""
    if shards is None:
        shards = store.list_shards(dataset)
    out: dict = {"dataset": dataset, "deep": deep, "shards": {}}
    total_failed = 0
    for sh in shards:
        chunks = passed = failed = nocrc = 0
        failures: list[str] = []
        for pk, cid, blob, crc in store.scan_chunk_rows(dataset, sh):
            chunks += 1
            if not crc:
                nocrc += 1
            try:
                verify_chunk_row(pk, cid, blob, crc, deep=deep,
                                 dataset=dataset, shard=sh)
                passed += 1
            except CorruptVectorError as e:
                failed += 1
                if len(failures) < max_failures:
                    failures.append(str(e))
        total_failed += failed
        out["shards"][sh] = {"chunks": chunks, "passed": passed,
                             "failed": failed, "unchecksummed": nocrc,
                             "failures": failures}
    out["total_failed"] = total_failed
    return out
