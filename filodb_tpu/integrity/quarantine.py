"""Quarantine registry: corrupt chunks excluded from serving.

One process-wide registry (``filodb_tpu.integrity.QUARANTINE``) keyed by
``(partkey bytes, chunk_id)`` — the pair is stable across every layer
that can detect corruption (store read-back, ODP page-in, partition
decode), so a chunk quarantined by any of them is excluded by all of
them.  Queries overlapping a quarantined chunk return a partial-data
warning (query/exec.py), never the corrupt values and never silence.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable, Optional


class QuarantineRegistry:
    """Thread-safe set of quarantined (partkey, chunk_id) pairs with a
    bounded detail log for the /admin/integrity endpoint."""

    def __init__(self, max_details: int = 1024):
        # partkey -> {chunk_id: (start_time, end_time) | None}: the time
        # range lets the query path warn only when a quarantined chunk
        # actually OVERLAPS the scanned window
        self._by_pk: dict[bytes, dict[int, Optional[tuple]]] = {}
        self._details: list[dict] = []
        self._max_details = max_details
        self._dropped_details = 0
        # bumped on every membership change (add OR clear): consumers
        # that memoize results computed with quarantine exclusions
        # applied (query/resultcache.py) key their validity on it — a
        # cached answer must never outlive the exclusion set it saw
        self._epoch = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def quarantine(self, partkey: bytes, chunk_id: int, *,
                   reason: str = "", detail: str = "",
                   dataset: Optional[str] = None,
                   shard: Optional[int] = None,
                   start_time: Optional[int] = None,
                   end_time: Optional[int] = None) -> bool:
        """Add one chunk.  Returns True when newly quarantined (callers
        use this for log-once semantics)."""
        partkey = bytes(partkey)
        chunk_id = int(chunk_id)
        span = (start_time, end_time) \
            if start_time is not None and end_time is not None else None
        with self._lock:
            ids = self._by_pk.setdefault(partkey, {})
            if chunk_id in ids:
                return False
            ids[chunk_id] = span
            self._epoch += 1
            if len(self._details) < self._max_details:
                self._details.append({
                    "partkey": partkey.hex(), "chunk_id": chunk_id,
                    "dataset": dataset, "shard": shard, "reason": reason,
                    "start_time": start_time, "end_time": end_time,
                    "detail": detail, "at_ms": int(time.time() * 1000)})
            else:
                self._dropped_details += 1
        # a quarantined chunk is an eviction from the serving set:
        # attribute it on the devicewatch eviction counter + flight ring
        from filodb_tpu.utils.devicewatch import LEDGER
        LEDGER.note_eviction(f"quarantine:{dataset}/{shard}",
                             "integrity_quarantine")
        return True

    def is_quarantined(self, partkey: bytes, chunk_id: int) -> bool:
        with self._lock:
            ids = self._by_pk.get(bytes(partkey))
            return ids is not None and int(chunk_id) in ids

    def chunk_ids(self, partkey: bytes) -> frozenset:
        """Quarantined chunk ids for one partkey (empty when none)."""
        with self._lock:
            ids = self._by_pk.get(bytes(partkey))
            return frozenset(ids) if ids else frozenset()

    def count_for(self, partkey: bytes) -> int:
        with self._lock:
            ids = self._by_pk.get(bytes(partkey))
            return len(ids) if ids else 0

    def count_overlapping(self, partkeys: Iterable[bytes],
                          start_time: int, end_time: int) -> int:
        """Quarantined chunks across a partkey set whose time range
        overlaps [start_time, end_time] — the leaf query plan's
        partial-data check: a corrupt chunk outside the scanned window
        excluded nothing from THIS result, so it must not flag it.
        Chunks quarantined without a known range count conservatively.
        O(1) when nothing is quarantined (the common case)."""
        with self._lock:
            if not self._by_pk:
                return 0
            by_pk = self._by_pk
            n = 0
            for pk in map(bytes, partkeys):
                ids = by_pk.get(pk)
                if not ids:
                    continue
                for span in ids.values():
                    if span is None or (span[1] >= start_time
                                        and span[0] <= end_time):
                        n += 1
            return n

    def total(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._by_pk.values())

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._by_pk)

    def items(self) -> list[dict]:
        """Detail records for the admin endpoint (bounded at
        construction; ``dropped`` in :meth:`summary` counts overflow)."""
        with self._lock:
            return [dict(d) for d in self._details]

    def summary(self) -> dict:
        with self._lock:
            return {"quarantined_chunks":
                    sum(len(v) for v in self._by_pk.values()),
                    "quarantined_partkeys": len(self._by_pk),
                    "detail_records": len(self._details),
                    "detail_records_dropped": self._dropped_details}

    def epoch(self) -> int:
        """Monotone membership version: changes whenever the exclusion
        set changes in either direction."""
        with self._lock:
            return self._epoch

    def clear(self) -> None:
        """Operator action (and test isolation): forget everything."""
        with self._lock:
            if self._by_pk:
                self._epoch += 1
            self._by_pk.clear()
            self._details.clear()
            self._dropped_details = 0
