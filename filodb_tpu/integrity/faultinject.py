"""Deterministic fault injection for integrity + workload testing.

The reference proves its corruption handling with unit-level byte
surgery; this harness does it end-to-end and deterministically from a
seed: flip bytes in chunks persisted in the sqlite ColumnStore, truncate
their frames, corrupt their stored checksums, or flip bytes in a live
partition's frozen (HBM-staging) chunk vectors.  Used by
tests/test_integrity.py; also handy from a REPL against a throwaway
store copy.  NEVER point it at data you care about.

ISSUE 5 adds :class:`FlakyTcpProxy` — a deterministic CONNECTION-fault
injector for the dispatch retry/hedge path: a TCP proxy in front of a
real data node whose per-connection behavior follows an explicit plan
(refuse / stall / pass), so tests/test_workload.py can prove bounded
retry-with-backoff and p99-triggered hedging without flaky sleeps.

ISSUE 15 adds :func:`inject_kernel_slowdown` — a deterministic
device-time fault for the kernel regression sentry: the named program's
SAMPLED launches sleep the given delay inside the timed region, so the
sentry's EWMA sees a real sustained slowdown without depending on
backend scheduling.
"""

from __future__ import annotations

import collections
import random
import socket
import socketserver
import threading
import time
from typing import Optional

from filodb_tpu.integrity import chunk_crc


def inject_kernel_slowdown(program: str, seconds: float) -> None:
    """Deterministically slow one program's SAMPLED device timings: the
    kernel timer sleeps ``seconds`` inside the timed region of every
    sampled launch of ``program``, so its EWMA device time rises by
    exactly that much — the injection the regression-sentry chaos test
    drives (tests/test_devicewatch.py)."""
    from filodb_tpu.utils.devicewatch import KERNEL_TIMER
    KERNEL_TIMER.set_fault_delay(program, seconds)


def clear_kernel_slowdown(program: str) -> None:
    """Lift an injected slowdown; the sentry re-arms once the EWMA
    decays back under the regression factor."""
    from filodb_tpu.utils.devicewatch import KERNEL_TIMER
    KERNEL_TIMER.clear_fault_delay(program)


class FaultInjector:
    """Seeded corruption source.  Every choice (which chunk, which byte,
    which bit) comes from ``random.Random(seed)`` so a failing test
    reproduces exactly."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)

    # ------------------------------------------------------------ byte ops

    def flip_byte(self, data: bytes, index: Optional[int] = None,
                  ) -> tuple[bytes, int]:
        """One bit flipped in one byte.  Returns (corrupted, index)."""
        b = bytearray(data)
        if not b:
            raise ValueError("cannot flip a byte of an empty buffer")
        if index is None:
            index = self.rng.randrange(len(b))
        b[index] ^= 1 << self.rng.randrange(8)
        return bytes(b), index

    def truncate(self, data: bytes, keep: Optional[int] = None) -> bytes:
        """Drop the tail of a frame (keep >= 1 byte so the row still
        parses as a blob)."""
        if keep is None:
            keep = self.rng.randrange(1, max(len(data), 2))
        return bytes(data[:keep])

    # ------------------------------------------------------- disk chunks

    def corrupt_stored_chunk(self, store, dataset: str, shard: int,
                             partkey: Optional[bytes] = None,
                             chunk_id: Optional[int] = None,
                             mode: str = "flip",
                             fix_crc: bool = False) -> tuple[bytes, int]:
        """Corrupt one chunk row in a DiskColumnStore.

        ``mode``: ``"flip"`` (one bit of the framed blob), ``"truncate"``
        (drop the frame tail), or ``"crc"`` (corrupt only the stored
        checksum, leaving the data intact).  ``fix_crc=True`` recomputes
        the stored checksum over the corrupted blob so the checksum
        verify PASSES and the decode tripwire must catch it instead.
        Returns (partkey, chunk_id) of the victim."""
        conn = store._conn()
        where = "dataset=? AND shard=?"
        params: list = [dataset, shard]
        if partkey is not None:
            where += " AND partkey=?"
            params.append(partkey)
        if chunk_id is not None:
            where += " AND chunk_id=?"
            params.append(chunk_id)
        rows = conn.execute(
            f"SELECT partkey, chunk_id, vectors, crc FROM chunks "
            f"WHERE {where} ORDER BY partkey, chunk_id",
            params).fetchall()
        if not rows:
            raise LookupError(f"no chunks match {dataset}/{shard}")
        pk, cid, blob, crc = rows[self.rng.randrange(len(rows))]
        if mode == "flip":
            blob, _ = self.flip_byte(blob)
        elif mode == "truncate":
            blob = self.truncate(blob)
        elif mode == "crc":
            crc = (crc ^ 0xDEADBEEF) or 1
        else:
            raise ValueError(f"unknown mode {mode!r}")
        if mode != "crc":
            crc = chunk_crc(blob) if fix_crc else crc
        conn.execute(
            "UPDATE chunks SET vectors=?, crc=? "
            "WHERE dataset=? AND shard=? AND partkey=? AND chunk_id=?",
            (blob, crc, dataset, shard, pk, cid))
        conn.commit()
        return bytes(pk), int(cid)

    # ------------------------------------------- staged (in-memory) chunks

    def corrupt_staged_chunk(self, partition, chunk_index: Optional[int] = None,
                             vector: Optional[int] = None,
                             mode: str = "flip") -> int:
        """Corrupt a frozen chunk's encoded vector ON the live partition
        object — the stand-in for corruption of HBM-resident staging
        buffers (encoded chunks awaiting device-grid staging or flush).

        ``mode``: ``"flip"`` (one random bit — may or may not break the
        decode, exactly like real bit rot), ``"wire"`` (invalid wire-type
        byte: decode MUST fail — deterministic tests), or ``"truncate"``.
        Returns the victim chunk_id."""
        if not partition.chunks:
            raise LookupError("partition has no frozen chunks")
        if chunk_index is None:
            chunk_index = self.rng.randrange(len(partition.chunks))
        cs = partition.chunks[chunk_index]
        vecs = list(cs.vectors)
        if vector is None:
            vector = self.rng.randrange(len(vecs))
        raw = bytes(vecs[vector])
        if mode == "flip":
            corrupted, _ = self.flip_byte(raw)
        elif mode == "wire":
            corrupted = bytes([0xEE]) + raw[1:]
        elif mode == "truncate":
            corrupted = self.truncate(raw)
        else:
            raise ValueError(f"unknown mode {mode!r}")
        vecs[vector] = corrupted
        cs.vectors = vecs
        # the decoded cache may hold the clean decode: drop it so the
        # corruption is actually exercised on the next read
        partition._decoded.pop(cs.info.chunk_id, None)
        return int(cs.info.chunk_id)


# ---------------------------------------------------------------------------
# Connection faults (ISSUE 5: dispatch retry / hedge testing)
# ---------------------------------------------------------------------------


class FlakyTcpProxy:
    """TCP proxy with a deterministic per-connection fault plan.

    Sits between an HttpPlanDispatcher and a real data node.  Each
    accepted connection pops the next mode from the plan (default
    ``pass``):

    - ``refuse``: close immediately — the client sees a reset /
      RemoteDisconnected, the retryable connection-error class;
    - ``stall``: sleep ``stall_s`` BEFORE forwarding — a tail-slow
      backend, the hedge trigger;
    - ``pass``: forward transparently.

    A seeded ``failure_rate`` can inject random refusals
    reproducibly; explicit plans (``fail_next``/``stall_next``) make
    assertions exact."""

    def __init__(self, backend_port: int, backend_host: str = "127.0.0.1",
                 stall_s: float = 0.5, failure_rate: float = 0.0,
                 seed: int = 0):
        self.backend = (backend_host, backend_port)
        self.stall_s = stall_s
        self.failure_rate = failure_rate
        self.rng = random.Random(seed)
        self.port = 0
        self.connections = 0
        self.refused = 0
        self.stalled = 0
        self._plan: collections.deque = collections.deque()
        self._blackhole = False
        self._lock = threading.Lock()
        self._server: Optional[socketserver.ThreadingTCPServer] = None

    def fail_next(self, n: int = 1) -> None:
        with self._lock:
            self._plan.extend(["refuse"] * n)

    def stall_next(self, n: int = 1) -> None:
        with self._lock:
            self._plan.extend(["stall"] * n)

    def _next_mode(self) -> str:
        with self._lock:
            self.connections += 1
            if self._blackhole:
                # node-level partition (ISSUE 7): EVERY connection dies
                # until heal() — an explicit plan cannot override it
                return "refuse"
            if self._plan:
                return self._plan.popleft()
            if self.failure_rate and self.rng.random() < self.failure_rate:
                return "refuse"
            return "pass"

    # ---- persistent node-level modes (ISSUE 7 chaos controller) ----

    def blackhole(self, on: bool = True) -> None:
        """Partition this endpoint: refuse every connection until
        ``blackhole(False)`` — unlike the per-connection plan, this is
        a STATE, so in-flight reconnects/retries keep failing."""
        with self._lock:
            self._blackhole = bool(on)

    def start(self) -> int:
        proxy = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                mode = proxy._next_mode()
                if mode == "refuse":
                    with proxy._lock:
                        proxy.refused += 1
                    try:  # RST, not FIN: an unambiguous connection error
                        self.request.setsockopt(
                            socket.SOL_SOCKET, socket.SO_LINGER,
                            b"\x01\x00\x00\x00\x00\x00\x00\x00")
                    except OSError:
                        pass
                    return
                if mode == "stall":
                    with proxy._lock:
                        proxy.stalled += 1
                    time.sleep(proxy.stall_s)
                try:
                    upstream = socket.create_connection(proxy.backend,
                                                        timeout=10)
                except OSError:
                    return
                try:
                    t = threading.Thread(
                        target=proxy._pump,
                        args=(self.request, upstream), daemon=True)
                    t.start()
                    proxy._pump(upstream, self.request)
                    t.join(timeout=10)
                finally:
                    upstream.close()

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         name="flaky-proxy", daemon=True).start()
        return self.port

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()


# ---------------------------------------------------------------------------
# Node-level chaos (ISSUE 7: replica-group HA testing)
# ---------------------------------------------------------------------------


class NodeChaosController:
    """Deterministic node-level faults for in-process multi-node
    clusters (ISSUE 7): kill a node mid-query/mid-ingest, partition it
    from its peers, stall its connections, and later restart it.

    Each node registers a ``kill_fn`` (hard process-death stand-in — an
    abrupt FiloServer teardown with NO graceful flush beyond in-flight
    tasks, so the checkpoint stays behind the head exactly like a real
    crash) plus optionally a :class:`FlakyTcpProxy` fronting its HTTP
    endpoint, which lets partitions and stalls hit both peer gossip and
    dispatch traffic without taking the node down.  Everything is
    explicit and synchronous — a failing chaos test reproduces exactly
    (the FaultInjector contract)."""

    def __init__(self):
        self._nodes: dict[str, dict] = {}
        self.events: list[tuple[str, str]] = []  # (action, node), ordered
        # split-phase chaos (ISSUE 13): SplitControllers registered per
        # node so scenarios can latch the phase machine at an exact
        # transition ("kill a child's node mid-catch-up", "partition
        # the coordinator during cutover") and observe transitions
        self._split: dict[str, object] = {}
        self.split_phases: list[tuple[str, str, str]] = []  # (node, ds, phase)

    def register(self, name: str, kill_fn=None,
                 proxy: Optional[FlakyTcpProxy] = None,
                 stall_ingest_fn=None, resume_ingest_fn=None) -> None:
        """``stall_ingest_fn``/``resume_ingest_fn`` (ISSUE 9) wedge and
        un-wedge the node's ingest consumers while the node itself
        keeps serving — the fault class the self-monitoring rule pack
        must detect end to end (ingest stall -> watermark ledger ->
        self-scrape -> alert)."""
        self._nodes[name] = {"kill": kill_fn, "proxy": proxy,
                             "killed": False,
                             "stall_ingest": stall_ingest_fn,
                             "resume_ingest": resume_ingest_fn}

    def _note(self, action: str, node: str) -> None:
        self.events.append((action, node))
        from filodb_tpu.utils.devicewatch import FLIGHT
        FLIGHT.record("chaos." + action, node=node)

    def kill(self, name: str) -> None:
        """Hard-stop the node: its HTTP endpoint dies (peers see
        connection failures, heartbeats lapse), its ingest consumers
        stop, nothing graceful beyond in-flight work."""
        ent = self._nodes[name]
        if ent["killed"]:
            return
        ent["killed"] = True
        if ent["proxy"] is not None:
            ent["proxy"].blackhole(True)
        if ent["kill"] is not None:
            ent["kill"]()
        self._note("kill", name)

    def partition(self, name: str) -> None:
        """Cut the node off from its peers (proxy blackhole) while the
        node itself keeps running — the classic asymmetric partition."""
        proxy = self._nodes[name]["proxy"]
        if proxy is None:
            raise ValueError(f"node {name} has no chaos proxy")
        proxy.blackhole(True)
        self._note("partition", name)

    def stall(self, name: str, n: int = 1,
              stall_s: Optional[float] = None) -> None:
        """Stall the node's next ``n`` connections (tail-latency/wedge
        injection for hedging + failover paths)."""
        proxy = self._nodes[name]["proxy"]
        if proxy is None:
            raise ValueError(f"node {name} has no chaos proxy")
        if stall_s is not None:
            proxy.stall_s = float(stall_s)
        proxy.stall_next(n)
        self._note("stall", name)

    def stall_ingest(self, name: str) -> None:
        """Wedge the node's ingest consumers (producers keep queueing,
        so lag grows and the watermark stall machine eventually fires)."""
        fn = self._nodes[name]["stall_ingest"]
        if fn is None:
            raise ValueError(f"node {name} has no ingest-stall hook")
        fn()
        self._note("stall_ingest", name)

    def resume_ingest(self, name: str) -> None:
        """Un-wedge a stalled node's ingest consumers; the backlog
        drains and lag returns to zero."""
        fn = self._nodes[name]["resume_ingest"]
        if fn is None:
            raise ValueError(f"node {name} has no ingest-resume hook")
        fn()
        self._note("resume_ingest", name)

    def heal(self, name: str) -> None:
        """Lift a partition (kills need :meth:`restart`)."""
        proxy = self._nodes[name]["proxy"]
        if proxy is not None:
            proxy.blackhole(False)
        self._note("heal", name)

    def restart(self, name: str, start_fn) -> object:
        """Mark the node live again and run ``start_fn`` (typically
        builds a fresh FiloServer over the same data-dir, re-registering
        its kill hook); returns start_fn's result."""
        ent = self._nodes[name]
        if ent["proxy"] is not None:
            ent["proxy"].blackhole(False)
        ent["killed"] = False
        out = start_fn()
        self._note("restart", name)
        return out

    def killed(self, name: str) -> bool:
        return self._nodes[name]["killed"]

    # ---- split-phase hooks (ISSUE 13: elastic-resharding chaos) ----

    def attach_split_controller(self, name: str, controller) -> None:
        """Track a node's SplitController and record its (dataset,
        phase) transitions in ``split_phases`` — scenarios assert exact
        phase interleavings against the fault schedule."""
        self._split[name] = controller
        controller.on_transition(
            lambda ds, phase, _n=name: self.split_phases.append(
                (_n, ds, phase)))

    def hold_split(self, name: str, transition: str) -> None:
        """Latch the node's split phase machine right BEFORE
        ``transition`` ("cutover" | "retire" | "complete") — the
        deterministic window for killing a child's node mid-catch-up or
        partitioning the coordinator mid-cutover."""
        self._split[name].hold(transition)
        self._note(f"split_hold:{transition}", name)

    def release_split(self, name: str, transition: str) -> None:
        self._split[name].release(transition)
        self._note(f"split_release:{transition}", name)

    def wait_split_phase(self, dataset: str, phase: str,
                         timeout_s: float = 30.0) -> bool:
        """Block until ANY tracked controller reports the dataset in
        ``phase`` (poll the recorded transitions; deterministic — the
        phase either arrives or the scenario fails loudly)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if any(ds == dataset and ph == phase
                   for _n, ds, ph in self.split_phases):
                return True
            time.sleep(0.02)
        return False
