"""Deterministic fault injection for integrity testing.

The reference proves its corruption handling with unit-level byte
surgery; this harness does it end-to-end and deterministically from a
seed: flip bytes in chunks persisted in the sqlite ColumnStore, truncate
their frames, corrupt their stored checksums, or flip bytes in a live
partition's frozen (HBM-staging) chunk vectors.  Used by
tests/test_integrity.py; also handy from a REPL against a throwaway
store copy.  NEVER point it at data you care about.
"""

from __future__ import annotations

import random
from typing import Optional

from filodb_tpu.integrity import chunk_crc


class FaultInjector:
    """Seeded corruption source.  Every choice (which chunk, which byte,
    which bit) comes from ``random.Random(seed)`` so a failing test
    reproduces exactly."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)

    # ------------------------------------------------------------ byte ops

    def flip_byte(self, data: bytes, index: Optional[int] = None,
                  ) -> tuple[bytes, int]:
        """One bit flipped in one byte.  Returns (corrupted, index)."""
        b = bytearray(data)
        if not b:
            raise ValueError("cannot flip a byte of an empty buffer")
        if index is None:
            index = self.rng.randrange(len(b))
        b[index] ^= 1 << self.rng.randrange(8)
        return bytes(b), index

    def truncate(self, data: bytes, keep: Optional[int] = None) -> bytes:
        """Drop the tail of a frame (keep >= 1 byte so the row still
        parses as a blob)."""
        if keep is None:
            keep = self.rng.randrange(1, max(len(data), 2))
        return bytes(data[:keep])

    # ------------------------------------------------------- disk chunks

    def corrupt_stored_chunk(self, store, dataset: str, shard: int,
                             partkey: Optional[bytes] = None,
                             chunk_id: Optional[int] = None,
                             mode: str = "flip",
                             fix_crc: bool = False) -> tuple[bytes, int]:
        """Corrupt one chunk row in a DiskColumnStore.

        ``mode``: ``"flip"`` (one bit of the framed blob), ``"truncate"``
        (drop the frame tail), or ``"crc"`` (corrupt only the stored
        checksum, leaving the data intact).  ``fix_crc=True`` recomputes
        the stored checksum over the corrupted blob so the checksum
        verify PASSES and the decode tripwire must catch it instead.
        Returns (partkey, chunk_id) of the victim."""
        conn = store._conn()
        where = "dataset=? AND shard=?"
        params: list = [dataset, shard]
        if partkey is not None:
            where += " AND partkey=?"
            params.append(partkey)
        if chunk_id is not None:
            where += " AND chunk_id=?"
            params.append(chunk_id)
        rows = conn.execute(
            f"SELECT partkey, chunk_id, vectors, crc FROM chunks "
            f"WHERE {where} ORDER BY partkey, chunk_id",
            params).fetchall()
        if not rows:
            raise LookupError(f"no chunks match {dataset}/{shard}")
        pk, cid, blob, crc = rows[self.rng.randrange(len(rows))]
        if mode == "flip":
            blob, _ = self.flip_byte(blob)
        elif mode == "truncate":
            blob = self.truncate(blob)
        elif mode == "crc":
            crc = (crc ^ 0xDEADBEEF) or 1
        else:
            raise ValueError(f"unknown mode {mode!r}")
        if mode != "crc":
            crc = chunk_crc(blob) if fix_crc else crc
        conn.execute(
            "UPDATE chunks SET vectors=?, crc=? "
            "WHERE dataset=? AND shard=? AND partkey=? AND chunk_id=?",
            (blob, crc, dataset, shard, pk, cid))
        conn.commit()
        return bytes(pk), int(cid)

    # ------------------------------------------- staged (in-memory) chunks

    def corrupt_staged_chunk(self, partition, chunk_index: Optional[int] = None,
                             vector: Optional[int] = None,
                             mode: str = "flip") -> int:
        """Corrupt a frozen chunk's encoded vector ON the live partition
        object — the stand-in for corruption of HBM-resident staging
        buffers (encoded chunks awaiting device-grid staging or flush).

        ``mode``: ``"flip"`` (one random bit — may or may not break the
        decode, exactly like real bit rot), ``"wire"`` (invalid wire-type
        byte: decode MUST fail — deterministic tests), or ``"truncate"``.
        Returns the victim chunk_id."""
        if not partition.chunks:
            raise LookupError("partition has no frozen chunks")
        if chunk_index is None:
            chunk_index = self.rng.randrange(len(partition.chunks))
        cs = partition.chunks[chunk_index]
        vecs = list(cs.vectors)
        if vector is None:
            vector = self.rng.randrange(len(vecs))
        raw = bytes(vecs[vector])
        if mode == "flip":
            corrupted, _ = self.flip_byte(raw)
        elif mode == "wire":
            corrupted = bytes([0xEE]) + raw[1:]
        elif mode == "truncate":
            corrupted = self.truncate(raw)
        else:
            raise ValueError(f"unknown mode {mode!r}")
        vecs[vector] = corrupted
        cs.vectors = vecs
        # the decoded cache may hold the clean decode: drop it so the
        # corruption is actually exercised on the next read
        partition._decoded.pop(cs.info.chunk_id, None)
        return int(cs.info.chunk_id)
