"""Device-discipline rules for the jit/Pallas layer (ISSUE 10 pillar 3).

The repo's most failure-prone surface — the device layer — had zero
static coverage: PR 9 detects recompile storms at RUNTIME, and PR 8's
kernels rely on hand-checked VMEM layout arithmetic.  Four rules hold
the "compile the whole program" discipline statically:

- ``host-sync``: a host synchronization — ``np.asarray``/``np.array``,
  ``float(...)``, ``.item()``, ``jax.device_get``,
  ``.block_until_ready()`` — applied to the RESULT of a
  devicewatch-jit program inside the serving path (``query/``,
  ``memstore/devicestore.py``, ``parallel/``, ``ops/``) without a
  ``# host-sync-ok: <reason>`` annotation.  Every such readback stalls
  the device pipeline for a host round trip; the serving path earns
  exactly the readbacks it declares.  Detection is dataflow-based
  (taint from jit-program call results), so the hundreds of
  ``np.asarray`` calls on host data never fire.
- ``host-sync-annotation``: a ``# host-sync-ok:`` comment with no
  reason, or one sitting on a line with no detected host sync — stale
  annotations must not rot silently (the stale-suppression principle).
- ``recompile-hazard``: a devicewatch-jit call site passing a
  shape-deriving Python scalar (``len(...)``) or an f-string-valued
  argument that the entry point does not declare in
  ``static_argnames`` — the static complement of PR 9's runtime
  recompile-storm detector: each distinct value traces a new program.
- ``vmem-budget``: a ``pallas_call`` whose BlockSpec/scratch shapes
  resolve to constants and whose per-grid-step block footprint exceeds
  the VMEM budget (default 16 MiB — the per-core VMEM size; override
  with ``--vmem-budget-mib``).  Unresolvable dims are skipped, so the
  computed footprint is a lower bound: the rule under-counts, it never
  false-positives.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Optional

from . import callgraph
from .engine import Finding, rule

_HOST_SYNC_OK_RE = re.compile(r"#\s*host-sync-ok:(.*)$")

#: serving-path modules the host-sync rule covers
_SERVING_PREFIXES = ("filodb_tpu/query/", "filodb_tpu/parallel/",
                     "filodb_tpu/ops/")
_SERVING_FILES = ("filodb_tpu/memstore/devicestore.py",)

#: per-core VMEM (pallas guide: ~16 MB/core); --vmem-budget-mib overrides
DEFAULT_VMEM_BUDGET_BYTES = 16 * 2 ** 20
VMEM_BUDGET_BYTES = DEFAULT_VMEM_BUDGET_BYTES

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
}


def _in_serving_path(rel: str) -> bool:
    return rel.startswith(_SERVING_PREFIXES) or rel in _SERVING_FILES


# ---------------------------------------------------------------------------
# jit entry-point discovery (shared per-run context)
# ---------------------------------------------------------------------------


def _is_jit_marker(expr) -> bool:
    """devicewatch.jit / jax.jit / bare jit, as a decorator target or a
    callable being invoked."""
    if isinstance(expr, ast.Attribute):
        return expr.attr == "jit" and isinstance(expr.value, ast.Name) \
            and expr.value.id in ("devicewatch", "jax")
    return isinstance(expr, ast.Name) and expr.id == "jit"


def _static_argnames(call: ast.Call) -> frozenset:
    for kw in call.keywords:
        if kw.arg == "static_argnames" and isinstance(
                kw.value, (ast.Tuple, ast.List)):
            return frozenset(e.value for e in kw.value.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
        if kw.arg == "static_argnames" and isinstance(kw.value,
                                                      ast.Constant):
            return frozenset({kw.value.value})
    return frozenset()


def _jit_decoration(fn) -> Optional[frozenset]:
    """static_argnames if ``fn`` wears a jit decorator, else None."""
    for d in fn.decorator_list:
        if _is_jit_marker(d):
            return frozenset()
        if isinstance(d, ast.Call):
            if _is_jit_marker(d.func):
                return _static_argnames(d)
            # functools.partial(devicewatch.jit, static_argnames=...)
            f = d.func
            if isinstance(f, ast.Attribute) and f.attr == "partial" \
                    and d.args and _is_jit_marker(d.args[0]):
                return _static_argnames(d)
    return None


class _JitTable:
    """Project-wide index of jit entry points and jit factories.

    - ``entries[(rel, name)] = (FunctionDef, static_argnames)`` for
      TOP-LEVEL functions decorated with devicewatch.jit — the only
      ones reachable by the name resolution ``entry_for`` performs (a
      nested jit closure is not callable by bare name from elsewhere,
      and indexing it flat would misresolve unrelated same-named
      functions);
    - ``factories`` holds (rel, name) of top-level functions and class
      methods that BUILD jit programs (contain a jit call or a
      jit-decorated nested def — devicestore's fused programs are such
      closures — without being jit-decorated themselves): their
      results, and anything called through them
      (``_fused_progs()["grouped"](...)``), are jit programs too.
    """

    def __init__(self, project):
        self.entries: dict = {}
        self.factories: set = set()
        for m in project.modules:
            if m.tree is None:
                continue
            top = [n for n in m.tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
            methods = [f for cls in m.tree.body
                       if isinstance(cls, ast.ClassDef)
                       for f in cls.body
                       if isinstance(f, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            for fn in top:
                static = _jit_decoration(fn)
                if static is not None:
                    self.entries[(m.rel, fn.name)] = (fn, static)
            for fn in top + methods:
                if _jit_decoration(fn) is not None:
                    continue
                for n in ast.walk(fn):
                    if (isinstance(n, ast.Call)
                            and _is_jit_marker(n.func)) \
                            or (isinstance(n, (ast.FunctionDef,
                                               ast.AsyncFunctionDef))
                                and n is not fn
                                and _jit_decoration(n) is not None):
                        self.factories.add((m.rel, fn.name))
                        break

    def entry_for(self, call: ast.Call, rel: str, graph) -> Optional[tuple]:
        """(FunctionDef, static_argnames) when ``call`` invokes a known
        jit entry point by name (local, from-import, or module alias)."""
        f = call.func
        if isinstance(f, ast.Name):
            hit = self.entries.get((rel, f.id))
            if hit is not None:
                return hit
            tgt = graph.sym_aliases.get(rel, {}).get(f.id)
            if tgt is not None:
                return self.entries.get(tgt)
            return None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            mod = graph.mod_aliases.get(rel, {}).get(f.value.id)
            if mod is not None:
                return self.entries.get((mod, f.attr))
        return None

    def is_factory_call(self, call: ast.Call, rel: str, graph) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            if (rel, f.id) in self.factories:
                return True
            tgt = graph.sym_aliases.get(rel, {}).get(f.id)
            return tgt is not None and tgt in self.factories
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self" and (rel, f.attr) in self.factories:
                return True
            mod = graph.mod_aliases.get(rel, {}).get(f.value.id)
            return mod is not None and (mod, f.attr) in self.factories
        return False


def _jit_table(project) -> _JitTable:
    shared = getattr(project, "shared", None)
    if shared is None:
        return _JitTable(project)
    return shared("jit_table", _JitTable)


# ---------------------------------------------------------------------------
# host-sync — taint device results, flag undeclared readbacks
# ---------------------------------------------------------------------------


def _host_sync_kind(call: ast.Call) -> Optional[tuple]:
    """(label, synced expr) when ``call`` is a host synchronization."""
    f = call.func
    if isinstance(f, ast.Attribute):
        recv = f.value
        if f.attr in ("asarray", "array") and isinstance(recv, ast.Name) \
                and recv.id in ("np", "numpy") and call.args:
            return f"np.{f.attr}()", call.args[0]
        if f.attr == "device_get" and isinstance(recv, ast.Name) \
                and recv.id == "jax" and call.args:
            return "jax.device_get()", call.args[0]
        if f.attr == "item" and not call.args:
            return ".item()", recv
        if f.attr == "block_until_ready" and not call.args:
            return ".block_until_ready()", recv
    elif isinstance(f, ast.Name) and f.id == "float" and call.args:
        return "float()", call.args[0]
    return None


def _root_name(expr) -> Optional[str]:
    """The Name at the root of a Name/Subscript/Attribute chain."""
    while isinstance(expr, (ast.Subscript, ast.Attribute, ast.Starred)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


class _TaintPass:
    """One forward pass over a function body: which local names hold
    jit-program results (device values) / jit programs themselves."""

    def __init__(self, module, table, graph):
        self.m, self.table, self.graph = module, table, graph
        self.tainted: set = set()
        self.progs: set = set()

    def is_program_call(self, call: ast.Call) -> bool:
        if self.table.entry_for(call, self.m.rel, self.graph) is not None:
            return True
        f = call.func
        if isinstance(f, ast.Name) and f.id in self.progs:
            return True
        # _fused_progs()["grouped"](...) / factory(...)(...): any
        # factory call inside the callee expression makes this a
        # program invocation
        for n in ast.walk(f):
            if isinstance(n, ast.Call) \
                    and self.table.is_factory_call(n, self.m.rel,
                                                   self.graph):
                return True
        return False

    def value_taints(self, expr) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and self.is_program_call(n):
                return True
        root = _root_name(expr)
        return root is not None and root in self.tainted

    def note_assign(self, targets, value) -> None:
        names = []
        for t in targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                names.extend(e.id for e in t.elts
                             if isinstance(e, ast.Name))
        if not names:
            return
        if isinstance(value, ast.Call) \
                and self.table.is_factory_call(value, self.m.rel,
                                               self.graph):
            self.progs.update(names)
        elif self.value_taints(value):
            self.tainted.update(names)
        elif isinstance(value, ast.Name) and value.id in self.progs:
            self.progs.update(names)


def _own_expr_calls(stmt) -> list:
    """Call nodes in ``stmt``'s own expression subtrees — child
    statements report their own (no double-visit through parents)."""
    out = []
    stack = [c for c in ast.iter_child_nodes(stmt)
             if isinstance(c, ast.expr)]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            out.append(n)
        stack.extend(c for c in ast.iter_child_nodes(n)
                     if not isinstance(c, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)))
    return out


def _own_statements(fn) -> list:
    """Statements of ``fn`` in source order, nested defs excluded
    (each FunctionDef is analyzed on its own)."""
    out = []
    stack = list(reversed(fn.body))
    while stack:
        st = stack.pop()
        out.append(st)
        kids = []
        for c in ast.iter_child_nodes(st):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(c, ast.stmt):
                kids.append(c)
            elif isinstance(c, (ast.excepthandler,)):
                kids.extend(s for s in c.body)
            elif hasattr(c, "body") and isinstance(getattr(c, "body"),
                                                   list):
                kids.extend(s for s in c.body
                            if isinstance(s, ast.stmt))
        stack.extend(reversed(kids))
    return out


def _annotations(module) -> dict:
    """{line: reason-or-None} for ``# host-sync-ok`` comments — real
    COMMENT tokens only (a docstring quoting the syntax is not an
    annotation), the same discipline as the engine's suppression
    scanner and # lock-order:."""
    out: dict = {}
    if "host-sync-ok" not in module.src:
        return out
    try:
        toks = tokenize.generate_tokens(io.StringIO(module.src).readline)
        comments = [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for i, text in comments:
        m = _HOST_SYNC_OK_RE.search(text)
        if m is not None:
            reason = m.group(1).strip().lstrip("—-: ").strip()
            out[i] = reason or None
    return out


def _scan_host_syncs(project):
    """Shared worker for host-sync + host-sync-annotation: findings per
    rule, computed in one pass."""

    def _build(p):
        graph = callgraph.build(p)
        table = _jit_table(p)
        syncs, dangling = [], []
        for m in p.modules:
            if m.tree is None or not _in_serving_path(m.rel):
                continue
            notes = _annotations(m)
            used_lines: set = set()
            for fn in m.nodes:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                tp = _TaintPass(m, table, graph)
                for st in _own_statements(fn):
                    for n in _own_expr_calls(st):
                        kind = _host_sync_kind(n)
                        if kind is None:
                            continue
                        label, target = kind
                        if not tp.value_taints(target):
                            continue
                        used_lines.add(n.lineno)
                        if notes.get(n.lineno):
                            continue       # declared, with a reason
                        syncs.append(Finding(
                            "host-sync", m.rel, n.lineno,
                            f"{label} on the result of a devicewatch-"
                            f"jit program in the serving path — this "
                            f"readback stalls the device pipeline for "
                            f"a host round trip and silently demotes "
                            f"the fast path; batch it, keep the value "
                            f"on device, or declare it "
                            f"'# host-sync-ok: <reason>'"))
                    if isinstance(st, ast.Assign):
                        tp.note_assign(st.targets, st.value)
                    elif isinstance(st, ast.AnnAssign) \
                            and st.value is not None:
                        tp.note_assign([st.target], st.value)
            for line, reason in notes.items():
                if reason is None:
                    dangling.append(Finding(
                        "host-sync-annotation", m.rel, line,
                        "'# host-sync-ok' without a reason — append "
                        "': <why this readback is the design>'"))
                elif line not in used_lines:
                    dangling.append(Finding(
                        "host-sync-annotation", m.rel, line,
                        "'# host-sync-ok' on a line with no detected "
                        "host sync of a jit-program result — delete "
                        "it (stale annotations hide future "
                        "regressions)"))
        return syncs, dangling

    shared = getattr(project, "shared", None)
    return _build(project) if shared is None \
        else shared("host_sync_scan", _build)


@rule("host-sync", scope="project",
      doc="undeclared host syncs of jit results in the serving path")
def host_sync(project):
    return _scan_host_syncs(project)[0]


@rule("host-sync-annotation", scope="project",
      doc="# host-sync-ok annotations that are bare or stale")
def host_sync_annotation(project):
    return _scan_host_syncs(project)[1]


# ---------------------------------------------------------------------------
# recompile-hazard — per-call-varying traced args at jit call sites
# ---------------------------------------------------------------------------


def _contains_len_call(expr) -> bool:
    return any(isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
               and n.func.id == "len" for n in ast.walk(expr))


def _hazard(expr, varying: set) -> Optional[str]:
    if _contains_len_call(expr):
        return "a len(...)-derived Python scalar"
    if isinstance(expr, ast.JoinedStr):
        return "an f-string"
    if isinstance(expr, ast.Name) and expr.id in varying:
        return f"'{expr.id}' (bound to a len()/f-string value above)"
    return None


@rule("recompile-hazard", scope="project",
      doc="jit call sites passing varying values not declared static")
def recompile_hazard(project):
    graph = callgraph.build(project)
    table = _jit_table(project)
    findings = []
    for m in project.modules:
        if m.tree is None or not m.rel.startswith("filodb_tpu/"):
            continue
        for fn in m.nodes:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            varying: set = set()
            for st in _own_statements(fn):
                for n in _own_expr_calls(st):
                    hit = table.entry_for(n, m.rel, graph)
                    if hit is None:
                        continue
                    entry, static = hit
                    pos_names = [a.arg for a in entry.args.args]
                    for i, a in enumerate(n.args):
                        name = pos_names[i] if i < len(pos_names) \
                            else None
                        if name in static:
                            continue
                        why = _hazard(a, varying)
                        if why is not None:
                            findings.append(_hazard_finding(
                                m.rel, a.lineno, entry.name, name,
                                why))
                    for kw in n.keywords:
                        if kw.arg in static:
                            continue
                        why = _hazard(kw.value, varying)
                        if why is not None:
                            findings.append(_hazard_finding(
                                m.rel, kw.value.lineno, entry.name,
                                kw.arg, why))
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        if isinstance(t, ast.Name) and (
                                _contains_len_call(st.value)
                                or isinstance(st.value, ast.JoinedStr)):
                            varying.add(t.id)
    return findings


def _hazard_finding(rel, line, entry, argname, why) -> Finding:
    arg = f"argument {argname!r}" if argname else "a positional argument"
    return Finding(
        "recompile-hazard", rel, line,
        f"{entry}() is a jit entry point but {arg} receives {why} "
        f"without being declared in static_argnames — every distinct "
        f"value keys a fresh trace/compile (the recompile-storm shape "
        f"PR 9 detects at runtime); declare it static if its values "
        f"are bounded, or hoist it out of the traced signature")


# ---------------------------------------------------------------------------
# vmem-budget — pallas_call per-block byte footprint
# ---------------------------------------------------------------------------


def _const_env(module) -> dict:
    """{name: int} for names assigned EXACTLY one constant-int value
    anywhere in the module (module level or function-local)."""
    env: dict = {}
    poisoned: set = set()
    for n in module.nodes:
        if not isinstance(n, ast.Assign):
            continue
        for t in n.targets:
            if not isinstance(t, ast.Name):
                continue
            if isinstance(n.value, ast.Constant) \
                    and isinstance(n.value.value, int):
                if t.id in env and env[t.id] != n.value.value:
                    poisoned.add(t.id)
                env[t.id] = n.value.value
            else:
                poisoned.add(t.id)
    for name in poisoned:
        env.pop(name, None)
    return env


def _resolve_dim(expr, env: dict) -> Optional[int]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return expr.value
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = _resolve_dim(expr.operand, env)
        return None if v is None else -v
    if isinstance(expr, ast.BinOp):
        lo = _resolve_dim(expr.left, env)
        ro = _resolve_dim(expr.right, env)
        if lo is None or ro is None:
            return None
        try:
            if isinstance(expr.op, ast.Add):
                return lo + ro
            if isinstance(expr.op, ast.Sub):
                return lo - ro
            if isinstance(expr.op, ast.Mult):
                return lo * ro
            if isinstance(expr.op, ast.FloorDiv):
                return lo // ro
            if isinstance(expr.op, ast.Pow):
                return lo ** ro
        except (ZeroDivisionError, OverflowError):
            return None
    return None


def _dtype_bytes(expr) -> Optional[int]:
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        name = expr.value
    return _DTYPE_BYTES.get(name)


def _block_bytes(shape_expr, env, elem_bytes) -> Optional[int]:
    if not isinstance(shape_expr, (ast.Tuple, ast.List)):
        return None
    total = elem_bytes
    for dim in shape_expr.elts:
        v = _resolve_dim(dim, env)
        if v is None or v <= 0:
            return None
        total *= v
    return total


def _iter_specs(expr):
    """Flatten an in_specs/out_specs expression into BlockSpec calls."""
    if expr is None:
        return
    if isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            yield from _iter_specs(e)
    elif isinstance(expr, ast.Call):
        f = expr.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name == "BlockSpec":
            yield expr


def _out_dtype_bytes(call: ast.Call) -> int:
    """Element size from out_shape's ShapeDtypeStruct dtype; f32 when
    unresolvable (the repo's kernels are f32-dominant)."""
    for kw in call.keywords:
        if kw.arg != "out_shape":
            continue
        for n in ast.walk(kw.value):
            if isinstance(n, ast.Call):
                f = n.func
                nm = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if nm == "ShapeDtypeStruct" and len(n.args) >= 2:
                    b = _dtype_bytes(n.args[1])
                    if b is not None:
                        return b
    return 4


@rule("vmem-budget",
      doc="pallas_call block footprints exceeding the VMEM budget")
def vmem_budget(module):
    if "pallas_call" not in module.src:
        return []
    env = _const_env(module)
    findings = []
    for call in module.nodes:
        if not isinstance(call, ast.Call):
            continue
        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name != "pallas_call":
            continue
        elem = _out_dtype_bytes(call)
        total = 0
        parts = []
        for kw in call.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                for spec in _iter_specs(kw.value):
                    shape = spec.args[0] if spec.args else None
                    b = _block_bytes(shape, env, elem)
                    if b is not None:
                        total += b
                        parts.append(f"{kw.arg} block {b // 1024} KiB")
            elif kw.arg == "scratch_shapes":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Call) and n.args:
                        eb = _dtype_bytes(n.args[1]) \
                            if len(n.args) >= 2 else elem
                        b = _block_bytes(n.args[0], env, eb or elem)
                        if b is not None:
                            total += b
                            parts.append(
                                f"scratch {b // 1024} KiB")
        if total > VMEM_BUDGET_BYTES:
            findings.append(Finding(
                "vmem-budget", module.rel, call.lineno,
                f"pallas_call blocks resolve to {total / 2**20:.1f} "
                f"MiB of VMEM per grid step "
                f"({'; '.join(parts)}), over the "
                f"{VMEM_BUDGET_BYTES / 2**20:.0f} MiB budget — the "
                f"kernel will fail to fit at lowering (or spill); "
                f"shrink the BlockSpec tiles or raise "
                f"--vmem-budget-mib if this device has more VMEM"))
    return findings
