"""Lock-order deadlock detector (ISSUE 10 tentpole pillar 2).

PR 13's lock analyses see missing locks; they cannot see deadlocks.
This module builds the lock-ACQUISITION-ORDER graph: an edge A -> B
means some code path acquires B while holding A, either lexically
(nested ``with`` statements, including ``# holds-lock:`` / ``*_locked``
entry states) or through the whole-program call graph (holding A and
calling a function that — possibly transitively — takes B).  Two rules
report on the graph:

- ``lock-order-cycle``: a cycle A -> B -> ... -> A means two threads
  walking the edges in different orders can deadlock; every cycle is
  reported ONCE with the full acquisition chain of each edge.
- ``lock-order-inversion``: an acquisition edge that contradicts a
  declared ``# lock-order: <a> < <b>`` annotation (a before b), and
  declarations that bind to no lock the analysis knows (a typo'd
  annotation must not silently disarm the detector).

Lock identity is class-qualified — ``memstore.shard.TimeSeriesShard.
_dirty_lock`` — so same-named locks in different classes never collide.
Locks taken through receivers the analysis cannot type (``other._lock``)
contribute no edge: conservative, never false-positive.  The
``threading.Condition(self._lock)`` alias and the ``*_locked`` naming
convention are understood exactly as in locks.py.  Self-edges (re-
acquiring the lock you hold) are out of scope here — that is a
missing-``holds-lock`` bug, not an ordering bug.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Optional

from . import callgraph
from .engine import Finding, rule
from .locks import (_LockWalker, _class_lock_keys, _lock_aliases,
                    _method_held)

_LOCK_ORDER_RE = re.compile(r"#\s*lock-order:\s*(.+?)\s*$")

#: longest simple cycle the DFS enumerates; real deadlocks are almost
#: always 2-cycles, and the bound keeps the tree run inside budget
_MAX_CYCLE_LEN = 4


def _mod_dots(rel: str) -> str:
    d = rel[:-3] if rel.endswith(".py") else rel
    if d.endswith("/__init__"):
        d = d[: -len("/__init__")]
    if d.startswith("filodb_tpu/"):
        d = d[len("filodb_tpu/"):]
    return d.replace("/", ".")


class _Edge:
    """One observed A-held-while-acquiring-B site with its chain."""
    __slots__ = ("src", "dst", "rel", "line", "desc")

    def __init__(self, src, dst, rel, line, desc):
        self.src, self.dst = src, dst
        self.rel, self.line, self.desc = rel, line, desc


def _canon(raw: Optional[str], mod: str, cls: str,
           aliases: dict, class_locks: frozenset) -> Optional[str]:
    """Canonical project-wide lock key for a raw _lock_key string.

    ``self._x`` -> ``<mod>.<cls>._x``; a bare module-level name ->
    ``<mod>.<name>``; a bare ``holds-lock`` term naming one of the
    class's own locks is class-qualified.  Unresolvable receivers
    (``other._lock``) return None — no edge beats a wrong edge."""
    if raw is None:
        return None
    raw = aliases.get(raw, raw)
    if raw.startswith("self."):
        return f"{mod}.{cls}.{raw[5:]}" if cls else None
    if raw.startswith("?."):
        return None
    if "." in raw:          # some other receiver: cannot type it
        return None
    if cls and (f"self.{raw}" in class_locks
                or aliases.get(f"self.{raw}") is not None):
        return f"{mod}.{cls}.{raw}"
    return f"{mod}.{raw}"


def _decl_matches(decl: str, key: str) -> bool:
    """Does declaration name ``decl`` (terminal or dotted suffix) name
    canonical lock ``key``?"""
    return key == decl or key.endswith("." + decl)


def _lock_order_decls(module) -> list:
    """(line, [names...]) for each ``# lock-order: a < b [< c]`` comment
    (real COMMENT tokens only, same discipline as suppressions)."""
    if "lock-order" not in module.src:
        return []
    try:
        toks = tokenize.generate_tokens(io.StringIO(module.src).readline)
        comments = [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []
    out = []
    for line, text in comments:
        m = _LOCK_ORDER_RE.search(text)
        if m is None:
            continue
        names = [n.strip() for n in m.group(1).split("<")]
        out.append((line, names))
    return out


def _build_graph(project) -> tuple:
    """(edges {(src,dst): _Edge}, all_lock_keys set) over the project."""

    def _build(p):
        graph = callgraph.build(p)
        mods = {m.rel: m for m in p.modules}

        # pass 1: per-function direct acquisitions + call sites under
        # held locks, collected with one _LockWalker walk per method
        direct: dict = {}        # FuncKey -> {lock: (rel, line)}
        call_sites: list = []    # (caller key, call node, held canon set)
        edges: dict = {}         # (src, dst) -> _Edge (first site wins)
        all_keys: set = set()

        def add_edge(src, dst, rel, line, desc):
            if src == dst:
                return
            all_keys.update((src, dst))
            if (src, dst) not in edges:
                edges[(src, dst)] = _Edge(src, dst, rel, line, desc)

        for m in p.modules:
            if m.tree is None:
                continue
            mod = _mod_dots(m.rel)
            for node in m.tree.body:
                items = [("", node)] if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)) else \
                    [(node.name, f) for f in node.body
                     if isinstance(f, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))] \
                    if isinstance(node, ast.ClassDef) else []
                if isinstance(node, ast.ClassDef):
                    aliases = _lock_aliases(node)
                    class_locks = _class_lock_keys(node)
                else:
                    aliases, class_locks = {}, frozenset()
                for cls, fn in items:
                    key = (m.rel, cls, fn.name)
                    acquired = direct.setdefault(key, {})
                    held0 = _method_held(fn, m.lines)
                    if cls and fn.name.endswith("_locked"):
                        held0 = held0 | class_locks

                    def canon(raw, _c=cls):
                        return _canon(raw, mod, _c, aliases, class_locks)

                    def on_lock(raw, held_before, method, line,
                                _key=key, _m=m):
                        k = canon(raw)
                        if k is None:
                            return
                        acq = direct[_key]
                        if k not in acq:
                            acq[k] = (_m.rel, line)
                        for h_raw in held_before:
                            h = canon(h_raw)
                            if h is not None:
                                add_edge(
                                    h, k, _m.rel, line,
                                    f"{_disp_fn(_key)} takes {h} then "
                                    f"{k} at {_m.rel}:{line}")

                    def on_call(call, held, method, _key=key):
                        if held:
                            hs = {c for c in (canon(h) for h in held)
                                  if c is not None}
                            if hs:
                                call_sites.append((_key, call, hs))

                    w = _LockWalker(on_call=on_call, on_lock=on_lock)
                    w.walk_method(fn, frozenset(held0))

        # pass 2: propagate "locks this function acquires" to a
        # fixpoint over the whole-program call graph, keeping one
        # representative chain per (function, lock)
        summary: dict = {k: {lk: (site, [k])
                             for lk, site in v.items()}
                         for k, v in direct.items() if v}
        changed = True
        while changed:
            changed = False
            for key, callees in graph.edges.items():
                mine = summary.setdefault(key, {})
                for callee, _call in callees:
                    for lk, (site, chain) in summary.get(callee,
                                                         {}).items():
                        if lk not in mine:
                            mine[lk] = (site, [key] + chain)
                            changed = True

        # pass 3: call sites under held locks inherit the callee's
        # acquisitions as ordering edges
        for caller, call, held in call_sites:
            callee = graph.resolve_call(call, caller[0], caller[1])
            if callee is None:
                continue
            for lk, ((srel, sline), chain) in summary.get(callee,
                                                          {}).items():
                for h in held:
                    add_edge(
                        h, lk, caller[0], call.lineno,
                        f"{_disp_fn(caller)} holds {h} and calls "
                        f"{' -> '.join(_disp_fn(c) for c in chain)} "
                        f"which takes {lk} at {srel}:{sline}")
        # every acquired lock is declarable, not just the ones that
        # appear on ordering edges — a PROACTIVE lock-order declaration
        # over two never-yet-nested locks must not read as dangling
        for acq in direct.values():
            all_keys.update(acq.keys())
        return edges, all_keys

    shared = getattr(project, "shared", None)
    return _build(project) if shared is None \
        else shared("lockorder_graph", _build)


def _disp_fn(key) -> str:
    rel, cls, name = key
    stem = rel.rsplit("/", 1)[-1]
    stem = stem[:-3] if stem.endswith(".py") else stem
    return f"{stem}.{cls}.{name}" if cls else f"{stem}.{name}"


def _simple_cycles(edges: dict) -> list:
    """Simple cycles up to _MAX_CYCLE_LEN, each reported once (the
    lexicographically smallest lock key is the canonical start)."""
    adj: dict = {}
    for (src, dst) in edges:
        adj.setdefault(src, set()).add(dst)
    cycles = []
    for start in sorted(adj):
        stack = [(start, (start,))]
        while stack:
            cur, path = stack.pop()
            for nxt in sorted(adj.get(cur, ())):
                if nxt == start and len(path) > 1:
                    cycles.append(list(path))
                elif nxt > start and nxt not in path \
                        and len(path) < _MAX_CYCLE_LEN:
                    stack.append((nxt, path + (nxt,)))
    return cycles


@rule("lock-order-cycle", scope="project",
      doc="cyclic lock-acquisition orders (deadlock)")
def lock_order_cycle(project):
    edges, _keys = _build_graph(project)
    inverted = _inverted_edges(project, edges)
    findings = []
    for cyc in _simple_cycles(edges):
        cyc_edges = [edges[(cyc[i], cyc[(i + 1) % len(cyc)])]
                     for i in range(len(cyc))]
        if any((e.src, e.dst) in inverted for e in cyc_edges):
            continue           # the inversion finding already covers it
        site = min(cyc_edges, key=lambda e: (e.rel, e.line))
        ring = " -> ".join([*cyc, cyc[0]])
        chains = "; ".join(f"({i + 1}) {e.desc}"
                           for i, e in enumerate(cyc_edges))
        findings.append(Finding(
            "lock-order-cycle", site.rel, site.line,
            f"lock-order cycle {ring}: two threads taking these locks "
            f"in opposite orders deadlock. {chains}. Acquire in ONE "
            f"order everywhere, or declare the intended order with "
            f"'# lock-order: {cyc[0]} < {cyc[1]}' and fix the "
            f"violating side"))
    return findings


def _inverted_edges(project, edges) -> dict:
    """{(src,dst): (decl_line_info)} for edges contradicting a declared
    ordering (computed once, shared by both rules)."""

    def _build(p):
        out = {}
        for m in p.modules:
            for line, names in _lock_order_decls(m):
                for a, b in zip(names, names[1:]):
                    for (src, dst) in edges:
                        if _decl_matches(b, src) and _decl_matches(a, dst):
                            out[(src, dst)] = (m.rel, line, a, b)
        return out

    shared = getattr(project, "shared", None)
    return _build(project) if shared is None \
        else shared("lockorder_inversions", _build)


@rule("lock-order-inversion", scope="project",
      doc="acquisitions contradicting a declared # lock-order:")
def lock_order_inversion(project):
    edges, all_keys = _build_graph(project)
    findings = []
    for (src, dst), (drel, dline, a, b) in sorted(
            _inverted_edges(project, edges).items()):
        e = edges[(src, dst)]
        findings.append(Finding(
            "lock-order-inversion", e.rel, e.line,
            f"acquires {dst} while holding {src}, but {drel}:{dline} "
            f"declares '# lock-order: {a} < {b}': {e.desc}. Reorder "
            f"the acquisitions (or fix the declaration)"))
    # a declaration naming no known lock is itself an error — a typo'd
    # annotation must not silently disarm the detector
    for m in project.modules:
        for line, names in _lock_order_decls(m):
            if len(names) < 2 or any(not n for n in names):
                findings.append(Finding(
                    "lock-order-inversion", m.rel, line,
                    "unparseable '# lock-order:' — expected "
                    "'# lock-order: <a> < <b>'"))
                continue
            for n in names:
                if not any(_decl_matches(n, k) for k in all_keys):
                    findings.append(Finding(
                        "lock-order-inversion", m.rel, line,
                        f"'# lock-order:' names {n!r}, which matches no "
                        f"lock the analysis ever sees acquired — the "
                        f"declaration binds to nothing and orders "
                        f"nothing"))
    return findings
