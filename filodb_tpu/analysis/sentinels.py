"""The eight legacy sentinel lints, as registry rules.

These grew one per PR inside tests/test_sentinel_lint.py (760 lines of
ad-hoc AST walking); they now live in the engine so there is ONE
framework, ONE suppression mechanism (``# filolint: disable=`` replaces
the old ``# sentinel-ok:``), and ONE report.  The migration is
behavior-preserving: tests/test_sentinel_lint.py keeps the original
catch-tests, run through these rules.

Rules: decode-sentinel, timed-handler, interpret-coverage,
device-put-ledger, admission-routing, deadline-threading, metric-doc,
replica-routing, evaluator-workload, kernel-timer-coverage,
batch-admission-discipline.
"""

from __future__ import annotations

import ast
from typing import Optional

from .engine import Finding, rule

# ---------------------------------------------------------------------------
# decode-sentinel (PR 6): native decode -1/None sentinels must be checked
# ---------------------------------------------------------------------------

RAW_SENTINEL_FNS = {
    "np_unpack", "np_packed_end", "dd_decode", "xor_unpack",
    "ll_encode_batch", "dbl_encode_batch", "ll_decode_batch",
    "dbl_decode_batch", "page_decode_column", "influx_parse_batch",
    "gather_ranges", "head_hash128", "verify_heads",
}
ADAPTER_SENTINEL_FNS = {
    "page_decode": {"nb"},
    "page_decode_into": {"nb"},
    "gather": {"npr"},
    "head_hashes": {"npr"},
    "verify": {"npr"},
    "parse": {"npr", "nparse"},
}


def _receiver_name(func) -> tuple[Optional[str], Optional[str]]:
    if not isinstance(func, ast.Attribute):
        return None, None
    attr, v = func.attr, func.value
    if isinstance(v, ast.Name):
        return attr, v.id
    if isinstance(v, ast.Attribute):
        return attr, v.attr
    return attr, None


def _is_sentinel_call(node: ast.Call) -> bool:
    attr, recv = _receiver_name(node.func)
    if attr is None:
        return False
    if attr in RAW_SENTINEL_FNS and recv in ("_lib", "lib"):
        return True
    return attr in ADAPTER_SENTINEL_FNS and recv in ADAPTER_SENTINEL_FNS[attr]


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _guard_names(func_node) -> set:
    """Names whose value IS checked somewhere in the function."""
    used = set()
    for n in ast.walk(func_node):
        if isinstance(n, ast.Compare):
            used |= _names_in(n)
        elif isinstance(n, (ast.If, ast.While, ast.IfExp)):
            used |= _names_in(n.test)
        elif isinstance(n, ast.Assert):
            used |= _names_in(n.test)
        elif isinstance(n, ast.BoolOp):
            used |= _names_in(n)
        elif isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
            used |= _names_in(n)
    return used


def _own_sentinel_calls(stmt) -> list:
    """Sentinel calls whose NEAREST enclosing statement is ``stmt`` —
    calls inside this statement's expression subtrees only (child
    statements report their own)."""
    out = []
    stack = [c for c in ast.iter_child_nodes(stmt)
             if isinstance(c, ast.expr)]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call) and _is_sentinel_call(n):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _check_sentinel_stmt(stmt, guards, rel, findings) -> None:
    for call in _own_sentinel_calls(stmt):
        if callable(guards):
            guards = guards()  # lazy: most functions have no sentinel calls
        attr, _ = _receiver_name(call.func)
        if isinstance(stmt, (ast.If, ast.While)) \
                and any(call is t or call in ast.walk(t)
                        for t in [stmt.test]):
            continue           # branched on directly
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            continue           # raising with it
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            names = set()
            for t in targets:
                names |= _names_in(t)
            if names & guards:
                continue       # assigned, then checked
            findings.append(Finding(
                "decode-sentinel", rel, call.lineno,
                f"result of {attr}() assigned to {sorted(names)} "
                f"but never compared/branched on in this function "
                f"— a -1 sentinel would be silently discarded"))
            continue
        if isinstance(stmt, ast.Return) and isinstance(
                stmt.value, (ast.IfExp, ast.Compare, ast.BoolOp)):
            continue           # returns a checked form
        findings.append(Finding(
            "decode-sentinel", rel, call.lineno,
            f"result of {attr}() is discarded without raising or "
            f"counting (bare use); check the sentinel"))
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue           # nested functions checked on their own
        if isinstance(child, ast.stmt):
            _check_sentinel_stmt(child, guards, rel, findings)
        elif isinstance(child, ast.excepthandler):
            for s in child.body:
                _check_sentinel_stmt(s, guards, rel, findings)


def _check_sentinel_function(func_node, rel, findings) -> None:
    guards_cache: list = []

    def guards():
        if not guards_cache:
            guards_cache.append(_guard_names(func_node))
        return guards_cache[0]

    for stmt in func_node.body:
        _check_sentinel_stmt(stmt, guards, rel, findings)


@rule("decode-sentinel",
      doc="native decode -1 sentinels silently discarded")
def decode_sentinel(module):
    findings: list[Finding] = []
    for fn in module.nodes:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_sentinel_function(fn, module.rel, findings)
    return findings


# ---------------------------------------------------------------------------
# timed-handler (PR 7): every _route-dispatched handler wears @_timed
# ---------------------------------------------------------------------------


def _route_handlers(tree, nodes=None):
    for cls in (nodes if nodes is not None else ast.walk(tree)):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name == "FiloHttpServer"):
            continue
        for fn in cls.body:
            if isinstance(fn, ast.FunctionDef) and fn.name == "_route":
                names = set()
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Return) \
                            or node.value is None:
                        continue
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Attribute) \
                                and isinstance(c.func.value, ast.Name) \
                                and c.func.value.id == "self":
                            names.add(c.func.attr)
                return cls, names
    return None, set()


@rule("timed-handler",
      doc="HTTP handlers dispatched from _route without @_timed")
def timed_handler(module):
    cls, names = _route_handlers(module.tree, module.nodes)
    if cls is None:
        return []
    findings = []
    for fn in cls.body:
        if not (isinstance(fn, ast.FunctionDef) and fn.name in names):
            continue
        decorated = False
        for d in fn.decorator_list:
            target = d.func if isinstance(d, ast.Call) else d
            if isinstance(target, ast.Name) and target.id == "_timed":
                decorated = True
        if not decorated:
            findings.append(Finding(
                "timed-handler", module.rel, fn.lineno,
                f"{fn.name} is dispatched from _route but not decorated "
                f"with @_timed — its latency never reaches the request "
                f"histogram"))
    return findings


# ---------------------------------------------------------------------------
# admission-routing (PR 10): only _exec materializes; _exec must admit
# ---------------------------------------------------------------------------


@rule("admission-routing",
      doc="query handlers bypassing the admission controller")
def admission_routing(module):
    findings = []
    for cls in module.nodes:
        if not (isinstance(cls, ast.ClassDef)
                and cls.name == "FiloHttpServer"):
            continue
        exec_has_admit = False
        exec_line = cls.lineno
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef):
                continue
            if fn.name == "_exec":
                exec_line = fn.lineno
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                if node.func.attr == "materialize" and fn.name != "_exec":
                    findings.append(Finding(
                        "admission-routing", module.rel, node.lineno,
                        f"{fn.name} materializes a plan outside _exec — "
                        f"queries must route through self._exec so "
                        f"admission control prices and admits them"))
                if fn.name == "_exec" and node.func.attr == "_admit" \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self":
                    exec_has_admit = True
        if not exec_has_admit:
            findings.append(Finding(
                "admission-routing", module.rel, exec_line,
                "_exec does not call self._admit — the admission front "
                "door is disconnected"))
    return findings


# ---------------------------------------------------------------------------
# deadline-threading (PR 10): urlopen bounded; dispatch timeouts derive
# from the remaining deadline budget
# ---------------------------------------------------------------------------

_DEADLINE_NAMES = ("deadline", "remaining", "budget")


@rule("deadline-threading",
      doc="remote dispatch that does not thread the deadline")
def deadline_threading(module):
    findings = []

    def names_in(expr) -> set:
        got = set()
        for n in ast.walk(expr):
            if isinstance(n, ast.Name):
                got.add(n.id)
            elif isinstance(n, ast.Attribute):
                got.add(n.attr)
        return got

    dispatch_nodes = set()
    for cls in module.nodes:
        if isinstance(cls, ast.ClassDef) and (
                cls.name.endswith("Dispatcher")
                or cls.name.endswith("Exec")):
            for n in ast.walk(cls):
                dispatch_nodes.add(id(n))

    for node in module.nodes:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, (ast.Attribute, ast.Name))):
            continue
        fname = node.func.attr if isinstance(node.func, ast.Attribute) \
            else node.func.id
        if fname == "get_object" \
                and not module.rel.endswith("coldstore/bucket.py"):
            # cold-bucket fetches (ISSUE 16): every call-site outside
            # the bucket implementations must bound the fetch with a
            # timeout derived from the remaining query/admin budget —
            # an unbounded (or constant) timeout lets one stalled
            # bucket pin a query worker past its deadline
            to_kw = next((k for k in node.keywords
                          if k.arg == "timeout_s"), None)
            if to_kw is None:
                findings.append(Finding(
                    "deadline-threading", module.rel, node.lineno,
                    "cold-bucket get_object without timeout_s= — a "
                    "stalled bucket would pin the worker forever "
                    "(doc/coldstore.md)"))
                continue
            refs = {n.lower() for n in names_in(to_kw.value)}
            if not any(dn in r for dn in _DEADLINE_NAMES for r in refs):
                findings.append(Finding(
                    "deadline-threading", module.rel, node.lineno,
                    "cold-bucket get_object whose timeout_s does not "
                    "thread the deadline — derive it from the remaining "
                    "budget (workload/deadline.py budget_timeout_s)"))
            continue
        if fname != "urlopen":
            continue
        timeout_kw = next((k for k in node.keywords
                           if k.arg == "timeout"), None)
        if timeout_kw is None:
            findings.append(Finding(
                "deadline-threading", module.rel, node.lineno,
                "urlopen without timeout= — an unbounded socket can pin "
                "a worker forever"))
            continue
        if id(node) in dispatch_nodes:
            refs = {n.lower() for n in names_in(timeout_kw.value)}
            if not any(dn in r for dn in _DEADLINE_NAMES for r in refs):
                findings.append(Finding(
                    "deadline-threading", module.rel, node.lineno,
                    "remote dispatch urlopen whose timeout does not "
                    "thread the deadline — derive it from the remaining "
                    "budget (workload/deadline.py budget_timeout_s)"))
    return findings


# ---------------------------------------------------------------------------
# evaluator-workload (PR 14): every internal evaluator that issues
# queries — a class that both mints a QueryContext and materializes a
# plan — must declare an explicit workload priority class and thread a
# deadline (the PR 10 deadline-threading discipline generalized beyond
# dispatchers: background evaluators share the serving fabric with user
# traffic and must be admission-schedulable, never ambient-priority)
# ---------------------------------------------------------------------------


@rule("evaluator-workload",
      doc="query-issuing evaluators without an explicit priority class "
          "or deadline")
def evaluator_workload(module):
    findings = []
    for cls in module.nodes:
        if not isinstance(cls, ast.ClassDef):
            continue
        materialize_line = None
        minted_line = None
        has_priority = False
        has_deadline = False
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else None
            if attr == "materialize" and materialize_line is None:
                materialize_line = node.lineno
            if attr == "mint":
                has_deadline = True
            if attr == "QueryContext":
                kws = {k.arg for k in node.keywords if k.arg is not None}
                if kws and minted_line is None:
                    # a keyword-built context MINTS query identity; the
                    # bare QueryContext() library fallbacks do not
                    minted_line = node.lineno
                if "priority" in kws:
                    has_priority = True
                if "deadline_ms" in kws:
                    has_deadline = True
        if materialize_line is None or minted_line is None:
            continue
        if not has_priority:
            findings.append(Finding(
                "evaluator-workload", module.rel, minted_line,
                f"{cls.name} mints a QueryContext and materializes "
                f"plans but never sets an explicit priority= class — "
                f"background evaluators must declare their workload "
                f"class (workload/admission.py priority shares)"))
        if not has_deadline:
            findings.append(Finding(
                "evaluator-workload", module.rel, minted_line,
                f"{cls.name} issues queries without a deadline — mint "
                f"one (workload.deadline.mint) or set deadline_ms so "
                f"admission and the scheduler can bound its work"))
    return findings


# ---------------------------------------------------------------------------
# device-put-ledger (PR 9): raw jax.device_put is invisible to the ledger
# ---------------------------------------------------------------------------

DEVICE_PUT_ALLOWLIST = ("utils/devicewatch.py",)


@rule("device-put-ledger",
      doc="raw jax.device_put not routed through the HBM ledger")
def device_put_ledger(module):
    if module.rel.endswith(DEVICE_PUT_ALLOWLIST):
        return []
    imported = set()
    for node in module.nodes:
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[0] == "jax":
            for alias in node.names:
                if alias.name == "device_put":
                    imported.add(alias.asname or alias.name)
    findings = []
    for node in module.nodes:
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        raw = (isinstance(f, ast.Attribute) and f.attr == "device_put"
               and isinstance(f.value, ast.Name) and f.value.id == "jax") \
            or (isinstance(f, ast.Name) and f.id in imported)
        if raw:
            findings.append(Finding(
                "device-put-ledger", module.rel, node.lineno,
                "raw jax.device_put — route it through devicewatch "
                "LEDGER.device_put(..., owner=..., fmt=...) so the "
                "bytes are attributed on the HBM residency ledger"))
    return findings


# ---------------------------------------------------------------------------
# replica-routing (PR 12): replica selection only via ReplicaSet.pick
# ---------------------------------------------------------------------------

_REPLICA_ENUMERATORS = {"replicas", "replica_nodes", "live_replicas"}
# "mesh_feed" (ISSUE 18): which resident copy feeds the fused mesh
# fabric is a replica choice like any other — it must route through
# ReplicaSet.pick, never enumerate replicas or hardcode the local node
_ROUTING_FN_HINTS = ("failover", "retarget", "hedge_alternate",
                     "mesh_feed")
_ROUTING_HELPERS = {"pick", "alternate"}


@rule("replica-routing",
      doc="ad-hoc replica selection outside ReplicaSet.pick")
def replica_routing(module):
    if module.rel.endswith("coordinator/replicas.py"):
        return []              # the policy's one home

    def called_attrs(node) -> set:
        got = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute):
                got.add(n.func.attr)
        return got

    findings = []
    for cls in module.nodes:
        if not (isinstance(cls, ast.ClassDef)
                and cls.name.endswith("Dispatcher")):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            bad = called_attrs(fn) & _REPLICA_ENUMERATORS
            if bad:
                findings.append(Finding(
                    "replica-routing", module.rel, fn.lineno,
                    f"{cls.name}.{fn.name} enumerates replicas ad hoc "
                    f"({sorted(bad)}) — dispatchers must select through "
                    f"ReplicaSet.pick()"))
    for fn in module.nodes:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not any(h in fn.name for h in _ROUTING_FN_HINTS):
            continue
        if not (called_attrs(fn) & _ROUTING_HELPERS):
            findings.append(Finding(
                "replica-routing", module.rel, fn.lineno,
                f"routing site {fn.name}() does not go through "
                f"ReplicaSet.pick()/alternate()"))
    return findings


# ---------------------------------------------------------------------------
# interpret-coverage (PR 8, project scope): every ops/ kernel entry
# point with an ``interpret`` param needs an interpret=True test
# ---------------------------------------------------------------------------


def kernel_entry_points(project) -> list[tuple[str, str, int]]:
    """(rel, fn name, line) of public ops/ functions taking interpret."""
    out = []
    for m in project.modules:
        if "/ops/" not in f"/{m.rel}" or m.tree is None:
            continue
        for fn in m.tree.body:
            if not isinstance(fn, ast.FunctionDef) \
                    or fn.name.startswith("_"):
                continue
            names = [a.arg for a in fn.args.args + fn.args.kwonlyargs]
            if "interpret" in names:
                out.append((m.rel, fn.name, fn.lineno))
    return out


@rule("interpret-coverage", scope="project",
      doc="Pallas kernel entry points with no interpret-mode test")
def interpret_coverage(project):
    findings = []
    # per-run shared cache: only test files that run interpret mode at
    # all are candidates, computed once instead of per entry point
    srcs = project.shared(
        "interpret_test_sources",
        lambda p: [s for s in p.test_sources if "interpret=True" in s])
    for rel, fn, line in kernel_entry_points(project):
        covered = any(fn + "(" in src for src in srcs)
        if not covered:
            findings.append(Finding(
                "interpret-coverage", rel, line,
                f"{fn} has no interpret-mode test (call it with "
                f"interpret=True in tests/) — CPU CI never exercises "
                f"the kernel body"))
    return findings


# ---------------------------------------------------------------------------
# kernel-timer-coverage (PR 20, project scope): every devicewatch.jit
# entry point passes a stable, UNIQUE program= name — the kernel
# timer's ledger (and the compile table, and the regression sentry's
# persisted baselines) all key on it; the __name__ fallback silently
# forks a program's ledger row on any rename, and two entry points
# sharing one name merge their EWMAs into nonsense
# ---------------------------------------------------------------------------

KERNEL_TIMER_ALLOWLIST = ("utils/devicewatch.py",)


def _devicewatch_jit_sites(module) -> list:
    """AST nodes wrapping a function with devicewatch.jit: direct
    calls (``devicewatch.jit(fn, ...)``), partial decorators
    (``functools.partial(devicewatch.jit, ...)``), and bare
    ``@devicewatch.jit`` decorators (which can carry no program=)."""
    def is_dw_jit(n) -> bool:
        return isinstance(n, ast.Attribute) and n.attr == "jit" \
            and isinstance(n.value, ast.Name) \
            and n.value.id == "devicewatch"

    sites = []
    for node in module.nodes:
        if isinstance(node, ast.Call):
            f = node.func
            if is_dw_jit(f):
                sites.append(node)
            elif ((isinstance(f, ast.Attribute) and f.attr == "partial")
                  or (isinstance(f, ast.Name) and f.id == "partial")) \
                    and node.args and is_dw_jit(node.args[0]):
                sites.append(node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                if is_dw_jit(d):
                    sites.append(d)   # bare @devicewatch.jit decorator
    return sites


# fabric modules (ISSUE 18): every compiled program in the mesh query
# fabric must wear a devicewatch.jit program= so the flight deck
# attributes its launches — a bare jax.jit there is an invisible launch
_FABRIC_MODULES = ("parallel/mesh.py", "parallel/meshgrid.py",
                   "parallel/meshexec.py")


def _bare_jit_sites(module) -> list:
    """``jax.jit(...)`` / ``@jax.jit`` / bare ``jit(...)`` call sites —
    compiled programs that bypass the devicewatch kernel timer."""
    def is_bare_jit(n) -> bool:
        if isinstance(n, ast.Attribute):
            return n.attr == "jit" and isinstance(n.value, ast.Name) \
                and n.value.id == "jax"
        return isinstance(n, ast.Name) and n.id == "jit"

    sites = []
    for node in module.nodes:
        if isinstance(node, ast.Call) and is_bare_jit(node.func):
            sites.append(node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sites.extend(d for d in node.decorator_list if is_bare_jit(d))
    return sites


@rule("kernel-timer-coverage", scope="project",
      doc="devicewatch.jit entry points without a stable unique "
          "program= name")
def kernel_timer_coverage(project):
    findings = []
    seen: dict[str, tuple[str, int]] = {}
    for m in project.modules:
        if m.tree is None or m.rel.endswith(KERNEL_TIMER_ALLOWLIST):
            continue
        if m.rel.endswith(_FABRIC_MODULES):
            for node in _bare_jit_sites(m):
                findings.append(Finding(
                    "kernel-timer-coverage", m.rel, node.lineno,
                    "bare jax.jit in a mesh-fabric module — every "
                    "fused fabric program must compile through "
                    "devicewatch.jit(program=...) so the flight deck "
                    "attributes its launches, bytes, and roofline "
                    "fraction"))
        for node in _devicewatch_jit_sites(m):
            kw = None
            if isinstance(node, ast.Call):
                kw = next((k for k in node.keywords
                           if k.arg == "program"), None)
            if kw is None:
                findings.append(Finding(
                    "kernel-timer-coverage", m.rel, node.lineno,
                    "devicewatch.jit without program= — the kernel "
                    "timer ledger, compile table, and persisted sentry "
                    "baselines key on the program name; the __name__ "
                    "fallback silently forks the ledger row on any "
                    "rename"))
                continue
            if not (isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)):
                findings.append(Finding(
                    "kernel-timer-coverage", m.rel, node.lineno,
                    "program= must be a string literal — a computed "
                    "name is not stable across runs, so the sentry's "
                    "persisted baseline never matches"))
                continue
            name = kw.value.value
            if name in seen:
                first = seen[name]
                findings.append(Finding(
                    "kernel-timer-coverage", m.rel, node.lineno,
                    f"duplicate program name {name!r} (first at "
                    f"{first[0]}:{first[1]}) — two entry points "
                    f"sharing one name merge their device-time ledger "
                    f"rows into nonsense"))
            else:
                seen[name] = (m.rel, node.lineno)
    return findings


# ---------------------------------------------------------------------------
# metric-doc (PR 11, project scope): every registered filodb_* family
# appears in doc/observability.md's metric table
# ---------------------------------------------------------------------------

_METRIC_CTORS = {"counter", "gauge", "histogram"}


def registered_metric_names(project) -> dict[str, tuple[str, int]]:
    """{metric name: (rel, first registration line)}."""
    names: dict[str, tuple[str, int]] = {}
    for m in project.modules:
        if m.tree is None:
            continue
        for node in m.nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_CTORS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if name.startswith("filodb_") and name not in names:
                names[name] = (m.rel, node.lineno)
    return names


def metric_documented(name: str, doc_text: str, doc_lines) -> bool:
    if name in doc_text:
        return True
    parts = name.split("_")
    for i in range(2, len(parts)):
        fam = "_".join(parts[:i]) + "_*"
        suffix = "_".join(parts[i:])
        # same-line (table-row) matching: a suffix shared with another
        # family must not mask the drift
        if any(fam in line and suffix in line for line in doc_lines):
            return True
    return False


@rule("metric-doc", scope="project",
      doc="registered filodb_* metrics missing from doc/observability.md")
def metric_doc(project):
    doc_text = project.doc_text
    doc_lines = project.doc_lines   # split once per run (shared cache)
    findings = []
    for name, (rel, line) in sorted(registered_metric_names(project).items()):
        if not metric_documented(name, doc_text, doc_lines):
            findings.append(Finding(
                "metric-doc", rel, line,
                f"{name}: not in doc/observability.md's metric table — "
                f"add the full name, or list its suffix on a "
                f"`filodb_<family>_*` row"))
    return findings


# ---------------------------------------------------------------------------
# admin-endpoint-documented (ISSUE 19, project scope): every /admin/...
# route the HTTP server dispatches must appear in doc/http_api.md — the
# metric-doc discipline applied to the operational API surface.  The
# router matches path segments with AST compares (parts[0] == "admin"
# and parts[1] == "<name>"), never "/admin/..." string literals, so the
# rule reads the same compares instead of grepping for slashes.
# ---------------------------------------------------------------------------

_ROUTER_REL = "filodb_tpu/http/server.py"


def routed_admin_endpoints(project) -> dict[str, tuple[str, int]]:
    """{"/admin/<name>": (rel, line)} for every admin dispatch arm in
    the HTTP server's router."""
    routes: dict[str, tuple[str, int]] = {}
    for m in project.modules:
        if m.tree is None or not m.rel.endswith(_ROUTER_REL.rsplit(
                "/", 1)[-1]) or "http" not in m.rel:
            continue
        for node in m.nodes:
            if not isinstance(node, ast.BoolOp) \
                    or not isinstance(node.op, ast.And):
                continue
            segs: dict[int, str] = {}
            for cmp_ in node.values:
                if not (isinstance(cmp_, ast.Compare)
                        and len(cmp_.ops) == 1
                        and isinstance(cmp_.ops[0], ast.Eq)
                        and isinstance(cmp_.left, ast.Subscript)
                        and isinstance(cmp_.left.value, ast.Name)
                        and cmp_.left.value.id == "parts"
                        and isinstance(cmp_.left.slice, ast.Constant)
                        and isinstance(cmp_.left.slice.value, int)
                        and len(cmp_.comparators) == 1
                        and isinstance(cmp_.comparators[0], ast.Constant)
                        and isinstance(cmp_.comparators[0].value, str)):
                    continue
                segs[cmp_.left.slice.value] = cmp_.comparators[0].value
            if segs.get(0) == "admin" and 1 in segs:
                route = f"/admin/{segs[1]}"
                if route not in routes:
                    routes[route] = (m.rel, node.lineno)
    return routes


@rule("admin-endpoint-documented", scope="project",
      doc="/admin/... routes the HTTP server dispatches but "
          "doc/http_api.md does not describe")
def admin_endpoint_documented(project):
    api_doc = project.api_doc_text
    findings = []
    for route, (rel, line) in sorted(routed_admin_endpoints(project).items()):
        if route not in api_doc:
            findings.append(Finding(
                "admin-endpoint-documented", rel, line,
                f"{route}: dispatched here but absent from "
                f"doc/http_api.md — document the endpoint (operators "
                f"discover the admin surface from that table, not "
                f"from the router)"))
    return findings


# ---------------------------------------------------------------------------
# batch-admission-discipline (ISSUE 20): any function that stacks and
# executes a query GROUP (the fleet batching tier's leader) must
# reference each member's admission permit and deadline-derived budget
# — no batched execution path may bypass the per-query admission
# window or the deadline tripwires.  Heuristic: a function that walks
# ``members`` AND invokes a batched launch (a call whose name contains
# "batch") is a group executor.
# ---------------------------------------------------------------------------

_BATCH_BUDGET_NAMES = ("remaining_ms", "deadline_ms")
_BATCH_EXEC_HINTS = ("launch", "exec", "run", "dispatch")


@rule("batch-admission-discipline",
      doc="batched group execution bypassing per-member admission "
          "permits or deadline budgets")
def batch_admission_discipline(module):
    findings = []
    for node in module.nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        refs = set()
        calls = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                refs.add(n.id)
            elif isinstance(n, ast.Attribute):
                refs.add(n.attr)
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute):
                    calls.add(f.attr)
                elif isinstance(f, ast.Name):
                    calls.add(f.id)
                # getattr(x, "admission_permit", ...) IS a reference
                if isinstance(f, ast.Name) and f.id == "getattr" \
                        and len(n.args) >= 2 \
                        and isinstance(n.args[1], ast.Constant) \
                        and isinstance(n.args[1].value, str):
                    refs.add(n.args[1].value)
        # a group executor walks ``members`` AND invokes a batched
        # launch (e.g. batch_launch / run_batched) — bookkeeping like
        # ledger.note_batch() does not count as execution
        if "members" not in refs or not any(
                "batch" in c and any(h in c for h in _BATCH_EXEC_HINTS)
                for c in calls):
            continue           # not a group executor
        if "admission_permit" not in refs:
            findings.append(Finding(
                "batch-admission-discipline", module.rel, node.lineno,
                f"{node.name} stacks/executes a query group without "
                f"referencing each member's admission_permit — a "
                f"batched member must never execute outside its own "
                f"admission window (doc/batching.md)"))
        if not any(b in refs for b in _BATCH_BUDGET_NAMES):
            findings.append(Finding(
                "batch-admission-discipline", module.rel, node.lineno,
                f"{node.name} stacks/executes a query group without "
                f"consulting the members' deadline budgets "
                f"(remaining_ms/deadline_ms) — an expired member must "
                f"be dropped from the stack, not launched "
                f"(doc/batching.md)"))
    return findings
