"""Finding reporters: text for humans, JSON for CI annotation."""

from __future__ import annotations

import collections
import json
from typing import Iterable

from .engine import RULES, Finding


def summarize(findings: Iterable[Finding], files: int = 0) -> dict:
    findings = list(findings)
    open_ = [f for f in findings if not f.suppressed]
    per_rule = collections.Counter(f.rule for f in open_)
    return {
        "files": files,
        "rules": len(RULES),
        "findings": len(open_),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "by_rule": dict(sorted(per_rule.items())),
    }


def render_json(findings: Iterable[Finding], files: int = 0) -> str:
    findings = list(findings)
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "summary": summarize(findings, files),
    }, indent=2)


def render_text(findings: Iterable[Finding], files: int = 0,
                show_suppressed: bool = False) -> str:
    findings = list(findings)
    lines = []
    for f in findings:
        if f.suppressed and not show_suppressed:
            continue
        tag = "suppressed" if f.suppressed else f.severity
        lines.append(f"{f.where()}: {tag}[{f.rule}] {f.message}")
        if f.suppressed and f.suppress_reason:
            lines.append(f"    reason: {f.suppress_reason}")
    s = summarize(findings, files)
    lines.append(f"filolint: {s['findings']} finding(s), "
                 f"{s['suppressed']} suppressed, {files} file(s), "
                 f"{s['rules']} rule(s)")
    return "\n".join(lines)


def _gh_escape(s: str) -> str:
    """GitHub workflow-command data escaping (%, CR, LF)."""
    return s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def render_github(findings: Iterable[Finding], files: int = 0) -> str:
    """One ``::error`` workflow annotation per unsuppressed finding —
    CI logs render these inline on the PR diff."""
    findings = list(findings)
    lines = []
    for f in findings:
        if f.suppressed:
            continue
        lines.append(f"::error file={f.path},line={f.line},"
                     f"title=filolint[{f.rule}]::{_gh_escape(f.message)}")
    s = summarize(findings, files)
    lines.append(f"::notice::filolint: {s['findings']} finding(s), "
                 f"{s['suppressed']} suppressed, {files} file(s), "
                 f"{s['rules']} rule(s)")
    return "\n".join(lines)


def render_rule_list() -> str:
    lines = []
    for name in sorted(RULES):
        r = RULES[name]
        doc = (r.doc or "").strip().splitlines()[0] if r.doc else ""
        lines.append(f"{name:24s} {r.scope:8s} {r.severity:8s} {doc}")
    return "\n".join(lines)
