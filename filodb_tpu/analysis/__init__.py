"""filolint: whole-repo static analysis for filodb_tpu.

An AST-walking lint engine (engine.py) with a rule registry, per-rule
severity, justification-required suppressions, text/JSON reporting, and
a CLI (``python -m filodb_tpu.analysis`` / the ``lint`` CLI verb).

Rule modules register themselves on import:

- locks.py      — lock-discipline, blocking-under-lock
- lifecycle.py  — resource-lifecycle
- sentinels.py  — the eight migrated legacy sentinel lints

See doc/analysis.md for the catalog, the ``# guarded-by:`` annotation
syntax, the suppression policy, and how to add a rule.
"""

from .engine import (  # noqa: F401
    META_RULES, RULES, Finding, Module, Project, Rule, rule,
    load_modules, run_paths, run_project, run_source, unsuppressed,
)
from . import lifecycle, locks, sentinels  # noqa: F401,E402 — register rules
from .report import (  # noqa: F401
    render_json, render_rule_list, render_text, summarize,
)
