"""filolint: whole-repo static analysis for filodb_tpu.

An AST-walking lint engine (engine.py) with a rule registry, per-rule
severity, justification-required suppressions, text/JSON reporting, and
a CLI (``python -m filodb_tpu.analysis`` / the ``lint`` CLI verb).

Rule modules register themselves on import:

- caches.py     — bounded-cache (serving-path memos need eviction)
- locks.py      — lock-discipline, blocking-under-lock (whole-program)
- lockorder.py  — lock-order-cycle, lock-order-inversion (deadlocks)
- device.py     — host-sync, host-sync-annotation, recompile-hazard,
                  vmem-budget (the jit/Pallas device discipline)
- lifecycle.py  — resource-lifecycle
- sentinels.py  — the migrated legacy sentinel lints

callgraph.py builds the cross-module call graph the whole-program
analyses share (once per run, via the Project.shared cache).

See doc/analysis.md for the catalog, the ``# guarded-by:`` /
``# lock-order:`` / ``# host-sync-ok:`` annotation syntax, the
suppression policy, and how to add a rule.
"""

from .engine import (  # noqa: F401
    META_RULES, RULES, Finding, Module, Project, Rule, rule,
    load_modules, run_paths, run_project, run_source, run_sources,
    unsuppressed,
)
from . import callgraph  # noqa: F401,E402 — whole-program call graph
from . import caches, device, lifecycle, lockorder, locks, sentinels, topology  # noqa: F401,E402 — register rules
from .report import (  # noqa: F401
    render_github, render_json, render_rule_list, render_text, summarize,
)
