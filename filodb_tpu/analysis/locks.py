"""Lock-discipline analyses.

Two rules built on one held-lock AST walk:

- ``lock-discipline``: instance attributes protected by a lock (declared
  with a ``# guarded-by: _lock`` comment on the attribute's assignment
  line, or inferred when two or more methods write the attribute under
  the same ``with self._lock:``) must not be touched outside that lock.
  This is the PR 11/12 review-bug class made structural: mapper
  mutations outside the manager lock (``_note_local_watermarks``),
  tenant-gauge rows mutated off the export lock
  (``_set_tenant_gauges``), stall-machine state racing the sampler.

- ``blocking-under-lock``: no blocking call — network I/O
  (``urlopen``/peer POST), ``Future.result``/``Thread.join`` waits,
  ``sleep``, subprocess spawns, host→device transfers — may execute
  while a lock is held, directly or through ANY reachable helper: the
  fixpoint runs over the whole-program call graph (callgraph.py), so a
  ``with self._lock:`` in gateway/server.py that reaches a blocking
  helper in utils/observability.py two modules away fires too.  This
  is the ReplicaFanout wedge lesson: one blocking peer POST under a
  held lock converted one slow node into a cluster-wide ingest stall.

Annotations:

- ``self._attr = ...  # guarded-by: _lock`` declares ``_attr`` guarded
  (reads AND writes outside the lock are flagged);
- ``def _sweep_locked(self):  # holds-lock: _lock`` declares the caller
  holds the lock — the body is analyzed as if inside ``with``.

Nested ``def``/``lambda`` bodies run LATER, not under the enclosing
``with`` — the walker resets the held set for them (a ``set_fn``
callback registered under a lock does not hold it when sampled).
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from . import callgraph
from .engine import Finding, rule

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
_HOLDS_LOCK_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z_][\w.]*)")
_ATTR_ASSIGN_RE = re.compile(r"self\.([A-Za-z_]\w*)\s*(?::[^=]+)?=[^=]")

# container-mutation method names: receiver is being written, not read
_MUTATORS = {
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "update",
}

_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "Popen"}


def _is_lockish(name: Optional[str]) -> bool:
    return bool(name) and ("lock" in name.lower()
                           or name.endswith(("_cv", "_cond"))
                           or name in ("cv", "cond"))


def _lock_key(expr) -> Optional[str]:
    """Canonical key for a with-item context expression that looks like
    a lock: ``self._lock``, ``_EXPORT_LOCK``, ``cls._lock``..."""
    if isinstance(expr, ast.Name):
        return expr.id if _is_lockish(expr.id) else None
    if isinstance(expr, ast.Attribute):
        if not _is_lockish(expr.attr):
            return None
        if isinstance(expr.value, ast.Name):
            return f"{expr.value.id}.{expr.attr}"
        return f"?.{expr.attr}"
    return None


def _terminal(name_or_attr) -> Optional[str]:
    if isinstance(name_or_attr, ast.Name):
        return name_or_attr.id
    if isinstance(name_or_attr, ast.Attribute):
        return name_or_attr.attr
    return None


def _key_matches(guard: str, held: frozenset) -> bool:
    """Does the held set satisfy guard ``_lock`` / ``self._lock``?
    Matched on the full key or the terminal lock name, so the
    annotation can spell either form."""
    term = guard.rsplit(".", 1)[-1]
    for h in held:
        if h == guard or h == f"self.{guard}" or h.rsplit(".", 1)[-1] == term:
            return True
    return False


class _Access:
    __slots__ = ("attr", "kind", "line", "method", "held")

    def __init__(self, attr, kind, line, method, held):
        self.attr, self.kind, self.line = attr, kind, line
        self.method, self.held = method, held


class _LockWalker:
    """Statement walker threading the set of held lock keys; invokes
    ``on_call(call, held)`` for every Call, ``on_access`` for every
    ``self.<attr>`` touch (lock-discipline only sets the latter), and
    ``on_lock(key, held_before, line)`` whenever a ``with`` statement
    acquires a lock (lockorder.py builds its acquisition graph from
    these events)."""

    def __init__(self, on_call=None, on_access=None, on_lock=None):
        self.on_call = on_call
        self.on_access = on_access
        self.on_lock = on_lock
        self._method = ""

    def walk_method(self, fn, initial_held=frozenset()):
        self._method = fn.name
        self._stmts(fn.body, frozenset(initial_held))

    # ------------------------------------------------------------ statements

    def _stmts(self, body, held):
        for st in body:
            self._stmt(st, held)

    def _stmt(self, st, held):
        if isinstance(st, (ast.With, ast.AsyncWith)):
            new = set(held)
            for item in st.items:
                self._expr(item.context_expr, held)
                k = _lock_key(item.context_expr)
                if k is not None:
                    if self.on_lock is not None:
                        self.on_lock(k, frozenset(new), self._method,
                                     item.context_expr.lineno)
                    new.add(k)
                if item.optional_vars is not None:
                    self._writes(item.optional_vars, held)
            self._stmts(st.body, frozenset(new))
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in st.decorator_list:
                self._expr(d, held)
            # the body runs when CALLED, not here: no lock is held
            self._stmts(st.body, frozenset())
        elif isinstance(st, ast.ClassDef):
            self._stmts(st.body, held)
        elif isinstance(st, (ast.If, ast.While)):
            self._expr(st.test, held)
            self._stmts(st.body, held)
            self._stmts(st.orelse, held)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._writes(st.target, held)
            self._expr(st.iter, held)
            self._stmts(st.body, held)
            self._stmts(st.orelse, held)
        elif isinstance(st, ast.Try):
            self._stmts(st.body, held)
            for h in st.handlers:
                self._stmts(h.body, held)
            self._stmts(st.orelse, held)
            self._stmts(st.finalbody, held)
        elif isinstance(st, ast.Assign):
            for t in st.targets:
                self._writes(t, held)
            self._expr(st.value, held)
        elif isinstance(st, ast.AugAssign):
            self._writes(st.target, held)
            self._expr(st.value, held)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._writes(st.target, held)
                self._expr(st.value, held)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                self._writes(t, held)
        elif isinstance(st, ast.Match):
            # match_case is neither stmt nor expr — walk it explicitly
            # or everything inside a match block goes dark
            self._expr(st.subject, held)
            for case in st.cases:
                if case.guard is not None:
                    self._expr(case.guard, held)
                self._stmts(case.body, held)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._expr(child, held)
                elif isinstance(child, ast.stmt):
                    self._stmt(child, held)

    # ----------------------------------------------------------- expressions

    def _writes(self, target, held):
        """Record write accesses for an assignment/del/loop target."""
        if isinstance(target, ast.Attribute):
            self._note(target, "w", held)
            # deep target like self.a.b = x also READS self.a
            self._expr(target.value, held)
        elif isinstance(target, ast.Subscript):
            base = target.value
            if isinstance(base, ast.Attribute):
                self._note(base, "w", held)     # self._d[k] = v mutates _d
            self._expr(base, held)
            self._expr(target.slice, held)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._writes(e, held)
        elif isinstance(target, ast.Starred):
            self._writes(target.value, held)
        elif isinstance(target, ast.Name):
            pass
        else:
            self._expr(target, held)

    def _expr(self, node, held):
        if node is None:
            return
        if isinstance(node, ast.Lambda):
            # runs later, without the lock
            self._expr(node.body, frozenset())
            return
        if isinstance(node, ast.Call):
            if self.on_call is not None:
                self.on_call(node, held, self._method)
            # a mutator method call writes its receiver: self._d.pop(k)
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS \
                    and isinstance(f.value, ast.Attribute):
                self._note(f.value, "w", held)
        if isinstance(node, ast.Attribute):
            self._note(node, "r", held)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, held)
            elif isinstance(child, ast.comprehension):
                self._writes(child.target, held)
                self._expr(child.iter, held)
                for c in child.ifs:
                    self._expr(c, held)

    def _note(self, attr_node, kind, held):
        if self.on_access is None:
            return
        if isinstance(attr_node.value, ast.Name) \
                and attr_node.value.id == "self":
            self.on_access(_Access(attr_node.attr, kind, attr_node.lineno,
                                   self._method, held))


def _method_held(fn, lines) -> frozenset:
    """Locks declared held on entry via ``# holds-lock:`` on the def line."""
    line = lines[fn.lineno - 1] if fn.lineno - 1 < len(lines) else ""
    m = _HOLDS_LOCK_RE.search(line)
    return frozenset({m.group(1)}) if m else frozenset()


def _class_lock_keys(cls) -> frozenset:
    """Every lock key this class takes with ``with``."""
    keys = set()
    for n in ast.walk(cls):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                k = _lock_key(item.context_expr)
                if k is not None:
                    keys.add(k)
    return frozenset(keys)


def _lock_aliases(cls) -> dict:
    """``self._cv = threading.Condition(self._lock)`` shares the
    underlying lock: holding the condition IS holding the lock."""
    out = {}
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)):
            continue
        if _terminal(n.value.func) != "Condition" or not n.value.args:
            continue
        src = _lock_key(n.value.args[0])
        if src is None:
            continue
        for t in n.targets:
            tk = _lock_key(t)
            if tk is not None:
                out[tk] = src
    return out


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

_CTOR_METHODS = {"__init__", "__new__", "__init_subclass__"}
_INFER_MIN_METHODS = 2   # locked-writer methods needed to infer a guard


def _class_annotations(cls, lines) -> tuple[dict, list]:
    """{attr: lock} from ``# guarded-by:`` comments inside the class,
    plus (line, text) of annotations that bound to nothing — a typo'd
    annotation must not silently disarm the race detector."""
    end = getattr(cls, "end_lineno", None) or max(
        (getattr(n, "end_lineno", cls.lineno) or cls.lineno
         for n in ast.walk(cls)), default=cls.lineno)
    out, dangling = {}, []
    for i in range(cls.lineno - 1, min(end, len(lines))):
        line = lines[i]
        g = _GUARDED_BY_RE.search(line)
        if g is None:
            continue
        a = _ATTR_ASSIGN_RE.search(line)
        if a is not None:
            out[a.group(1)] = g.group(1)
        else:
            dangling.append((i + 1, g.group(1)))
    return out, dangling


@rule("lock-discipline", doc="guarded attributes touched outside their lock")
def lock_discipline(module):
    findings = []
    for cls in module.nodes:
        if not isinstance(cls, ast.ClassDef):
            continue
        annotated, dangling = _class_annotations(cls, module.lines)
        for line, lock in dangling:
            findings.append(Finding(
                "lock-discipline", module.rel, line,
                f"'# guarded-by: {lock}' does not sit on a recognizable "
                f"'self.<attr> = ...' line — the annotation binds to "
                f"nothing and guards nothing"))
        class_locks = _class_lock_keys(cls)
        aliases = _lock_aliases(cls)
        accesses: list[_Access] = []
        walker = _LockWalker(on_access=accesses.append)
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                held = _method_held(fn, module.lines)
                if fn.name.endswith("_locked"):
                    # repo convention: a ``*_locked`` method documents
                    # that its caller already holds the class's lock
                    held = held | class_locks
                walker.walk_method(fn, held)
        if aliases:
            for a in accesses:
                a.held = frozenset(aliases.get(k, k) for k in a.held)

        # annotated attrs: any touch outside the declared lock is flagged
        seen = set()
        for a in accesses:
            lock = annotated.get(a.attr)
            if lock is None or a.method in _CTOR_METHODS:
                continue
            if _key_matches(lock, a.held):
                continue
            key = (a.line, a.attr)
            if key in seen:
                continue
            seen.add(key)
            verb = "written" if a.kind == "w" else "read"
            findings.append(Finding(
                "lock-discipline", module.rel, a.line,
                f"{cls.name}.{a.attr} is declared '# guarded-by: {lock}' "
                f"but {verb} here without holding it (method "
                f"{a.method}); take the lock, or mark the method "
                f"'# holds-lock: {lock}' if every caller already holds "
                f"it"))

        # inferred guards: >= N methods write the attr under one lock ->
        # a write outside that lock anywhere else in the class is the
        # PR 11/12 race shape
        by_attr: dict[str, dict[str, set]] = {}
        for a in accesses:
            if a.kind != "w" or a.attr in annotated \
                    or _is_lockish(a.attr):
                continue
            for lock in a.held:
                by_attr.setdefault(a.attr, {}).setdefault(
                    lock, set()).add(a.method)
        for a in accesses:
            if a.kind != "w" or a.attr in annotated \
                    or a.method in _CTOR_METHODS or _is_lockish(a.attr):
                continue
            for lock, methods in by_attr.get(a.attr, {}).items():
                locked_elsewhere = methods - {a.method}
                if len(methods) < _INFER_MIN_METHODS \
                        or not locked_elsewhere:
                    continue
                if _key_matches(lock, a.held):
                    continue
                key = (a.line, a.attr)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    "lock-discipline", module.rel, a.line,
                    f"{cls.name}.{a.attr} is written under {lock} in "
                    f"{sorted(methods)} but this write (method "
                    f"{a.method}) does not hold it — the unguarded-"
                    f"mutation race PRs 11/12 kept refixing; take the "
                    f"lock or annotate the attribute '# guarded-by:'"))
                break
    return findings


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


def _call_names(call) -> tuple[Optional[str], Optional[str]]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id, None
    if isinstance(f, ast.Attribute):
        return f.attr, _terminal(f.value)
    return None, None


def direct_blocking(call) -> Optional[str]:
    """Why this call blocks, or None.  The vocabulary of the ReplicaFanout
    / gauge-scrape incidents: network, waits, sleeps, spawns, device
    transfers."""
    name, recv = _call_names(call)
    if name is None:
        return None
    if name == "urlopen":
        return "urlopen() does network I/O"
    if name == "sleep" and recv in (None, "time"):
        return "sleep() parks the thread"
    if name in _SUBPROCESS_FNS and recv == "subprocess":
        return f"subprocess.{name}() spawns a process"
    if name == "Popen":
        return "Popen() spawns a process"
    if name == "communicate":
        return "communicate() waits on a subprocess"
    if name == "http_container_push":
        return "http_container_push() POSTs to a peer"
    if name == "result" and not call.args:
        return "Future.result() waits on another worker"
    if name == "join" and not call.args \
            and all(k.arg == "timeout" for k in call.keywords):
        return "join() waits on another thread"
    if name == "get" and any(k.arg in ("timeout", "block")
                             for k in call.keywords):
        return "blocking queue get()"
    if name == "block_until_ready":
        return "block_until_ready() waits on the device"
    if name in ("get_object", "put_object"):
        return f"{name}() does cold-bucket I/O"
    if name == "device_put":
        return "device_put() is a host->device transfer (may compile)"
    return None


def _hop_disp(key, from_rel: str) -> str:
    """Chain-hop display: bare name within one module, module-qualified
    (``observability.http_container_push``) when the chain crosses."""
    rel, _cls, name = key
    if rel == from_rel:
        return name
    stem = rel.rsplit("/", 1)[-1]
    return f"{stem[:-3] if stem.endswith('.py') else stem}.{name}"


def blocking_chains(project) -> dict:
    """{FuncKey: (reason, [FuncKey chain])} — the blocking fixpoint over
    the WHOLE-program call graph (callgraph.py), so a ``with`` in one
    module that reaches a blocking helper two modules away still fires.
    Chains are kept as key lists and rendered relative to the module
    where the lock is taken."""

    def _build(p):
        graph = callgraph.build(p)
        table: dict = {}
        for key, fn in graph.funcs.items():
            for call in callgraph.own_calls(fn):
                why = direct_blocking(call)
                if why is not None:
                    table[key] = (why, [key])
                    break
        changed = True
        while changed:
            changed = False
            for key, callees in graph.edges.items():
                if key in table:
                    continue
                for callee, _call in callees:
                    if callee in table:
                        why, chain = table[callee]
                        table[key] = (why, [key] + chain)
                        changed = True
                        break
        return table

    shared = getattr(project, "shared", None)
    return _build(project) if shared is None \
        else shared("blocking_chains", _build)


@rule("blocking-under-lock", scope="project",
      doc="blocking calls executed while a lock is held")
def blocking_under_lock(project):
    findings = []
    graph = callgraph.build(project)
    table = blocking_chains(project)

    def check_module(module):
        seen = set()

        def check(call, held, method, cls_name):
            if not held:
                return
            why = direct_blocking(call)
            chain = None
            if why is None:
                key = graph.resolve_call(call, module.rel, cls_name)
                if key is not None and key in table:
                    why, keys = table[key]
                    chain = " -> ".join(_hop_disp(k, module.rel)
                                        for k in keys)
            if why is None:
                return
            if call.lineno in seen:
                return
            seen.add(call.lineno)
            locks = ", ".join(sorted(held))
            via = f" (via {chain})" if chain and chain != method else ""
            findings.append(Finding(
                "blocking-under-lock", module.rel, call.lineno,
                f"{why}{via} while holding {locks} — one slow peer/"
                f"device turns every thread contending this lock into "
                f"a convoy (the ReplicaFanout ingest-stall shape); "
                f"move the call outside the critical section"))

        def walk_container(body, cls_name):
            for fn in body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    w = _LockWalker(on_call=lambda c, h, m, _cn=cls_name:
                                    check(c, h, m, _cn))
                    # held starts empty even for # holds-lock / *_locked
                    # methods: blocking is attributed to the statement
                    # that lexically TAKES the lock (the propagated call
                    # graph already reaches these helpers from there),
                    # so each convoy is reported once, not once per
                    # call-chain hop
                    w.walk_method(fn, frozenset())

        walk_container(module.tree.body, "")
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                walk_container(node.body, node.name)

    for module in project.modules:
        if module.tree is not None:
            check_module(module)
    return findings
