"""filolint CLI: ``python -m filodb_tpu.analysis [paths] [options]``.

Exit codes (also documented in doc/analysis.md):

- ``0`` — zero unsuppressed findings (CI gates on this);
- ``1`` — at least one unsuppressed finding;
- ``2`` — usage error: unknown rule name, or a ``--changed`` ref git
  cannot diff against.

``--changed <ref>`` reports only findings in files the working tree
changed vs ``ref`` — but the ANALYSIS still runs over the whole
package, so cross-module results (blocking chains, lock order, jit
tables) and stale-suppression verdicts are identical to a full run,
just filtered.  Also reachable as ``python -m filodb_tpu.cli lint``
(argv passes straight through — no hand-mirrored flags to drop).
"""

from __future__ import annotations

import argparse
import pathlib
import subprocess
import sys

from . import (RULES, Project, device, load_modules, render_github,
               render_json, render_rule_list, render_text, run_project,
               unsuppressed)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m filodb_tpu.analysis",
        description="filolint: whole-repo static analysis "
                    "(doc/analysis.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories (default: the filodb_tpu "
                        "package)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (same as --format=json)")
    p.add_argument("--format", choices=("text", "json", "github"),
                   default=None,
                   help="report format; 'github' prints ::error "
                        "workflow annotations for CI logs")
    p.add_argument("--changed", metavar="REF", default=None,
                   help="report only findings in files changed vs REF "
                        "(git diff + untracked); the analysis itself "
                        "still sees the whole package")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in the text report")
    p.add_argument("--vmem-budget-mib", type=float, default=None,
                   help="vmem-budget rule budget in MiB (default 16, "
                        "the per-core VMEM size)")
    return p


def _changed_rels(root: pathlib.Path, ref: str):
    """Paths changed vs ``ref`` (diff + untracked), RELATIVE TO
    ``root`` so they compare against Finding.path — git reports diff
    names relative to its toplevel, which need not be the package
    root (monorepo layouts), so rebase through ``--show-prefix``.
    Returns None when git cannot answer (bad ref, not a repo)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=root, capture_output=True, text=True)
        if diff.returncode != 0:
            print(f"--changed: git diff vs {ref!r} failed: "
                  f"{diff.stderr.strip()}", file=sys.stderr)
            return None
        prefix = subprocess.run(
            ["git", "rev-parse", "--show-prefix"],
            cwd=root, capture_output=True, text=True).stdout.strip()
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True)
    except FileNotFoundError:
        print("--changed: git not available", file=sys.stderr)
        return None
    names = set()
    for n in diff.stdout.splitlines():
        # toplevel-relative -> root-relative; changes outside the
        # package root's subtree are not lintable here
        if prefix:
            if n.startswith(prefix):
                names.add(n[len(prefix):])
        else:
            names.add(n)
    if untracked.returncode == 0:
        # ls-files --others is cwd-relative, and cwd is already root
        names |= set(untracked.stdout.splitlines())
    return {n for n in names if n.endswith(".py")}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        print(render_rule_list())
        return 0
    fmt = args.format or ("json" if args.json else "text")
    if args.vmem_budget_mib is not None:
        device.VMEM_BUDGET_BYTES = int(args.vmem_budget_mib * 2 ** 20)
    paths = args.paths or [pathlib.Path(__file__).resolve().parents[1]]
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
    modules, root = load_modules(paths)
    findings = run_project(Project(modules, root), rules)
    files = len(modules)
    if args.changed is not None:
        changed = _changed_rels(root, args.changed)
        if changed is None:
            return 2
        # whole-program analysis, changed-subset REPORT: findings (and
        # the stale-suppression meta verdicts, which are computed from
        # the full run exactly like a --rules subset) filter by path
        findings = [f for f in findings if f.path in changed]
        files = len({m.rel for m in modules} & changed)
    if fmt == "json":
        print(render_json(findings, files=files))
    elif fmt == "github":
        print(render_github(findings, files=files))
    else:
        print(render_text(findings, files=files,
                          show_suppressed=args.show_suppressed))
    return 1 if unsuppressed(findings) else 0


if __name__ == "__main__":
    raise SystemExit(main())
