"""filolint CLI: ``python -m filodb_tpu.analysis [paths] [--json]``.

Exit status 0 means zero unsuppressed findings; 1 means at least one
(CI gates on this — tests/test_analysis.py runs it over the whole
tree).  Also reachable as ``python -m filodb_tpu.cli lint``.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from . import (RULES, Project, load_modules, render_json,
               render_rule_list, render_text, run_project, unsuppressed)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m filodb_tpu.analysis",
        description="filolint: whole-repo static analysis "
                    "(doc/analysis.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories (default: the filodb_tpu "
                        "package)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in the text report")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        print(render_rule_list())
        return 0
    paths = args.paths or [pathlib.Path(__file__).resolve().parents[1]]
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
    modules, root = load_modules(paths)
    findings = run_project(Project(modules, root), rules)
    if args.json:
        print(render_json(findings, files=len(modules)))
    else:
        print(render_text(findings, files=len(modules),
                          show_suppressed=args.show_suppressed))
    return 1 if unsuppressed(findings) else 0


if __name__ == "__main__":
    raise SystemExit(main())
