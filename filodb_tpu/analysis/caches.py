"""bounded-cache: serving-path memo dicts must have an eviction bound.

The PR 11 gateway-memo stampede made structural: a dict used as a memo
on a serving-path module (guarded read + keyed write — the classic
``if k not in memo: memo[k] = compute()`` shape) grows with the key
space, and on a label-flood the memo IS the OOM.  Every such memo must
show an eviction bound somewhere in its owning scope — a ``pop`` /
``popitem`` / ``del`` / ``clear``, a ``len(memo)`` comparison driving
one, or handing the memo to an evict helper.  Justified unbounded maps
(key space structurally bounded, process-lifetime registries) carry a
``# filolint: disable=bounded-cache — <reason>`` on the write line.

Detection is deliberately narrow: an attribute/module-global that is
(a) initialized as a dict/OrderedDict, (b) read through ``.get`` /
``in`` / subscript AND keyed-written in the SAME function.  Plain
accumulators, flush queues, and registries that only ever write (or
only read) never match.
"""

from __future__ import annotations

import ast
from typing import Optional

from .engine import Finding, rule

_SERVING_PREFIXES = (
    "filodb_tpu/query/", "filodb_tpu/http/", "filodb_tpu/gateway/",
    "filodb_tpu/coordinator/", "filodb_tpu/memstore/",
    "filodb_tpu/parallel/", "filodb_tpu/rollup/", "filodb_tpu/rules/",
)

_DICT_CTORS = {"dict", "OrderedDict", "defaultdict"}
_EVICT_METHODS = {"pop", "popitem", "clear"}


def _dict_init(value: ast.AST) -> bool:
    if isinstance(value, ast.Dict) and not value.keys:
        return True
    if isinstance(value, ast.Call) and not value.args:
        f = value.func
        name = f.id if isinstance(f, ast.Name) else \
            f.attr if isinstance(f, ast.Attribute) else None
        return name in _DICT_CTORS
    return False


def _target_name(node: ast.AST) -> Optional[str]:
    """'self._x' -> '_x' (attribute memo), bare NAME -> 'NAME' (module
    global); anything else -> None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _collect_dicts(scope_body: list, in_class: bool) -> dict[str, int]:
    """Memo candidates initialized as empty dicts: name -> def line."""
    out: dict[str, int] = {}
    stmts = list(scope_body)
    if in_class:
        stmts = [s for fn in scope_body
                 if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                 and fn.name == "__init__" for s in ast.walk(fn)]
    for s in stmts:
        targets = []
        if isinstance(s, ast.Assign):
            targets, value = s.targets, s.value
        elif isinstance(s, ast.AnnAssign) and s.value is not None:
            targets, value = [s.target], s.value
        else:
            continue
        if not _dict_init(value):
            continue
        for t in targets:
            name = _target_name(t)
            if name is not None:
                out[name] = s.lineno
    return out


def _function_memo_uses(fn: ast.AST, names: set[str]) -> dict[str, int]:
    """Names both guard-read AND keyed-written inside ``fn`` -> write
    line (the stampede memo shape)."""
    reads: set[str] = set()
    writes: dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr in ("get", "setdefault"):
            name = _target_name(node.func.value)
            if name in names:
                reads.add(name)
                if node.func.attr == "setdefault":
                    writes.setdefault(name, node.lineno)
        elif isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            for cmp in node.comparators:
                name = _target_name(cmp)
                if name in names:
                    reads.add(name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = _target_name(t.value)
                    if name in names:
                        writes.setdefault(name, node.lineno)
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx,
                                                            ast.Load):
            name = _target_name(node.value)
            if name in names:
                reads.add(name)
    return {n: ln for n, ln in writes.items() if n in reads}


def _scope_bounds(scope: ast.AST, names: set[str]) -> set[str]:
    """Names with an eviction-bound signal anywhere in ``scope``."""
    bounded: set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _EVICT_METHODS:
                name = _target_name(node.func.value)
                if name in names:
                    bounded.add(name)
            # handing the memo to an evict/prune helper counts
            # (gateway evict_memo_half shape)
            fname = node.func.attr if isinstance(node.func, ast.Attribute) \
                else node.func.id if isinstance(node.func, ast.Name) else ""
            if "evict" in fname or "prune" in fname or "trim" in fname:
                for a in node.args:
                    name = _target_name(a)
                    if name in names:
                        bounded.add(name)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = _target_name(t.value)
                    if name in names:
                        bounded.add(name)
                else:
                    name = _target_name(t)
                    if name in names:
                        bounded.add(name)
        elif isinstance(node, ast.Compare):
            # a len(memo) comparison is a bound check driving eviction
            for side in [node.left] + list(node.comparators):
                if isinstance(side, ast.Call) \
                        and isinstance(side.func, ast.Name) \
                        and side.func.id == "len" and side.args:
                    name = _target_name(side.args[0])
                    if name in names:
                        bounded.add(name)
    return bounded


def _check_scope(module, scope: ast.AST, body: list, in_class: bool,
                 findings: list) -> None:
    dicts = _collect_dicts(body, in_class)
    if not dicts:
        return
    names = set(dicts)
    bounded = _scope_bounds(scope, names)
    where = f"class {scope.name}" if in_class else "module scope"
    seen: set[str] = set()
    fns = [n for n in ast.walk(scope)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in fns:
        if in_class and fn.name == "__init__":
            continue
        for name, line in _function_memo_uses(fn, names).items():
            if name in bounded or name in seen:
                continue
            seen.add(name)
            findings.append(Finding(
                "bounded-cache", module.rel, line,
                f"{where}: {name!r} is a memo (guarded read + keyed "
                f"write in {fn.name}) with no eviction bound in scope — "
                f"on a serving path an unbounded memo grows with the "
                f"key space (the PR 11 gateway-memo stampede); add a "
                f"pop/clear/len-bound, or annotate the justified map"))


@rule("bounded-cache",
      doc="serving-path memo dicts without an eviction bound")
def bounded_cache(module):
    if not module.rel.startswith(_SERVING_PREFIXES) or module.tree is None:
        return []
    findings: list = []
    _check_scope(module, module.tree, module.tree.body, False, findings)
    for cls in module.nodes:
        if isinstance(cls, ast.ClassDef):
            _check_scope(module, cls, cls.body, True, findings)
    return findings
