"""topology-generation: shard-keyed serving memos must survive splits.

Elastic resharding (ISSUE 13, coordinator/split.py) doubles a live
dataset's shard count by swapping the ShardMapper's Topology — and
every serving-path structure that BAKED shard assignments into a memo
(the gateway's series->shard memo and replayable group plans, dispatch
staging memos, result-cache entries keyed on a shard layout) keeps
routing at the retired topology forever unless it revalidates.  The
mapper exposes exactly one cheap validity signal for this:
``topology_generation`` (monotone, bumped on every topology
transition), also folded into ``routing_token()``.

This rule fires on a class that

  (a) computes shard routing — calls ``.ingestion_shard(...)`` /
      ``.query_shards(...)`` or reads ``.num_shards`` — AND
  (b) keeps a memo/plan/cache attribute (name contains ``memo``,
      ``plan``, or ``cache``) — AND
  (c) never references ``topology_generation`` / ``topology`` /
      ``routing_token`` anywhere in its body.

A class that cannot observe a topology bump but caches per-shard
decisions is exactly the post-split "samples keep publishing to the
retired parent" regression the ISSUE 13 satellite fixed.  Structurally
safe caches (rebuilt per batch, keyed by something topology-free) carry
``# filolint: disable=topology-generation — <reason>`` on the reported
line.
"""

from __future__ import annotations

import ast

from .engine import Finding, rule

_SERVING_PREFIXES = (
    "filodb_tpu/query/", "filodb_tpu/http/", "filodb_tpu/gateway/",
    "filodb_tpu/coordinator/", "filodb_tpu/memstore/",
    "filodb_tpu/parallel/", "filodb_tpu/rollup/", "filodb_tpu/ingest/",
)

_ROUTING_CALLS = {"ingestion_shard", "query_shards"}
_MEMO_MARKERS = ("memo", "plan", "cache")
_VALIDATORS = {"topology_generation", "topology", "_topologies",
               "routing_token", "routing_token_fn"}


def _memo_attr_line(cls: ast.ClassDef) -> tuple:
    """(attr name, line) of the first self.<memo-ish> assignment."""
    for node in ast.walk(cls):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            # unwrap subscript writes: self._memo[k] = v
            if isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self" \
                    and any(m in t.attr.lower() for m in _MEMO_MARKERS):
                return t.attr, node.lineno
    return None, 0


def _routes_shards(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _ROUTING_CALLS:
            return True
        if isinstance(node, ast.Attribute) and node.attr == "num_shards":
            return True
    return False


def _validates_topology(cls: ast.ClassDef) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr in _VALIDATORS
               for n in ast.walk(cls))


@rule("topology-generation",
      doc="shard-routing classes with memo/plan/cache state that never "
          "validate against ShardMapper.topology_generation — stale "
          "after a live shard split")
def topology_generation(module):
    if not module.rel.startswith(_SERVING_PREFIXES) \
            or module.tree is None:
        return []
    findings = []
    for node in module.nodes:
        if not isinstance(node, ast.ClassDef):
            continue
        attr, line = _memo_attr_line(node)
        if attr is None:
            continue
        if not _routes_shards(node):
            continue
        if _validates_topology(node):
            continue
        findings.append(Finding(
            "topology-generation", module.rel, line,
            f"{node.name}.{attr}: caches shard-derived state in a "
            f"class that computes shard routing but never validates "
            f"against topology_generation — after a live split commits "
            f"(ISSUE 13) this memo keeps routing at the retired "
            f"topology; check mapper.topology_generation (or key on "
            f"routing_token()) and evict on a bump, or justify with a "
            f"disable"))
    return findings
