"""filolint engine: rule registry, suppression discipline, runner.

The engine walks Python sources, hands each module (or the whole
project) to registered rules, and folds the resulting findings through
ONE suppression mechanism:

    x = do_risky_thing()  # filolint: disable=<rule>[,<rule>] — <reason>

- the reason is mandatory: a ``disable`` with no justification is
  itself an error (``suppression-syntax``);
- a ``disable`` naming a rule that does not fire on that line is
  itself an error (``stale-suppression``) — suppressions cannot rot
  silently;
- the two meta rules above cannot be suppressed.

Rules come in two scopes:

- ``module``: ``fn(module) -> iterable[Finding]`` — sees one file;
- ``project``: ``fn(project) -> iterable[Finding]`` — sees every file
  plus the repo's tests/ sources and doc/observability.md (the
  cross-file lints: interpret coverage, metric-doc drift).

Register with the :func:`rule` decorator; see doc/analysis.md for the
catalog and for how to add a rule.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import pathlib
import re
import tokenize
from typing import Callable, Iterable, Optional

# meta rules the engine itself owns (not suppressible, not in RULES)
STALE_SUPPRESSION = "stale-suppression"
SUPPRESSION_SYNTAX = "suppression-syntax"
META_RULES = (STALE_SUPPRESSION, SUPPRESSION_SYNTAX)

# the suppression-comment grammar, matched against real COMMENT tokens
# only (a docstring showing the syntax is not a directive); the reason
# separator may be an em dash, --, or a colon, and the reason is required
_SUPPRESS_RE = re.compile(
    r"^#\s*filolint:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*(?:—|--|:)\s*(.*))?$")


@dataclasses.dataclass
class Finding:
    """One diagnostic: where, which rule, why it matters."""
    rule: str
    path: str            # project-relative posix path
    line: int            # 1-based
    message: str
    severity: str = "error"
    suppressed: bool = False
    suppress_reason: str = ""

    def where(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    fn: Callable
    scope: str           # "module" | "project"
    severity: str
    doc: str


RULES: dict[str, Rule] = {}


def rule(name: str, *, scope: str = "module", severity: str = "error",
         doc: str = ""):
    """Register a lint rule under ``name`` (kebab-case)."""
    assert scope in ("module", "project"), scope

    def deco(fn):
        if name in RULES:
            raise ValueError(f"duplicate rule {name!r}")
        RULES[name] = Rule(name, fn, scope, severity, doc or fn.__doc__)
        return fn
    return deco


@dataclasses.dataclass
class Suppression:
    line: int
    rule: str
    reason: str
    used: bool = False


class Module:
    """One parsed source file plus its suppression comments."""

    def __init__(self, rel: str, src: str, path: Optional[pathlib.Path] = None):
        self.rel = rel
        self.path = path
        self.src = src
        self.lines = src.splitlines()
        self._tree: Optional[ast.AST] = None
        self._nodes: Optional[list] = None
        self.parse_error: Optional[SyntaxError] = None
        self.suppressions: list[Suppression] = []
        self.bad_suppressions: list[tuple[int, str]] = []  # (line, problem)
        self._scan_suppressions()

    @property
    def tree(self) -> Optional[ast.AST]:
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.src)
            except SyntaxError as e:
                self.parse_error = e
        return self._tree

    @property
    def nodes(self) -> list:
        """Flat ``ast.walk`` of the tree, computed once — rules iterate
        this instead of re-walking per rule (the engine's 10s full-tree
        budget is mostly AST traversal)."""
        if self._nodes is None:
            t = self.tree
            self._nodes = [] if t is None else list(ast.walk(t))
        return self._nodes

    def _comments(self) -> list[tuple[int, str]]:
        """(line, text) of real comment tokens (strings excluded)."""
        if "filolint" not in self.src:
            return []          # skip tokenizing the common case
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.src).readline)
            return [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return []

    def _scan_suppressions(self) -> None:
        for i, text in self._comments():
            if "filolint:" not in text:
                continue
            m = _SUPPRESS_RE.match(text)
            if m is None:
                self.bad_suppressions.append(
                    (i, "unparseable filolint comment — expected "
                        "'# filolint: disable=<rule> — <reason>'"))
                continue
            names = [n.strip() for n in m.group(1).split(",") if n.strip()]
            reason = (m.group(2) or "").strip()
            if not reason:
                self.bad_suppressions.append(
                    (i, "suppression without a justification — append "
                        "'— <non-empty reason>'"))
                # still record the rules so the original finding stays
                # VISIBLE (an unjustified disable must not hide it)
                continue
            for n in names:
                if n in META_RULES:
                    self.bad_suppressions.append(
                        (i, f"rule {n!r} cannot be suppressed"))
                elif n not in RULES:
                    self.bad_suppressions.append(
                        (i, f"unknown rule {n!r} in disable "
                            f"(see --list-rules)"))
                else:
                    self.suppressions.append(Suppression(i, n, reason))

    def suppression_for(self, rule_name: str, line: int) -> Optional[Suppression]:
        for s in self.suppressions:
            if s.rule == rule_name and s.line == line:
                return s
        return None


class Project:
    """The whole analysis target: modules + cross-file context.

    ``shared`` is the per-run engine cache: cross-file context that
    more than one rule needs (the call graph, the jit entry-point
    table, tests/doc text) is built ONCE per run and shared, so adding
    a rule family never multiplies I/O or re-derivation."""

    def __init__(self, modules: list[Module], root: Optional[pathlib.Path] = None,
                 test_sources: Optional[list[str]] = None,
                 doc_text: Optional[str] = None,
                 api_doc_text: Optional[str] = None):
        self.modules = modules
        self.root = root
        self._test_sources = test_sources
        self._doc_text = doc_text
        self._api_doc_text = api_doc_text
        self._shared: dict = {}

    def shared(self, key: str, build: Callable):
        """Memoized per-run cross-file context: ``build(project)`` runs
        at most once per key per run."""
        if key not in self._shared:
            self._shared[key] = build(self)
        return self._shared[key]

    @property
    def test_sources(self) -> list[str]:
        """tests/*.py contents (interpret-coverage needs them)."""
        if self._test_sources is None:
            out = []
            if self.root is not None:
                for p in sorted((self.root / "tests").glob("test_*.py")):
                    out.append(p.read_text())
            self._test_sources = out
        return self._test_sources


    @property
    def doc_text(self) -> str:
        """doc/observability.md (metric-doc drift needs it)."""
        if self._doc_text is None:
            p = (self.root / "doc" / "observability.md") if self.root else None
            self._doc_text = p.read_text() if p is not None and p.exists() \
                else ""
        return self._doc_text

    @property
    def doc_lines(self) -> list[str]:
        return self.shared("doc_lines", lambda p: p.doc_text.splitlines())

    @property
    def api_doc_text(self) -> str:
        """doc/http_api.md (admin-endpoint drift needs it)."""
        if self._api_doc_text is None:
            p = (self.root / "doc" / "http_api.md") if self.root else None
            self._api_doc_text = p.read_text() \
                if p is not None and p.exists() else ""
        return self._api_doc_text


def _find_repo_root(path: pathlib.Path) -> pathlib.Path:
    """Nearest ancestor holding the filodb_tpu package (so rel paths in
    reports look like filodb_tpu/memstore/shard.py)."""
    p = path if path.is_dir() else path.parent
    for cand in (p, *p.parents):
        if (cand / "filodb_tpu" / "__init__.py").exists():
            return cand
    return p


def load_modules(paths: Iterable[pathlib.Path | str]) -> tuple[list[Module], pathlib.Path]:
    files: list[pathlib.Path] = []
    root: Optional[pathlib.Path] = None
    for raw in paths:
        p = pathlib.Path(raw).resolve()
        if root is None:
            root = _find_repo_root(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    assert root is not None, "no paths given"
    # dedupe: overlapping args (a dir + a file inside it) must not load
    # a module twice — the duplicate's suppressions would never be
    # marked used and report as falsely stale
    seen: set = set()
    files = [f for f in files if not (f in seen or seen.add(f))]
    modules = []
    for f in files:
        try:
            rel = f.relative_to(root).as_posix()
        except ValueError:
            rel = f.name
        modules.append(Module(rel, f.read_text(), f))
    return modules, root


def _select(rules: Optional[Iterable[str]]) -> list[Rule]:
    if rules is None:
        return list(RULES.values())
    out = []
    for n in rules:
        if n not in RULES:
            raise KeyError(f"unknown rule {n!r}; have {sorted(RULES)}")
        out.append(RULES[n])
    return out


def run_project(project: Project,
                rules: Optional[Iterable[str]] = None) -> list[Finding]:
    """Run rules, apply suppressions, append the meta findings.

    Returns EVERY finding; suppressed ones carry suppressed=True.
    """
    selected = _select(rules)
    findings: list[Finding] = []
    by_rel = {m.rel: m for m in project.modules}
    for m in project.modules:
        if m.tree is None:
            findings.append(Finding(
                SUPPRESSION_SYNTAX, m.rel,
                m.parse_error.lineno or 1 if m.parse_error else 1,
                f"unparseable module: {m.parse_error}"))
            continue
        for r in selected:
            if r.scope != "module":
                continue
            for f in r.fn(m):
                f.severity = r.severity
                findings.append(f)
    for r in selected:
        if r.scope != "project":
            continue
        for f in r.fn(project):
            f.severity = r.severity
            findings.append(f)

    # fold suppressions: a finding is suppressed by a justified disable
    # of its rule on its own line
    for f in findings:
        m = by_rel.get(f.path)
        if m is None:
            continue
        s = m.suppression_for(f.rule, f.line)
        if s is not None:
            s.used = True
            f.suppressed = True
            f.suppress_reason = s.reason

    # meta findings: stale + malformed suppressions.  A suppression is
    # only stale relative to rules that actually RAN — a --rules subset
    # must not condemn the other rules' suppressions.
    selected_names = {r.name for r in selected}
    for m in project.modules:
        for s in m.suppressions:
            if s.rule not in selected_names:
                continue
            if not s.used:
                findings.append(Finding(
                    STALE_SUPPRESSION, m.rel, s.line,
                    f"suppression for {s.rule!r} never fires on this "
                    f"line — delete it (stale suppressions hide future "
                    f"regressions)"))
        for line, problem in m.bad_suppressions:
            findings.append(Finding(SUPPRESSION_SYNTAX, m.rel, line,
                                    problem))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_paths(paths: Iterable[pathlib.Path | str],
              rules: Optional[Iterable[str]] = None,
              test_sources: Optional[list[str]] = None,
              doc_text: Optional[str] = None,
              api_doc_text: Optional[str] = None) -> list[Finding]:
    modules, root = load_modules(paths)
    return run_project(Project(modules, root, test_sources, doc_text,
                               api_doc_text),
                       rules)


def run_source(src: str, rules: Optional[Iterable[str]] = None,
               rel: str = "fake.py",
               test_sources: Optional[list[str]] = None,
               doc_text: str = "",
               api_doc_text: str = "") -> list[Finding]:
    """Lint one in-memory source string (rule self-tests)."""
    m = Module(rel, src)
    return run_project(Project([m], None, test_sources or [], doc_text,
                               api_doc_text),
                       rules)


def run_sources(srcs: dict, rules: Optional[Iterable[str]] = None,
                test_sources: Optional[list[str]] = None,
                doc_text: str = "",
                api_doc_text: str = "") -> list[Finding]:
    """Lint several in-memory modules TOGETHER ({rel: src}) — the
    whole-program analyses (cross-module blocking, lock order) see the
    combined project, exactly like a tree run over those files."""
    modules = [Module(rel, src) for rel, src in srcs.items()]
    return run_project(Project(modules, None, test_sources or [], doc_text,
                               api_doc_text),
                       rules)


def unsuppressed(findings: Iterable[Finding]) -> list[Finding]:
    return [f for f in findings if not f.suppressed]
