"""Resource-lifecycle analysis.

``resource-lifecycle``: a class that registers a long-lived callback or
thread must own a reachable release path — the Gauge.remove contract
(doc/observability.md) that needed a manual review fix in three
consecutive PRs (QueryScheduler, FlushScheduler, CardinalityTracker).

Checked registrations (inside class methods; module-scope registrations
are process-lifetime by convention — filodb_process_*, the devicewatch
module gauges — and are exempt):

- ``<gauge>.set_fn(...)``: the registry holds the callback (and every
  object it captures) alive and keeps exporting rows for dead
  instances; the class must call ``.remove(...)`` somewhere.
- ``PeriodicThread(...)``: the class must call ``.stop()`` / ``.close()``
  / ``.cancel()`` / ``.shutdown()`` somewhere.
- ``weakref.finalize(...)``: the class must either ``.detach()`` the
  finalizer or own a release-shaped method (close/stop/deregister/
  untrack/...) that unwinds the registration.
"""

from __future__ import annotations

import ast
import re

from .engine import Finding, rule

_THREAD_RELEASES = {"stop", "close", "cancel", "shutdown"}
_RELEASEY_METHOD_RE = re.compile(
    r"close|stop|shutdown|deregister|unregister|detach|untrack|remove"
    r"|reset|clear|teardown", re.I)


def _attr_calls(cls) -> list:
    out = []
    for n in ast.walk(cls):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            out.append(n)
    return out


@rule("resource-lifecycle",
      doc="registrations without a release path in the same class")
def resource_lifecycle(module):
    findings = []
    for cls in module.nodes:
        if not isinstance(cls, ast.ClassDef):
            continue
        calls = _attr_calls(cls)
        called_attrs = {c.func.attr for c in calls}
        has_remove = "remove" in called_attrs
        has_thread_stop = bool(called_attrs & _THREAD_RELEASES)
        has_detach = "detach" in called_attrs
        releasey_method = any(
            isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            and _RELEASEY_METHOD_RE.search(m.name)
            for m in cls.body)

        for call in calls:
            attr = call.func.attr
            if attr == "register_pool" \
                    and "deregister_pool" not in called_attrs:
                findings.append(Finding(
                    "resource-lifecycle", module.rel, call.lineno,
                    f"{cls.name} registers a devicewatch pool (a gauge "
                    f"set_fn under the hood) but never calls "
                    f"deregister_pool — the ledger samples and exports "
                    f"this instance forever"))
            elif attr == "set_fn" and not has_remove:
                findings.append(Finding(
                    "resource-lifecycle", module.rel, call.lineno,
                    f"{cls.name} registers a gauge set_fn callback but "
                    f"never calls .remove(...): the registry keeps this "
                    f"instance alive and exports rows for it forever — "
                    f"add a close/shutdown that removes the label set "
                    f"(Gauge.remove contract, doc/observability.md)"))
            elif attr == "finalize" and isinstance(call.func.value,
                                                   ast.Name) \
                    and call.func.value.id == "weakref" \
                    and not (has_detach or releasey_method):
                findings.append(Finding(
                    "resource-lifecycle", module.rel, call.lineno,
                    f"{cls.name} arms a weakref.finalize but has no "
                    f"release path (.detach() or a close/deregister-"
                    f"shaped method) — the finalizer and its captures "
                    f"outlive every explicit teardown"))
        for n in ast.walk(cls):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            tname = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if tname == "PeriodicThread" and not has_thread_stop:
                findings.append(Finding(
                    "resource-lifecycle", module.rel, n.lineno,
                    f"{cls.name} starts a PeriodicThread but never "
                    f"calls .stop()/.close() on anything — the daemon "
                    f"loop (and this instance) runs until process "
                    f"exit"))
    return findings
