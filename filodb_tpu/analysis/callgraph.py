"""Whole-program call graph (ISSUE 10 tentpole pillar 1).

PR 13's deepest analysis — blocking-under-lock — propagated only
within a single module: a ``with self._lock:`` in gateway/server.py
that reached a blocking helper in utils/observability.py two modules
away was invisible (the exact cross-module shape of the PR 12
ReplicaFanout wedge).  This module resolves calls ACROSS the
``filodb_tpu`` package so the lock analyses (locks.py, lockorder.py)
can run their fixpoints over the whole program:

- ``import filodb_tpu.a.b as z`` / ``from filodb_tpu.a import b`` /
  relative ``from .b import f`` all bind local names to project
  modules or project functions;
- ``self.x.m()`` resolves best-effort when ``self.x = SomeClass(...)``
  in ``__init__`` and ``SomeClass`` is a project class;
- ``SomeClass(...)`` resolves to ``SomeClass.__init__``.

The graph is built ONCE per run and shared by every rule through
``Project.shared`` (the per-run engine cache), keeping the full-tree
run inside the tier-1 10s budget.

Nothing here is a rule; the graph is analysis infrastructure.  A call
that cannot be resolved contributes no edge — resolution is
deliberately conservative so downstream rules stay false-positive-free
rather than complete.
"""

from __future__ import annotations

import ast
from typing import Optional

# (module rel path, class name or "", function name)
FuncKey = tuple

#: Project.shared key under which the built graph lives for a run.
CACHE_KEY = "callgraph"


def _dotted(rel: str) -> str:
    """filodb_tpu/utils/observability.py -> filodb_tpu.utils.observability
    (packages: filodb_tpu/analysis/__init__.py -> filodb_tpu.analysis)."""
    d = rel[:-3] if rel.endswith(".py") else rel
    if d.endswith("/__init__"):
        d = d[: -len("/__init__")]
    return d.replace("/", ".")


class CallGraph:
    """Function index + resolved call edges over a Project."""

    def __init__(self):
        self.funcs: dict[FuncKey, ast.AST] = {}
        self.classes: dict[tuple, ast.ClassDef] = {}   # (rel, name)
        self.mod_aliases: dict[str, dict[str, str]] = {}    # rel -> {name: rel}
        self.sym_aliases: dict[str, dict[str, tuple]] = {}  # rel -> {name: (rel, sym)}
        self.attr_types: dict[tuple, dict[str, tuple]] = {} # (rel, cls) -> {attr: (rel, cls)}
        self.var_types: dict[tuple, tuple] = {}   # (rel, module var) -> (rel, cls)
        self.edges: dict[FuncKey, list] = {}   # key -> [(callee key, call node)]
        self._by_dotted: dict[str, str] = {}

    # -------------------------------------------------------------- resolution

    def resolve_class(self, rel: str, expr) -> Optional[tuple]:
        """A Name/Attribute that names a project class, or None."""
        if isinstance(expr, ast.Name):
            if (rel, expr.id) in self.classes:
                return (rel, expr.id)
            tgt = self.sym_aliases.get(rel, {}).get(expr.id)
            if tgt is not None and tgt in self.classes:
                return tgt
        elif isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                            ast.Name):
            mod = self.mod_aliases.get(rel, {}).get(expr.value.id)
            if mod is not None and (mod, expr.attr) in self.classes:
                return (mod, expr.attr)
        return None

    def resolve_call(self, call: ast.Call, rel: str,
                     cls: str = "") -> Optional[FuncKey]:
        """Best-effort resolution of a call made from (rel, cls)."""
        f = call.func
        if isinstance(f, ast.Name):
            if (rel, "", f.id) in self.funcs:
                return (rel, "", f.id)
            tgt = self.sym_aliases.get(rel, {}).get(f.id)
            if tgt is not None:
                trel, tsym = tgt
                if (trel, "", tsym) in self.funcs:
                    return (trel, "", tsym)
            ck = self.resolve_class(rel, f)
            if ck is not None and (*ck, "__init__") in self.funcs:
                return (*ck, "__init__")
            return None
        if not isinstance(f, ast.Attribute):
            return None
        v = f.value
        if isinstance(v, ast.Name):
            if v.id == "self" and cls:
                if (rel, cls, f.attr) in self.funcs:
                    return (rel, cls, f.attr)
                return None
            mod = self.mod_aliases.get(rel, {}).get(v.id)
            if mod is not None:
                if (mod, "", f.attr) in self.funcs:
                    return (mod, "", f.attr)
                if (mod, f.attr) in self.classes \
                        and (mod, f.attr, "__init__") in self.funcs:
                    return (mod, f.attr, "__init__")
            ck = self.resolve_class(rel, v)   # SomeClass.method(...)
            if ck is not None and (*ck, f.attr) in self.funcs:
                return (*ck, f.attr)
            owner = self.resolve_var(rel, v.id)   # LEDGER.track(...)
            if owner is not None and (*owner, f.attr) in self.funcs:
                return (*owner, f.attr)
            return None
        if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                and v.value.id == "self" and cls:
            # self.x.m() where __init__ bound x to a known class
            owner = self.attr_types.get((rel, cls), {}).get(v.attr)
            if owner is not None and (*owner, f.attr) in self.funcs:
                return (*owner, f.attr)
        return None

    def resolve_var(self, rel: str, name: str) -> Optional[tuple]:
        """Class of a module-level singleton (``LEDGER = HbmLedger()``),
        followed through from-imports (``from ..utils.devicewatch
        import LEDGER``)."""
        hit = self.var_types.get((rel, name))
        if hit is not None:
            return hit
        tgt = self.sym_aliases.get(rel, {}).get(name)
        return self.var_types.get(tgt) if tgt is not None else None

    def callees(self, key: FuncKey) -> list:
        return self.edges.get(key, [])


def own_calls(fn) -> list:
    """Call nodes in ``fn``'s body EXCLUDING nested def/lambda bodies —
    deferred bodies run later (without locks, off this stack), so they
    are separate call-graph nodes, not part of this one."""
    stack = list(fn.body)
    out = []
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            out.append(n)
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.append(c)
    return out


def _index_module(g: CallGraph, m) -> None:
    rel = m.rel
    g._by_dotted[_dotted(rel)] = rel
    if m.tree is None:
        return
    for node in m.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            g.funcs[(rel, "", node.name)] = node
        elif isinstance(node, ast.ClassDef):
            g.classes[(rel, node.name)] = node
            for meth in node.body:
                if isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    g.funcs[(rel, node.name, meth.name)] = meth


def _resolve_import_target(g: CallGraph, dotted: str) -> Optional[str]:
    return g._by_dotted.get(dotted)


def _scan_imports(g: CallGraph, m) -> None:
    rel, tree = m.rel, m.tree
    mods: dict[str, str] = {}
    syms: dict[str, tuple] = {}
    if tree is None:
        g.mod_aliases[rel], g.sym_aliases[rel] = mods, syms
        return
    pkg_parts = _dotted(rel).split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                tgt = _resolve_import_target(g, alias.name)
                if tgt is None:
                    continue
                local = alias.asname or alias.name.split(".")[0]
                if alias.asname is None and "." in alias.name:
                    # ``import filodb_tpu.a.b`` binds ``filodb_tpu``;
                    # chained-attribute call resolution is not attempted
                    continue
                mods[local] = tgt
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[:-node.level]
                if node.module:
                    base = base + node.module.split(".")
                src = ".".join(base)
            else:
                src = node.module or ""
            src_rel = _resolve_import_target(g, src)
            for alias in node.names:
                local = alias.asname or alias.name
                sub = _resolve_import_target(g, f"{src}.{alias.name}")
                if sub is not None:           # from pkg import module
                    mods[local] = sub
                elif src_rel is not None:     # from module import symbol
                    syms[local] = (src_rel, alias.name)
    g.mod_aliases[rel], g.sym_aliases[rel] = mods, syms


def _scan_attr_types(g: CallGraph, m) -> None:
    """``self.x = SomeClass(...)`` in ``__init__`` types the attribute;
    module-level ``LEDGER = HbmLedger()`` types the singleton."""
    rel = m.rel
    if m.tree is not None:
        for st in m.tree.body:
            if not (isinstance(st, ast.Assign)
                    and isinstance(st.value, ast.Call)):
                continue
            owner = g.resolve_class(rel, st.value.func)
            if owner is None:
                continue
            for t in st.targets:
                if isinstance(t, ast.Name):
                    g.var_types[(rel, t.id)] = owner
    for (crel, cname), cls in g.classes.items():
        if crel != rel:
            continue
        init = g.funcs.get((rel, cname, "__init__"))
        if init is None:
            continue
        types: dict[str, tuple] = {}
        for st in ast.walk(init):
            if not (isinstance(st, ast.Assign)
                    and isinstance(st.value, ast.Call)):
                continue
            owner = g.resolve_class(rel, st.value.func)
            if owner is None:
                continue
            for t in st.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    types[t.attr] = owner
        if types:
            g.attr_types[(rel, cname)] = types


def _scan_edges(g: CallGraph) -> None:
    for key, fn in g.funcs.items():
        rel, cls, _name = key
        out = []
        for call in own_calls(fn):
            callee = g.resolve_call(call, rel, cls)
            if callee is not None and callee != key:
                out.append((callee, call))
        if out:
            g.edges[key] = out


def build(project) -> CallGraph:
    """Build (or fetch the per-run cached) whole-program call graph."""

    def _build(p) -> CallGraph:
        g = CallGraph()
        for m in p.modules:
            _index_module(g, m)
        for m in p.modules:
            _scan_imports(g, m)
        for m in p.modules:
            _scan_attr_types(g, m)
        _scan_edges(g)
        return g

    shared = getattr(project, "shared", None)
    if shared is None:
        return _build(project)
    return shared(CACHE_KEY, _build)


