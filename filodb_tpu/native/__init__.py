"""Native (C++) codec fast paths, bound via ctypes.

The shared library is built from ``src/codecs.cpp`` with g++ on first use
and cached next to this module.  :func:`enable` installs the fast paths
into the pure-Python codec modules' ``_native`` hooks
(filodb_tpu/codecs/nibblepack.py etc.); :func:`disable` restores the
numpy implementations.  Everything degrades gracefully: if no compiler is
available the Python paths keep working.

This layer is the TPU-native stand-in for the reference's Unsafe/jffi
off-heap codec code (reference: memory/src/main/scala/filodb.memory/
format/UnsafeUtils.scala, NibblePack.scala:12) — host-side C++ feeding
dense arrays to the device.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "codecs.cpp")
_SO = os.path.join(_HERE, "_codecs.so")

_lock = threading.Lock()
_lib = None
_build_error: str | None = None


def _build() -> str | None:
    """Compile the shared library if missing/stale.  Returns error or None."""
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return None
        tmp = f"{_SO}.{os.getpid()}.tmp"  # unique per process: no build races
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               "-fno-exceptions", "-o", tmp, _SRC]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            if os.path.exists(tmp):
                os.remove(tmp)
            return proc.stderr.strip() or "g++ failed"
        os.replace(tmp, _SO)
        return None
    except Exception as e:  # compiler missing, read-only fs, ...
        return str(e)


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()  # filolint: disable=blocking-under-lock — single-flight native build: the first caller compiles once per process; contenders must wait for the artifact, not race the compiler
        if err is not None:
            _build_error = err
            return None
        try:
            lib = _bind(ctypes.CDLL(_SO))
        except OSError as e:  # corrupt/mismatched cached .so
            _build_error = str(e)
            return None
        _lib = lib
        return _lib


def _bind(lib):
    lib.np_max_packed.restype = ctypes.c_size_t
    lib.np_max_packed.argtypes = [ctypes.c_size_t]
    lib.np_pack.restype = ctypes.c_longlong
    lib.np_pack.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p]
    lib.np_unpack.restype = ctypes.c_longlong
    lib.np_unpack.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                              ctypes.c_size_t, ctypes.c_size_t,
                              ctypes.c_void_p]
    lib.np_packed_end.restype = ctypes.c_longlong
    lib.np_packed_end.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                  ctypes.c_size_t, ctypes.c_size_t]
    lib.dd_decode.restype = ctypes.c_longlong
    lib.dd_decode.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                              ctypes.c_int, ctypes.c_int,
                              ctypes.c_void_p, ctypes.c_size_t]
    lib.xor_unpack.restype = ctypes.c_longlong
    lib.xor_unpack.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                               ctypes.c_size_t, ctypes.c_size_t,
                               ctypes.c_void_p]
    for fn in (lib.ll_encode_batch, lib.dbl_encode_batch):
        fn.restype = ctypes.c_longlong
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
                       ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p]
    for fn in (lib.ll_decode_batch, lib.dbl_decode_batch):
        fn.restype = ctypes.c_longlong
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
                       ctypes.c_void_p, ctypes.c_void_p]
    lib.page_decode_column.restype = ctypes.c_longlong
    lib.page_decode_column.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p]
    lib.influx_parse_batch.restype = ctypes.c_longlong
    lib.influx_parse_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.gather_ranges.restype = ctypes.c_longlong
    lib.gather_ranges.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p]
    lib.head_hash128.restype = ctypes.c_longlong
    lib.head_hash128.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
        ctypes.c_void_p]
    lib.verify_heads.restype = ctypes.c_longlong
    lib.verify_heads.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong]
    # c_char_p: bytes pass zero-copy with no numpy wrapper — the store
    # verifies one blob per chunk row on the ODP page-in hot path
    lib.crc32c_buf.restype = ctypes.c_uint32
    lib.crc32c_buf.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                               ctypes.c_uint32]
    lib.crc32c_verify_batch.restype = ctypes.c_longlong
    lib.crc32c_verify_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p,
        ctypes.c_longlong, ctypes.c_void_p, ctypes.c_void_p]
    lib.crc32c_verify_spans.restype = ctypes.c_longlong
    lib.crc32c_verify_spans.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
        ctypes.c_void_p, ctypes.c_void_p]
    return lib


def build_error() -> str | None:
    """The compiler error from the last failed build attempt, if any."""
    _load()
    return _build_error


class _NibbleNative:
    """Adapter matching the ``_native`` hook protocol in nibblepack.py."""

    def __init__(self, lib):
        self._lib = lib

    def nibble_pack(self, values: np.ndarray) -> bytes:
        v = np.ascontiguousarray(values, dtype=np.uint64)
        out = np.empty(self._lib.np_max_packed(len(v)), dtype=np.uint8)
        n = self._lib.np_pack(v.ctypes.data, len(v),
                              out.ctypes.data if len(out) else None)
        return out[:n].tobytes()

    def nibble_unpack(self, buf, count: int, offset: int = 0):
        b = bytes(buf)
        out = np.zeros(max(count, 1), dtype=np.uint64)
        nxt = self._lib.np_unpack(b, len(b), offset, count, out.ctypes.data)
        if nxt < 0:
            raise ValueError("nibble stream truncated")
        return out[:count], int(nxt)

    def nibble_packed_end(self, buf, count: int, offset: int = 0) -> int:
        b = bytes(buf)
        nxt = self._lib.np_packed_end(b, len(b), offset, count)
        if nxt < 0:
            raise ValueError("nibble stream truncated")
        return int(nxt)


class _DeltaDeltaNative:
    """Adapter for deltadelta's ``_native`` hook: fused full-buffer decode."""

    def __init__(self, lib, wire_const: int, wire_delta2: int):
        self._lib = lib
        self._wc = wire_const
        self._wd = wire_delta2

    def dd_decode(self, buf) -> np.ndarray:
        from filodb_tpu.codecs import deltadelta

        b = np.frombuffer(buf, dtype=np.uint8)   # zero-copy over any buffer
        if len(b) < 1 + deltadelta._HDR.size:
            raise ValueError("DELTA2 buffer too short")
        n = deltadelta._HDR.unpack_from(b, 1)[0]
        out = np.empty(max(n, 1), dtype=np.int64)
        got = self._lib.dd_decode(b.ctypes.data, len(b), self._wc, self._wd,
                                  out.ctypes.data, len(out))
        if got < 0:
            raise ValueError("corrupt DELTA2 vector")
        return out[:n]


class _XorNative:
    """Adapter for doublecodec's ``_native`` hook: fused XOR-chain decode
    + batch double encode (the flush/downsample hot loop)."""

    def __init__(self, lib):
        self._lib = lib

    def xor_unpack(self, buf, count: int, offset: int) -> np.ndarray:
        b = np.frombuffer(buf, dtype=np.uint8)   # zero-copy over any buffer
        out = np.empty(max(count, 1), dtype=np.float64)
        nxt = self._lib.xor_unpack(b.ctypes.data, len(b), offset, count,
                                   out.ctypes.data)
        if nxt < 0:
            raise ValueError("corrupt XOR double vector")
        return out[:count]

    def dbl_encode_batch(self, arrays) -> list[bytes]:
        return _encode_batch(self._lib.dbl_encode_batch, arrays,
                             np.float64)

    def dbl_encode_batch_2d(self, arr2d) -> list[bytes]:
        """Encode every ROW of a [nvec, n] float64 matrix — the columnar
        downsample write path: the data is already contiguous, so the
        per-vector concat of the list form is skipped entirely."""
        return _encode_batch_2d(self._lib.dbl_encode_batch, arr2d,
                                np.float64)


class _LLEncodeNative:
    """Adapter for deltadelta's batch-encode hook."""

    def __init__(self, lib):
        self._lib = lib

    def ll_encode_batch(self, arrays) -> list[bytes]:
        return _encode_batch(self._lib.ll_encode_batch, arrays,
                             np.int64)


class _BatchDecodeNative:
    """Adapter for chunk.py's batch column decode: one native call per
    numeric family over many blobs (ODP page-in / batch downsampler)."""

    def __init__(self, lib):
        self._lib = lib

    def _decode(self, fn, blobs, counts, dtype):
        nvec = len(blobs)
        offs = np.zeros(nvec + 1, dtype=np.int64)
        np.cumsum([len(b) for b in blobs], out=offs[1:])
        buf = np.frombuffer(b"".join(blobs), dtype=np.uint8) \
            if offs[-1] else np.empty(0, np.uint8)
        out_offs = np.zeros(nvec + 1, dtype=np.int64)
        np.cumsum(counts, out=out_offs[1:])
        out = np.empty(max(int(out_offs[-1]), 1), dtype=dtype)
        got = fn(buf.ctypes.data if len(buf) else None, offs.ctypes.data,
                 nvec, out.ctypes.data, out_offs.ctypes.data)
        if got < 0:
            raise ValueError("corrupt vector in batch decode")
        return [out[out_offs[i]:out_offs[i + 1]] for i in range(nvec)]

    def ll_decode_batch(self, blobs, counts) -> list[np.ndarray]:
        return self._decode(self._lib.ll_decode_batch, blobs, counts,
                            np.int64)

    def dbl_decode_batch(self, blobs, counts) -> list[np.ndarray]:
        return self._decode(self._lib.dbl_decode_batch, blobs, counts,
                            np.float64)

    def _frame_buf(self, blobs):
        nrows = len(blobs)
        offs = np.zeros(nrows + 1, dtype=np.int64)
        np.cumsum([len(b) for b in blobs], out=offs[1:])
        buf = np.frombuffer(b"".join(blobs), dtype=np.uint8) \
            if offs[-1] else np.empty(0, np.uint8)
        return buf, offs

    def _verify_spans(self, buf, offs, nrows, crcs) -> bool:
        """CRC32C-verify every row span of an already-joined frame
        buffer against its stored checksum (integrity subsystem,
        deferred-verify contract: the store skipped verification
        because this decode pass rides the same join).  crc 0 = legacy
        unchecksummed row, passes.  False on any mismatch — callers
        return their corrupt sentinel and the generic (store-verified)
        path takes over."""
        exp = np.ascontiguousarray(crcs, dtype=np.uint32)
        ok = np.empty(max(nrows, 1), dtype=np.uint8)
        bad = self._lib.crc32c_verify_spans(
            buf.ctypes.data if len(buf) else None, offs.ctypes.data,
            nrows, exp.ctypes.data, ok.ctypes.data)
        return bad == 0

    def page_decode(self, blobs, counts, cols, crcs=None):
        """Decode columns of FRAMED ColumnStore row blobs (pack_vectors
        layout) — the ODP bulk page-in: one C pass per column over the
        whole row set, no per-row unpack.  ``cols``: (column_index,
        is_double) pairs; column 0 is the timestamp vector.  With
        ``crcs``, every row blob is first CRC32C-verified against its
        stored checksum on this call's own join (deferred store
        verification).  Returns one flat array per requested column
        (int64 or float64, rows adjacent in blob order), or None if any
        checksum/framing/vector is corrupt (the caller falls back to
        the per-chunk path, which raises usefully)."""
        nrows = len(blobs)
        buf, offs = self._frame_buf(blobs)
        if crcs is not None and not self._verify_spans(buf, offs, nrows,
                                                       crcs):
            return None
        cnts = np.ascontiguousarray(counts, dtype=np.int64)
        starts = np.zeros(nrows, dtype=np.int64)
        np.cumsum(cnts[:-1], out=starts[1:])
        total = int(cnts.sum())
        outs = []
        for col, dbl in cols:
            out = np.empty(max(total, 1),
                           dtype=np.float64 if dbl else np.int64)
            got = self._lib.page_decode_column(
                buf.ctypes.data if len(buf) else None, offs.ctypes.data,
                nrows, int(col), 1 if dbl else 0, out.ctypes.data,
                starts.ctypes.data, cnts.ctypes.data)
            if got < 0:
                return None
            outs.append(out[:total])
        return outs

    def page_decode_into(self, blobs, counts, specs, out_starts,
                         crcs=None) -> bool:
        """Decode framed row blobs DIRECTLY into caller-allocated
        arrays: row k writes counts[k] values at flat index
        out_starts[k] of each spec's output.  ``specs``: (column_index,
        is_double, out_array) with out_array C-contiguous and of the
        matching dtype — the ODP cold path points these at the padded
        [S, R] query batch so decode IS the batch assembly.  With
        ``crcs``, rows are CRC32C-verified on this call's join BEFORE
        any decode writes (deferred store verification).  False on
        corrupt input (outputs then hold partial garbage; callers must
        discard them and fall back)."""
        nrows = len(blobs)
        buf, offs = self._frame_buf(blobs)
        if crcs is not None and not self._verify_spans(buf, offs, nrows,
                                                       crcs):
            return False
        cnts = np.ascontiguousarray(counts, dtype=np.int64)
        starts = np.ascontiguousarray(out_starts, dtype=np.int64)
        for col, dbl, out in specs:
            # raw-pointer writes: a dtype/layout mismatch would corrupt
            # the heap, so this must raise even under python -O
            want = np.float64 if dbl else np.int64
            if not out.flags.c_contiguous or out.dtype != want:
                raise ValueError(
                    f"page_decode_into output for column {col} must be "
                    f"C-contiguous {want.__name__}")
            got = self._lib.page_decode_column(
                buf.ctypes.data if len(buf) else None, offs.ctypes.data,
                nrows, int(col), 1 if dbl else 0, out.ctypes.data,
                starts.ctypes.data, cnts.ctypes.data)
            if got < 0:
                return False
        return True


class _InfluxNative:
    """Adapter for influx.py's ``_native_parse`` hook: one C pass scans
    the payload into per-line spans + parsed values/timestamps."""

    INVALID = "invalid"    # sentinel: batch needs the general parser

    def __init__(self, lib):
        self._lib = lib

    def parse(self, data: bytes):
        a = np.frombuffer(data, np.uint8)
        maxn = int(np.count_nonzero(a == 10))
        if maxn == 0:
            return self.INVALID
        starts = np.empty(maxn, np.int64)
        sp1 = np.empty(maxn, np.int64)
        eq1 = np.empty(maxn, np.int64)
        values = np.empty(maxn, np.float64)
        ts_ns = np.empty(maxn, np.int64)
        got = self._lib.influx_parse_batch(
            a.ctypes.data, len(a), maxn, starts.ctypes.data,
            sp1.ctypes.data, eq1.ctypes.data, values.ctypes.data,
            ts_ns.ctypes.data)
        if got < 0:
            return self.INVALID
        n = int(got)
        return (starts[:n], sp1[:n], eq1[:n], values[:n], ts_ns[:n])

    def gather(self, a: np.ndarray, starts: np.ndarray,
               ends: np.ndarray) -> "np.ndarray | None":
        """Concatenated a[starts[k]:ends[k]] bytes in ONE C pass
        (replaces the numpy arange+repeat flat-index gather).  The C
        side bounds-checks every span against len(a) and returns -1 on
        a malformed one."""
        starts = np.ascontiguousarray(starts, np.int64)
        ends = np.ascontiguousarray(ends, np.int64)
        lens = ends - starts
        if len(lens) and int(lens.min()) < 0:
            return None          # malformed span: match the C guard
        total = int(lens.sum())
        out = np.empty(total, np.uint8)
        got = self._lib.gather_ranges(a.ctypes.data, len(a),
                                      starts.ctypes.data,
                                      ends.ctypes.data, len(starts),
                                      out.ctypes.data)
        return out if got == total else None

    def head_hashes(self, a: np.ndarray, starts: np.ndarray,
                    ends: np.ndarray, p1: np.ndarray, p2: np.ndarray):
        """Per-line 2x64-bit positional hashes, bit-identical to the
        numpy reduceat formulation in gateway/influx.py."""
        starts = np.ascontiguousarray(starts, np.int64)
        ends = np.ascontiguousarray(ends, np.int64)
        n = len(starts)
        h1 = np.empty(n, np.uint64)
        h2 = np.empty(n, np.uint64)
        got = self._lib.head_hash128(
            a.ctypes.data, len(a), starts.ctypes.data, ends.ctypes.data,
            n, p1.ctypes.data, p2.ctypes.data, len(p1),
            h1.ctypes.data, h2.ctypes.data)
        return (h1, h2) if got == n else None

    def verify(self, a: np.ndarray, starts: np.ndarray,
               ends: np.ndarray, rep: np.ndarray) -> "bool | None":
        """memcmp every line's head against its group representative;
        True = all equal, False = collision (fall back), None = error."""
        starts = np.ascontiguousarray(starts, np.int64)
        ends = np.ascontiguousarray(ends, np.int64)
        rep = np.ascontiguousarray(rep, np.int64)
        got = self._lib.verify_heads(a.ctypes.data, len(a),
                                     starts.ctypes.data,
                                     ends.ctypes.data, rep.ctypes.data,
                                     len(starts))
        if got < 0:
            return None
        return bool(got)


def _encode_batch_2d(fn, arr2d, dtype) -> list[bytes]:
    arr2d = np.ascontiguousarray(arr2d, dtype)
    nvec, n = arr2d.shape
    if nvec == 0:
        return []
    starts = np.arange(nvec + 1, dtype=np.int64) * n
    per = 26 + ((n + 7) // 8) * 66          # same bound as _encode_batch
    cap = int(nvec * per)
    out = np.empty(max(cap, 1), dtype=np.uint8)
    offs = np.empty(nvec + 1, dtype=np.int64)
    total = fn(arr2d.ctypes.data, starts.ctypes.data, nvec,
               out.ctypes.data, len(out), offs.ctypes.data)
    if total < 0:
        raise ValueError("native batch encode overflow")
    buf = out[:total].tobytes()
    return [buf[offs[i]:offs[i + 1]] for i in range(nvec)]


def _encode_batch(fn, arrays, dtype) -> list[bytes]:
    nvec = len(arrays)
    if nvec == 0:
        return []
    lens = np.array([len(a) for a in arrays], dtype=np.int64)
    starts = np.zeros(nvec + 1, dtype=np.int64)
    np.cumsum(lens, out=starts[1:])
    flat = np.ascontiguousarray(
        np.concatenate([np.asarray(a, dtype).ravel() for a in arrays])
        if starts[-1] else np.empty(0, dtype))
    # per-vector worst case: nested headers (<=26B) + the nibblepack
    # bound ((n+7)//8 groups * 66B), closed-form — no per-vector FFI
    cap = int((26 + ((lens + 7) // 8) * 66).sum())
    out = np.empty(max(cap, 1), dtype=np.uint8)
    offs = np.empty(nvec + 1, dtype=np.int64)
    total = fn(flat.ctypes.data if len(flat) else None, starts.ctypes.data,
               nvec, out.ctypes.data, len(out), offs.ctypes.data)
    if total < 0:
        raise ValueError("native batch encode overflow")
    buf = out[:total].tobytes()
    return [buf[offs[i]:offs[i + 1]] for i in range(nvec)]


def enable() -> bool:
    """Install native fast paths into the codec modules.  True on success."""
    lib = _load()
    if lib is None:
        return False
    from filodb_tpu.codecs import deltadelta, doublecodec, nibblepack
    from filodb_tpu.codecs.wire import WireType

    nibblepack._native = _NibbleNative(lib)
    deltadelta._native = _DeltaDeltaNative(lib, int(WireType.CONST_LONG),
                                           int(WireType.DELTA2))
    deltadelta._native_enc = _LLEncodeNative(lib)
    doublecodec._native = _XorNative(lib)
    global _batch_dec, _influx_parse
    _batch_dec = _BatchDecodeNative(lib)
    _influx_parse = _InfluxNative(lib)
    return True


def disable() -> None:
    from filodb_tpu.codecs import deltadelta, doublecodec, nibblepack

    nibblepack._native = None
    deltadelta._native = None
    deltadelta._native_enc = None
    doublecodec._native = None
    global _batch_dec, _influx_parse
    _batch_dec = None
    _influx_parse = None


_batch_dec = None
_influx_parse = None


def batch_decoder():
    """The batch column-decode adapter, or None when native is off.
    Looked up lazily by core/chunk.py — enable() runs during the codecs
    package import, when core.chunk cannot be imported yet."""
    return _batch_dec


def influx_parser():
    """The influx batch-scan adapter, or None when native is off.
    Looked up lazily by gateway/influx.py (same reason as
    :func:`batch_decoder`)."""
    return _influx_parse


def crc32c(buf, seed: int = 0) -> "int | None":
    """CRC32C of a buffer via the C kernel, or None when the library is
    unavailable (the integrity layer then uses its bit-identical Python
    fallback).  Deliberately independent of :func:`enable`: checksums
    must not change value because the codec hooks were toggled."""
    lib = _load()
    if lib is None:
        return None
    if not isinstance(buf, bytes):
        buf = bytes(buf)
    return int(lib.crc32c_buf(buf, len(buf), seed & 0xFFFFFFFF))


def crc32c_verify(blobs, expected) -> "tuple[int, np.ndarray] | None":
    """Batch CRC32C verify: ONE C call over a pointer array of blobs
    against the per-blob expected checksums (integrity.chunk_crc's
    never-zero mapping applied).  Returns (mismatch_count, ok bool
    array), or None when the native library is unavailable.  This is
    the ODP page-in read-back verifier: no join/copy of the blob bytes,
    and the C side interleaves three crc32 instruction streams — the
    naive per-blob formulation cost ~30% of a cold ODP scan, this one
    ~2% (BASELINE.md)."""
    lib = _load()
    if lib is None:
        return None
    n = len(blobs)
    ptrs = (ctypes.c_char_p * n)(*blobs)
    lens = np.array(list(map(len, blobs)), dtype=np.int64)
    exp = np.ascontiguousarray(expected, dtype=np.uint32)
    ok = np.empty(max(n, 1), dtype=np.uint8)
    bad = lib.crc32c_verify_batch(ptrs, lens.ctypes.data, n,
                                  exp.ctypes.data, ok.ctypes.data)
    return int(bad), ok[:n].astype(bool)


def is_enabled() -> bool:
    from filodb_tpu.codecs import nibblepack

    return nibblepack._native is not None
