// Native codec fast paths for filodb_tpu.
//
// Implements the same storage formats as the Python codecs in
// filodb_tpu/codecs/ (NibblePack groups, DELTA2 sloped-line residuals,
// XOR-double residual chains) — the TPU-native equivalent of the
// reference's Unsafe-level hot codecs (reference:
// memory/src/main/scala/filodb.memory/format/NibblePack.scala:12,
// format/vectors/DeltaDeltaVector.scala:28, DoubleVector.scala:14).
// Bound from Python via ctypes (filodb_tpu/native/__init__.py); every
// function is extern "C" and operates on caller-owned buffers.
//
// All decode paths are bounds-checked against buflen and return -1 on
// overrun so a corrupt chunk can never read out of bounds.

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string_view>
#include <unordered_map>
#include <vector>

#if defined(_MSC_VER)
#include <intrin.h>
#endif

namespace {

inline int ctz64(uint64_t x) {
#if defined(_MSC_VER)
  unsigned long idx;
  _BitScanForward64(&idx, x);
  return static_cast<int>(idx);
#else
  return __builtin_ctzll(x);
#endif
}

inline int clz64(uint64_t x) {
#if defined(_MSC_VER)
  unsigned long idx;
  _BitScanReverse64(&idx, x);
  return 63 - static_cast<int>(idx);
#else
  return __builtin_clzll(x);
#endif
}

inline int popcount8(uint8_t x) {
#if defined(_MSC_VER)
  return static_cast<int>(__popcnt16(x));
#else
  return __builtin_popcount(x);
#endif
}

inline uint64_t zigzag_enc(int64_t v) {
  return (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
}

inline int64_t zigzag_dec(uint64_t u) {
  return static_cast<int64_t>(u >> 1) ^ -static_cast<int64_t>(u & 1);
}

// Nibble-stream writer: accumulates nibbles into bytes, low nibble first.
struct NibbleWriter {
  uint8_t* out;
  size_t pos;
  bool half;     // true => low nibble of out[pos] already written
  void put(uint8_t nib) {
    if (!half) {
      out[pos] = nib;
      half = true;
    } else {
      out[pos] |= static_cast<uint8_t>(nib << 4);
      ++pos;
      half = false;
    }
  }
  void flush() {
    if (half) {
      ++pos;
      half = false;
    }
  }
};

}  // namespace

extern "C" {

// Upper bound on packed size for n values (2 header bytes + 16 nibbles
// per value, per group of 8).
size_t np_max_packed(size_t n) {
  size_t ngroups = (n + 7) / 8;
  return ngroups * (2 + 8 * 8);
}

// NibblePack n u64 values into out (which must hold np_max_packed(n)).
// Returns bytes written.
long long np_pack(const uint64_t* v, size_t n, uint8_t* out) {
  size_t ngroups = (n + 7) / 8;
  size_t opos = 0;
  for (size_t g = 0; g < ngroups; ++g) {
    uint64_t group[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    size_t base = g * 8;
    size_t lim = (base + 8 <= n) ? 8 : n - base;
    for (size_t i = 0; i < lim; ++i) group[i] = v[base + i];

    uint8_t bitmask = 0;
    int tz = 64, lz = 64;
    for (int i = 0; i < 8; ++i) {
      if (group[i] != 0) {
        bitmask |= static_cast<uint8_t>(1u << i);
        int t = ctz64(group[i]);
        int l = clz64(group[i]);
        if (t < tz) tz = t;
        if (l < lz) lz = l;
      }
    }
    out[opos++] = bitmask;
    if (bitmask == 0) continue;

    int trailing = tz / 4;
    int leading = lz / 4;
    int num_nibbles = 16 - leading - trailing;
    if (num_nibbles < 1) num_nibbles = 1;
    out[opos++] = static_cast<uint8_t>((trailing & 0xF) |
                                       ((num_nibbles - 1) << 4));
    NibbleWriter w{out, opos, false};
    for (int i = 0; i < 8; ++i) {
      if (group[i] == 0) continue;
      uint64_t shifted = group[i] >> (trailing * 4);
      for (int k = 0; k < num_nibbles; ++k) {
        w.put(static_cast<uint8_t>((shifted >> (4 * k)) & 0xF));
      }
    }
    w.flush();
    opos = w.pos;
  }
  return static_cast<long long>(opos);
}

// Decode count u64 values from buf starting at offset into out.
// Returns the next offset, or -1 on buffer overrun.
long long np_unpack(const uint8_t* buf, size_t buflen, size_t offset,
                    size_t count, uint64_t* out) {
  size_t pos = offset;
  size_t ngroups = (count + 7) / 8;
  size_t emitted = 0;
  for (size_t g = 0; g < ngroups; ++g) {
    if (pos >= buflen) return -1;
    uint8_t bitmask = buf[pos++];
    if (bitmask == 0) {
      for (int i = 0; i < 8 && emitted < count; ++i) out[emitted++] = 0;
      continue;
    }
    if (pos >= buflen) return -1;
    uint8_t hdr = buf[pos++];
    int trailing = hdr & 0xF;
    int num_nibbles = (hdr >> 4) + 1;
    int nnz = popcount8(bitmask);
    size_t total_nibbles = static_cast<size_t>(num_nibbles) * nnz;
    size_t nbytes = (total_nibbles + 1) / 2;
    if (pos + nbytes > buflen) return -1;

    size_t nib_idx = 0;  // index into the nibble stream for this group
    // fast path: one unaligned u64 load covers a whole value's nibbles
    // (num_nibbles < 16 -> <= 60 bits + a 4-bit phase shift); the slow
    // per-nibble walk remains for 16-nibble values and the buffer tail
    if (num_nibbles < 16 && pos + nbytes + 8 <= buflen) {
      uint64_t vmask = (1ull << (4 * num_nibbles)) - 1;
      int tshift = trailing * 4;
      for (int i = 0; i < 8; ++i) {
        uint64_t val = 0;
        if (bitmask & (1u << i)) {
          uint64_t w;
          std::memcpy(&w, buf + pos + (nib_idx >> 1), 8);
          val = ((w >> (4 * (nib_idx & 1))) & vmask) << tshift;
          nib_idx += static_cast<size_t>(num_nibbles);
        }
        if (emitted < count) out[emitted++] = val;
      }
      pos += nbytes;
      continue;
    }
    for (int i = 0; i < 8; ++i) {
      uint64_t val = 0;
      if (bitmask & (1u << i)) {
        for (int k = 0; k < num_nibbles; ++k, ++nib_idx) {
          uint8_t byte = buf[pos + nib_idx / 2];
          uint8_t nib = (nib_idx & 1) ? (byte >> 4) : (byte & 0xF);
          val |= static_cast<uint64_t>(nib) << (4 * k);
        }
        val <<= (trailing * 4);
      }
      if (emitted < count) out[emitted++] = val;
    }
    pos += nbytes;
  }
  return static_cast<long long>(pos);
}

// Walk a packed run without materializing values; returns end offset or -1.
long long np_packed_end(const uint8_t* buf, size_t buflen, size_t offset,
                        size_t count) {
  size_t pos = offset;
  size_t ngroups = (count + 7) / 8;
  for (size_t g = 0; g < ngroups; ++g) {
    if (pos >= buflen) return -1;
    uint8_t bitmask = buf[pos++];
    if (bitmask == 0) continue;
    if (pos >= buflen) return -1;
    uint8_t hdr = buf[pos++];
    int num_nibbles = (hdr >> 4) + 1;
    int nnz = popcount8(bitmask);
    pos += (static_cast<size_t>(num_nibbles) * nnz + 1) / 2;
    if (pos > buflen) return -1;
  }
  return static_cast<long long>(pos);
}

// Fused DELTA2 decode.  buf points at the wire-type byte of a
// CONST_LONG/DELTA2 vector: u8 wire, u32 n, i64 base, i64 slope,
// [nibble-packed zigzag residuals].  Writes n int64s; returns n or -1.
// wire_const / wire_delta2 are passed in so the wire-code registry stays
// single-sourced in Python (filodb_tpu/codecs/wire.py).
long long dd_decode(const uint8_t* buf, size_t buflen, int wire_const,
                    int wire_delta2, int64_t* out, size_t out_cap) {
  if (buflen < 21) return -1;
  int wire = buf[0];
  if (wire != wire_const && wire != wire_delta2) return -1;
  uint32_t n;
  uint64_t base, slope;
  std::memcpy(&n, buf + 1, 4);
  std::memcpy(&base, buf + 5, 8);
  std::memcpy(&slope, buf + 13, 8);
  if (n > out_cap) return -1;

  uint64_t pred = base;
  if (wire == wire_const) {
    for (uint32_t i = 0; i < n; ++i) {
      out[i] = static_cast<int64_t>(pred);
      pred += slope;
    }
    return n;
  }
  // DELTA2: stream groups of 8 residuals and fuse line + zigzag add.
  size_t pos = 21;
  uint32_t emitted = 0;
  uint64_t resid[8];
  size_t ngroups = (static_cast<size_t>(n) + 7) / 8;
  for (size_t g = 0; g < ngroups; ++g) {
    long long next = np_unpack(buf, buflen, pos, 8, resid);
    if (next < 0) return -1;
    pos = static_cast<size_t>(next);
    for (int i = 0; i < 8 && emitted < n; ++i, ++emitted) {
      out[emitted] = static_cast<int64_t>(
          pred + static_cast<uint64_t>(zigzag_dec(resid[i])));
      pred += slope;
    }
  }
  return n;
}

// Fused XOR-double decode: nibble-unpack count u64 residuals starting at
// offset and invert the XOR-with-previous chain in one pass.
// Returns next offset or -1.
long long xor_unpack(const uint8_t* buf, size_t buflen, size_t offset,
                     size_t count, double* out) {
  size_t pos = offset;
  size_t ngroups = (count + 7) / 8;
  size_t emitted = 0;
  uint64_t acc = 0;
  uint64_t resid[8];
  for (size_t g = 0; g < ngroups; ++g) {
    long long next = np_unpack(buf, buflen, pos, 8, resid);
    if (next < 0) return -1;
    pos = static_cast<size_t>(next);
    for (int i = 0; i < 8 && emitted < count; ++i, ++emitted) {
      acc ^= resid[i];
      std::memcpy(&out[emitted], &acc, 8);
    }
  }
  return static_cast<long long>(pos);
}

// ---------------------------------------------------------------------------
// Columnar container decode: the ingest fast path.
//
// Parses one RecordContainer (filodb_tpu/core/record.py wire layout:
// u32 total, then records of [u16 schema_hash, u32 shard_hash,
// u32 part_hash, i64 ts, data cols..., u16 pklen, pk bytes]) straight
// into columnar arrays, deduplicating partition keys with a hash map so
// Python touches one object per *series*, not per record.  Ingest-side
// equivalent of the reference's zero-copy RecordContainer.iterate over
// off-heap BinaryRecords (reference: core/src/main/scala/filodb.core/
// binaryrecord2/RecordContainer.scala:27, TimeSeriesShard.scala:488-522).
//
// Schema table: per schema, its 16-bit hash, data-column count, and
// column type codes (1 = f64 bit pattern into the i64 cell, 2 = i64,
// 3 = i32 widened, 4 = histogram blob: the cell receives the blob's
// ABSOLUTE byte offset; hist_col_decode below expands the blobs)
// flattened as sch_types[si * max_cols + ci].  String columns are
// unsupported (-2): those containers take the Python path.  Every
// record must carry the same schema hash (-3 otherwise — mixed
// containers fall back too).  Returns the record count, or a negative
// error code: -1 malformed, -2 unsupported column, -3 mixed/unknown
// schema, -4 capacity exceeded.
long long cd_decode(const uint8_t* buf, size_t buflen,
                    const uint16_t* sch_hashes, const uint8_t* sch_ncols,
                    const uint8_t* sch_types, size_t max_cols,
                    size_t n_schemas, size_t cap, int64_t* ts_out,
                    int64_t* vals_out, uint32_t* shard_out,
                    uint32_t* part_out, int32_t* uniq_out,
                    int64_t* pk_off, int64_t* pk_len, int64_t* uniq_first,
                    long long* n_uniq_out, int32_t* schema_hash_out) {
  if (buflen < 4) return -1;
  uint32_t total;
  std::memcpy(&total, buf, 4);
  size_t end = 4 + static_cast<size_t>(total);
  if (end > buflen) return -1;

  // resolve the (single) schema from the first record
  if (end < 4 + 18) return total == 0 ? 0 : -1;
  uint16_t schema_hash;
  std::memcpy(&schema_hash, buf + 4, 2);
  size_t si = n_schemas;
  for (size_t i = 0; i < n_schemas; ++i)
    if (sch_hashes[i] == schema_hash) { si = i; break; }
  if (si == n_schemas) return -3;
  const size_t ncols = sch_ncols[si];
  const uint8_t* types = sch_types + si * max_cols;
  for (size_t c = 0; c < ncols; ++c)
    if (types[c] < 1 || types[c] > 4) return -2;

  std::unordered_map<std::string_view, int32_t> pk_map;
  pk_map.reserve(256);
  size_t pos = 4;
  long long n = 0, n_uniq = 0;
  while (pos < end) {
    if (pos + 18 > end) return -1;
    if (static_cast<size_t>(n) >= cap) return -4;
    uint16_t sh;
    std::memcpy(&sh, buf + pos, 2);
    if (sh != schema_hash) return -3;
    std::memcpy(&shard_out[n], buf + pos + 2, 4);
    std::memcpy(&part_out[n], buf + pos + 6, 4);
    std::memcpy(&ts_out[n], buf + pos + 10, 8);
    pos += 18;
    int64_t* row = vals_out + static_cast<size_t>(n) * max_cols;
    for (size_t c = 0; c < ncols; ++c) {
      switch (types[c]) {
        case 1:  // f64: keep the bit pattern; Python views as float64
        case 2:  // i64
          if (pos + 8 > end) return -1;
          std::memcpy(&row[c], buf + pos, 8);
          pos += 8;
          break;
        case 3: {  // i32 widened
          if (pos + 4 > end) return -1;
          int32_t v;
          std::memcpy(&v, buf + pos, 4);
          row[c] = v;
          pos += 4;
          break;
        }
        case 4: {  // histogram blob: u16 len + bytes; record the offset
          if (pos + 2 > end) return -1;
          uint16_t blen;
          std::memcpy(&blen, buf + pos, 2);
          pos += 2;
          if (pos + blen > end) return -1;
          row[c] = static_cast<int64_t>(pos);
          pos += blen;
          break;
        }
      }
    }
    if (pos + 2 > end) return -1;
    uint16_t pklen;
    std::memcpy(&pklen, buf + pos, 2);
    pos += 2;
    if (pos + pklen > end) return -1;
    std::string_view key(reinterpret_cast<const char*>(buf + pos), pklen);
    auto it = pk_map.find(key);
    int32_t uid;
    if (it == pk_map.end()) {
      uid = static_cast<int32_t>(n_uniq);
      pk_map.emplace(key, uid);
      pk_off[n_uniq] = static_cast<int64_t>(pos);
      pk_len[n_uniq] = pklen;
      uniq_first[n_uniq] = n;
      ++n_uniq;
    } else {
      uid = it->second;
    }
    uniq_out[n] = uid;
    pos += pklen;
    ++n;
  }
  *n_uniq_out = n_uniq;
  *schema_hash_out = static_cast<int32_t>(schema_hash);
  return n;
}

// ---------------------------------------------------------------------------
// Histogram column expansion: decode every record's BinaryHistogram blob
// (filodb_tpu/codecs/histcodec.py encode_hist_value layout: u8 wire_hist,
// u16 n_buckets, bucket scheme [geometric: u8 id + 19 B | custom: u8 id
// + u16 cn + 8*cn B], nibble-packed zigzag deltas) into a dense
// [n, hb_cap] cumulative-counts matrix in one native pass, deduplicating
// bucket schemes by their serialized bytes.  The ingest-side answer to
// the reference's per-record BinHistogram parse (reference:
// memory/format/vectors/HistogramVector.scala:34; the jmh analog is
// HistogramIngestBenchmark.scala:29).
//
// blob_off comes from cd_decode's type-4 cells (each u16 length prefix
// precedes the blob, so the bound is re-read here).  Returns n, or -1
// malformed, -2 wrong wire/scheme, -4 a blob exceeds hb_cap, -5 scheme
// capacity exceeded.
long long hist_col_decode(const uint8_t* buf, size_t buflen,
                          const int64_t* blob_off, size_t n,
                          int wire_hist, int scheme_geo, int scheme_custom,
                          size_t hb_cap, int64_t* counts_out,
                          int32_t* nb_out, int32_t* scheme_idx,
                          int64_t* uscheme_off, int64_t* uscheme_len,
                          size_t cap_schemes, long long* n_schemes_out) {
  std::unordered_map<std::string_view, int32_t> smap;
  smap.reserve(4);
  long long ns = 0;
  uint64_t tmp[8];
  for (size_t i = 0; i < n; ++i) {
    size_t pos = static_cast<size_t>(blob_off[i]);
    if (pos < 2 || pos + 3 > buflen) return -1;
    uint16_t blen;
    std::memcpy(&blen, buf + pos - 2, 2);
    size_t bend = pos + blen;
    if (bend > buflen || pos + 3 > bend) return -1;
    if (buf[pos] != wire_hist) return -2;
    uint16_t nv;
    std::memcpy(&nv, buf + pos + 1, 2);
    if (nv > hb_cap) return -4;
    size_t spos = pos + 3;
    if (spos >= bend) return -1;
    size_t slen;
    int sid = buf[spos];
    if (sid == scheme_geo) {
      slen = 20;
    } else if (sid == scheme_custom) {
      if (spos + 3 > bend) return -1;
      uint16_t cn;
      std::memcpy(&cn, buf + spos + 1, 2);
      slen = 3 + static_cast<size_t>(cn) * 8;
    } else {
      return -2;
    }
    if (spos + slen > bend) return -1;
    std::string_view sv(reinterpret_cast<const char*>(buf + spos), slen);
    auto it = smap.find(sv);
    int32_t suid;
    if (it == smap.end()) {
      if (static_cast<size_t>(ns) >= cap_schemes) return -5;
      suid = static_cast<int32_t>(ns);
      smap.emplace(sv, suid);
      uscheme_off[ns] = static_cast<int64_t>(spos);
      uscheme_len[ns] = static_cast<int64_t>(slen);
      ++ns;
    } else {
      suid = it->second;
    }
    scheme_idx[i] = suid;
    nb_out[i] = nv;
    // nibble-unpack the zigzag deltas group-wise and fuse the cumsum
    int64_t* row = counts_out + i * hb_cap;
    size_t dpos = spos + slen;
    int64_t acc = 0;
    uint32_t emitted = 0;
    size_t ngroups = (static_cast<size_t>(nv) + 7) / 8;
    for (size_t g = 0; g < ngroups; ++g) {
      long long next = np_unpack(buf, bend, dpos, 8, tmp);
      if (next < 0) return -1;
      dpos = static_cast<size_t>(next);
      for (int k = 0; k < 8 && emitted < nv; ++k, ++emitted) {
        acc += zigzag_dec(tmp[k]);
        row[emitted] = acc;
      }
    }
    for (size_t k = nv; k < hb_cap; ++k) row[k] = acc;  // edge-pad
  }
  *n_schemes_out = ns;
  return static_cast<long long>(n);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batch ENCODE: the flush/downsample hot loop (reference:
// TimeSeriesPartition.encodeOneChunkset optimize() step, and the Spark
// downsampler's chunk re-encode, DownsamplerMain.scala:43).  One call
// encodes a whole batch of vectors — per-vector Python overhead was the
// dominant cost of small downsample chunks.
//
// Wire constants mirror filodb_tpu/codecs/wire.py (DELTA2=1,
// CONST_LONG=2, DELTA2_DOUBLE=16, XOR_DOUBLE=17, RAW_DOUBLE=18,
// CONST_DOUBLE=19, GORILLA_DOUBLE=20); the byte-identity tests against the Python
// encoders guard the pairing.

namespace {

constexpr uint8_t kWireDelta2 = 1;
constexpr uint8_t kWireConstLong = 2;
constexpr uint8_t kWireDelta2Double = 16;
constexpr uint8_t kWireXorDouble = 17;
constexpr uint8_t kWireRawDouble = 18;
constexpr uint8_t kWireConstDouble = 19;
constexpr uint8_t kWireGorillaDouble = 20;

// LSB-first bit writer over a pre-zeroed region (matches
// np.packbits(bitorder="little")).
struct BitWriter {
  uint8_t* out;
  size_t bitpos = 0;
  void put(uint64_t bits, int nbits) {
    for (int i = 0; i < nbits; ++i, ++bitpos) {
      if ((bits >> i) & 1)
        out[bitpos >> 3] |= static_cast<uint8_t>(1u << (bitpos & 7));
    }
  }
};

inline void put_u32(uint8_t* out, uint32_t v) { std::memcpy(out, &v, 4); }
inline void put_i64(uint8_t* out, int64_t v) { std::memcpy(out, &v, 8); }

// DELTA2/CONST_LONG encode of one int64 vector.  scratch holds n u64.
long long ll_encode_one(const int64_t* v, size_t n, uint8_t* out,
                        size_t cap, uint64_t* scratch) {
  if (cap < 21) return -1;
  if (n == 0) {
    out[0] = kWireConstLong;
    std::memset(out + 1, 0, 20);
    return 21;
  }
  int64_t base = v[0];
  int64_t slope = 0;
  if (n > 1) {
    // divide at LONG DOUBLE precision (x86: 64-bit mantissa, holding
    // any int64-pair span exactly) so the quotient matches Python's
    // correctly-rounded int/int true division; a double-precision
    // intermediate would double-round spans beyond 2^53 and break the
    // byte pairing with the Python encoder
    long double diff = static_cast<long double>(
        static_cast<__int128>(v[n - 1]) - static_cast<__int128>(base));
    double d = static_cast<double>(diff /
                                   static_cast<long double>(n - 1));
    d = std::nearbyint(d);  // round-half-even, like Python round()
    // wrap into int64 modulo 2^64, like the Python encoder — residual
    // arithmetic is modular, so wraparound round-trips exactly; a clamp
    // would lose the modular compression on full-span vectors.  |d| <
    // 2^64 always (an int64 pair spans at most 2^64-1), so ONE exact
    // 2^64 shift suffices; in-range values must cast directly (going
    // through fmod/addition at 2^64 scale would quantize them)
    if (d >= 9223372036854775808.0) d -= 18446744073709551616.0;
    else if (d < -9223372036854775808.0) d += 18446744073709551616.0;
    slope = static_cast<int64_t>(d);
  }
  const uint64_t ubase = static_cast<uint64_t>(base);
  const uint64_t uslope = static_cast<uint64_t>(slope);
  bool all_zero = true;
  uint64_t pred = ubase;
  for (size_t i = 0; i < n; ++i, pred += uslope) {
    uint64_t resid = static_cast<uint64_t>(v[i]) - pred;
    scratch[i] = zigzag_enc(static_cast<int64_t>(resid));
    all_zero &= (resid == 0);
  }
  if (all_zero) {
    out[0] = kWireConstLong;
    put_u32(out + 1, static_cast<uint32_t>(n));
    put_i64(out + 5, base);
    put_i64(out + 13, slope);
    return 21;
  }
  if (cap < 21 + np_max_packed(n)) return -1;
  out[0] = kWireDelta2;
  put_u32(out + 1, static_cast<uint32_t>(n));
  put_i64(out + 5, base);
  put_i64(out + 13, slope);
  long long w = np_pack(scratch, n, out + 21);
  return 21 + w;
}

// Full double-selector encode of one f64 vector.  scratch holds n u64,
// packbuf holds np_max_packed(n).
long long dbl_encode_one(const double* v, size_t n, uint8_t* out,
                         size_t cap, uint64_t* scratch, uint8_t* packbuf) {
  // integral doubles -> nested DELTA2 long encoding
  bool integral = n > 0;
  for (size_t i = 0; i < n && integral; ++i) {
    double x = v[i];
    if (!std::isfinite(x) || !(std::fabs(x) < 9223372036854775808.0) ||
        (x == 0.0 && std::signbit(x))) {
      integral = false;
      break;
    }
    int64_t iv = static_cast<int64_t>(x);
    if (static_cast<double>(iv) != x) integral = false;
  }
  if (integral) {
    if (cap < 1) return -1;
    out[0] = kWireDelta2Double;
    // reuse packbuf's tail as the int64 conversion buffer? sizes differ;
    // convert into scratch reinterpreted as int64
    std::vector<int64_t> iv(n);
    for (size_t i = 0; i < n; ++i) iv[i] = static_cast<int64_t>(v[i]);
    long long w = ll_encode_one(iv.data(), n, out + 1, cap - 1, scratch);
    return w < 0 ? -1 : 1 + w;
  }
  // constant (value equality, matching the Python np.all(v[0] == v))
  if (n > 0 && !std::isnan(v[0])) {
    bool all_eq = true;
    for (size_t i = 1; i < n && all_eq; ++i) all_eq = (v[i] == v[0]);
    if (all_eq) {
      if (cap < 13) return -1;
      out[0] = kWireConstDouble;
      put_u32(out + 1, static_cast<uint32_t>(n));
      std::memcpy(out + 5, &v[0], 8);
      return 13;
    }
  }
  // XOR residual chain
  uint64_t prev = 0;
  size_t nnz = 0;
  size_t sig_total = 0;
  for (size_t i = 0; i < n; ++i) {
    uint64_t bits;
    std::memcpy(&bits, &v[i], 8);
    uint64_t r = bits ^ prev;
    prev = bits;
    scratch[i] = r;
    if (r) {
      ++nnz;
      sig_total += 64 - clz64(r) - ctz64(r);
    }
  }
  // closed-form gorilla size vs nibblepack size (same rule as Python)
  size_t gorilla_bytes = 8 + (n + 7) / 8 + (nnz * 12 + 7) / 8
                         + (sig_total + 7) / 8;
  long long packed = np_pack(scratch, n, packbuf);
  // compression must pay for itself: unless the best bit-packed form
  // saves >=10% over raw, emit RAW_DOUBLE (one memcpy to decode).
  // Integer rule identical to the Python encoder (doublecodec.encode).
  size_t best = gorilla_bytes < static_cast<size_t>(packed) + 4
                    ? gorilla_bytes
                    : static_cast<size_t>(packed) + 4;
  size_t raw_bytes = 4 + 8 * n;
  if (best * 10 > raw_bytes * 9) {
    size_t total = 5 + 8 * n;
    if (cap < total) return -1;
    out[0] = kWireRawDouble;
    put_u32(out + 1, static_cast<uint32_t>(n));
    std::memcpy(out + 5, v, 8 * n);
    return static_cast<long long>(total);
  }
  if (gorilla_bytes <= static_cast<size_t>(packed) + 4) {
    size_t total = 1 + gorilla_bytes;
    if (cap < total) return -1;
    std::memset(out, 0, total);
    out[0] = kWireGorillaDouble;
    put_u32(out + 1, static_cast<uint32_t>(n));
    put_u32(out + 5, static_cast<uint32_t>(nnz));
    uint8_t* bitmap = out + 9;
    uint8_t* hdrs = bitmap + (n + 7) / 8;
    uint8_t* sig = hdrs + (nnz * 12 + 7) / 8;
    BitWriter hw{hdrs};
    BitWriter sw{sig};
    for (size_t i = 0; i < n; ++i) {
      uint64_t r = scratch[i];
      if (!r) continue;
      bitmap[i >> 3] |= static_cast<uint8_t>(1u << (i & 7));
      int clz = clz64(r);
      int ctz = ctz64(r);
      int len = 64 - clz - ctz;
      hw.put((static_cast<uint64_t>(clz) << 6) |
                 static_cast<uint64_t>(len - 1),
             12);
      sw.put(r >> ctz, len);
    }
    return static_cast<long long>(total);
  }
  size_t total = 5 + static_cast<size_t>(packed);
  if (cap < total) return -1;
  out[0] = kWireXorDouble;
  put_u32(out + 1, static_cast<uint32_t>(n));
  std::memcpy(out + 5, packbuf, static_cast<size_t>(packed));
  return static_cast<long long>(total);
}

// Decode ONE double vector of any double wire form into o[0..n).
// Returns n or -1 on corruption.  iscratch is reused across calls.
long long dbl_decode_one(const uint8_t* b, size_t blen, double* o,
                         size_t n, std::vector<int64_t>& iscratch) {
  if (blen < 1) return -1;
  uint8_t wire = b[0];
  if (wire == kWireDelta2Double) {
    if (iscratch.size() < n) iscratch.resize(n);
    long long got = dd_decode(b + 1, blen - 1, kWireConstLong,
                              kWireDelta2, iscratch.data(), n);
    if (got < 0 || static_cast<size_t>(got) != n) return -1;
    for (size_t i = 0; i < n; ++i)
      o[i] = static_cast<double>(iscratch[i]);
  } else if (wire == kWireConstDouble) {
    if (blen < 13) return -1;
    uint32_t nn;
    std::memcpy(&nn, b + 1, 4);
    if (nn != n) return -1;
    double v;
    std::memcpy(&v, b + 5, 8);
    for (size_t i = 0; i < n; ++i) o[i] = v;
  } else if (wire == kWireXorDouble) {
    uint32_t nn;
    if (blen < 5) return -1;
    std::memcpy(&nn, b + 1, 4);
    if (nn != n) return -1;
    if (xor_unpack(b, blen, 5, n, o) < 0) return -1;
  } else if (wire == kWireRawDouble) {
    uint32_t nn;
    if (blen < 5 + 8 * n) return -1;
    std::memcpy(&nn, b + 1, 4);
    if (nn != n) return -1;
    std::memcpy(o, b + 5, 8 * n);
  } else if (wire == kWireGorillaDouble) {
    if (blen < 9) return -1;
    uint32_t nn, nnz;
    std::memcpy(&nn, b + 1, 4);
    std::memcpy(&nnz, b + 5, 4);
    if (nn != n) return -1;
    size_t bm = 9;
    size_t hdrs = bm + (n + 7) / 8;
    size_t sig = hdrs + (static_cast<size_t>(nnz) * 12 + 7) / 8;
    if (sig > blen) return -1;
    size_t hbit = 0, sbit = 0;
    auto read_bits = [&](const uint8_t* p, size_t& bitpos,
                         int nbits) -> uint64_t {
      uint64_t v = 0;
      for (int i = 0; i < nbits; ++i, ++bitpos)
        v |= static_cast<uint64_t>((p[bitpos >> 3] >> (bitpos & 7)) & 1)
             << i;
      return v;
    };
    uint64_t acc = 0;
    size_t sig_end_bits = (blen - sig) * 8;
    size_t hdr_end_bits = (sig - hdrs) * 8;
    for (size_t i = 0; i < n; ++i) {
      if ((b[bm + (i >> 3)] >> (i & 7)) & 1) {
        // a corrupt bitmap whose popcount exceeds nnz must fail,
        // never walk header reads past the buffer
        if (hbit + 12 > hdr_end_bits) return -1;
        uint64_t hdr = read_bits(b + hdrs, hbit, 12);
        int clz = static_cast<int>(hdr >> 6);
        int len = static_cast<int>(hdr & 63) + 1;
        int ctz = 64 - clz - len;
        if (ctz < 0 || sbit + static_cast<size_t>(len) > sig_end_bits)
          return -1;
        acc ^= read_bits(b + sig, sbit, len) << ctz;
      }
      std::memcpy(&o[i], &acc, 8);
    }
  } else {
    return -1;
  }
  return static_cast<long long>(n);
}

}  // namespace

extern "C" {

// Decode nvec DELTA2/CONST_LONG blobs (each a full encoding incl. the
// wire byte) into one contiguous int64 output.  offs: nvec+1 prefix
// byte offsets into buf; out_offs: nvec+1 prefix VALUE offsets.
// Returns total values or -1 on corruption.
long long ll_decode_batch(const uint8_t* buf, const int64_t* offs,
                          int64_t nvec, int64_t* out,
                          const int64_t* out_offs) {
  for (int64_t k = 0; k < nvec; ++k) {
    size_t expect = static_cast<size_t>(out_offs[k + 1] - out_offs[k]);
    long long got = dd_decode(buf + offs[k],
                              static_cast<size_t>(offs[k + 1] - offs[k]),
                              kWireConstLong, kWireDelta2,
                              out + out_offs[k], expect);
    // a blob whose header count disagrees with the caller-expected
    // count must fail loudly, never serve uninitialized memory
    if (got < 0 || static_cast<size_t>(got) != expect) return -1;
  }
  int64_t total = out_offs[nvec];
  return total;
}

// Decode nvec double blobs (any double wire form) into one contiguous
// f64 output.  Same offset contract as ll_decode_batch.
long long dbl_decode_batch(const uint8_t* buf, const int64_t* offs,
                           int64_t nvec, double* out,
                           const int64_t* out_offs) {
  std::vector<int64_t> iscratch;
  for (int64_t k = 0; k < nvec; ++k) {
    if (dbl_decode_one(buf + offs[k],
                       static_cast<size_t>(offs[k + 1] - offs[k]),
                       out + out_offs[k],
                       static_cast<size_t>(out_offs[k + 1] - out_offs[k]),
                       iscratch) < 0)
      return -1;
  }
  return out_offs[nvec];
}

// Decode data-column `col` of nrows FRAMED ColumnStore row blobs (u16
// vector count, then (u32 byte length, encoded bytes) per vector — the
// pack_vectors layout, store/persistence.py) into caller-placed output
// spans.  This is the ODP bulk page-in hot path: framing walk + codec
// decode in one C pass, replacing a per-row Python unpack + per-chunk
// decode object dance (reference: DemandPagedChunkStore.scala:34 pages
// raw Cassandra chunks straight into block memory).  is_dbl selects the
// double-wire decoder (out is double*), otherwise DELTA2/CONST_LONG
// (out is int64_t*).  Row k writes counts[k] values at out_starts[k] —
// arbitrary placement, so the caller can decode STRAIGHT INTO a padded
// [S, R] query batch and skip the concat/copy assembly entirely.
// Returns total values or -1 on corruption.
long long page_decode_column(const uint8_t* buf, const int64_t* offs,
                             int64_t nrows, int64_t col, int is_dbl,
                             void* out, const int64_t* out_starts,
                             const int64_t* counts) {
  std::vector<int64_t> iscratch;
  long long total = 0;
  for (int64_t k = 0; k < nrows; ++k) {
    const uint8_t* b = buf + offs[k];
    size_t blen = static_cast<size_t>(offs[k + 1] - offs[k]);
    if (blen < 2) return -1;
    uint16_t nvec;
    std::memcpy(&nvec, b, 2);
    if (col < 0 || col >= static_cast<int64_t>(nvec)) return -1;
    size_t pos = 2;
    uint32_t ln = 0;
    for (int64_t j = 0; j <= col; ++j) {
      if (pos + 4 > blen) return -1;
      std::memcpy(&ln, b + pos, 4);
      pos += 4;
      if (j < col) pos += ln;
    }
    if (pos + ln > blen) return -1;
    size_t n = static_cast<size_t>(counts[k]);
    total += counts[k];
    if (is_dbl) {
      if (dbl_decode_one(b + pos, ln,
                         static_cast<double*>(out) + out_starts[k], n,
                         iscratch) < 0)
        return -1;
    } else {
      long long got = dd_decode(b + pos, ln, kWireConstLong, kWireDelta2,
                                static_cast<int64_t*>(out) + out_starts[k],
                                n);
      if (got < 0 || static_cast<size_t>(got) != n) return -1;
    }
  }
  return total;
}

// Encode nvec int64 vectors (DELTA2/CONST_LONG per vector).  starts is
// an nvec+1 prefix-offset array into vals; blob_offs (nvec+1) receives
// output prefix offsets.  Returns total bytes or -1 on overflow.
long long ll_encode_batch(const int64_t* vals, const int64_t* starts,
                          int64_t nvec, uint8_t* out, int64_t cap,
                          int64_t* blob_offs) {
  std::vector<uint64_t> scratch;
  int64_t pos = 0;
  blob_offs[0] = 0;
  for (int64_t k = 0; k < nvec; ++k) {
    size_t n = static_cast<size_t>(starts[k + 1] - starts[k]);
    if (scratch.size() < n) scratch.resize(n);
    long long w = ll_encode_one(vals + starts[k], n, out + pos,
                                static_cast<size_t>(cap - pos),
                                scratch.data());
    if (w < 0) return -1;
    pos += w;
    blob_offs[k + 1] = pos;
  }
  return pos;
}

// Encode nvec float64 vectors with the full double selector.
long long dbl_encode_batch(const double* vals, const int64_t* starts,
                           int64_t nvec, uint8_t* out, int64_t cap,
                           int64_t* blob_offs) {
  std::vector<uint64_t> scratch;
  std::vector<uint8_t> packbuf;
  int64_t pos = 0;
  blob_offs[0] = 0;
  for (int64_t k = 0; k < nvec; ++k) {
    size_t n = static_cast<size_t>(starts[k + 1] - starts[k]);
    if (scratch.size() < n) scratch.resize(n);
    size_t need = np_max_packed(n);
    if (packbuf.size() < need) packbuf.resize(need);
    long long w = dbl_encode_one(vals + starts[k], n, out + pos,
                                 static_cast<size_t>(cap - pos),
                                 scratch.data(), packbuf.data());
    if (w < 0) return -1;
    pos += w;
    blob_offs[k + 1] = pos;
  }
  return pos;
}

// Influx line-protocol batch scan: one pass over the payload finds each
// line's head span, field '=', float value, and integer ns timestamp
// (the gateway's columnar hot path; the Python layer keeps head dedup +
// memoization).  Caller pre-rejects escapes/quotes/comments and
// guarantees a trailing '\n'.  Writes per-line starts/sp1/eq1 offsets,
// values, and timestamps; returns the line count, or -1 when ANY line
// needs the general parser (the fast path is never wrong, only absent).
long long influx_parse_batch(const uint8_t* buf, int64_t n,
                             int64_t max_lines, int64_t* starts,
                             int64_t* sp1, int64_t* eq1, double* values,
                             long long* ts_ns) {
  int64_t nl = 0;
  int64_t i = 0;
  while (i < n) {
    const uint8_t* p =
        static_cast<const uint8_t*>(memchr(buf + i, '\n', n - i));
    if (!p) break;
    int64_t j = p - buf;
    int64_t end = j;
    if (end > i && buf[end - 1] == '\r') --end;
    if (end == i) { i = j + 1; continue; }           // blank line
    if (nl >= max_lines) return -1;
    if (buf[i] == ' ' || buf[end - 1] == ' ') return -1;
    const uint8_t* s1 =
        static_cast<const uint8_t*>(memchr(buf + i, ' ', end - i));
    if (!s1) return -1;                              // no fields
    int64_t a1 = s1 - buf;
    const uint8_t* s2 = static_cast<const uint8_t*>(
        memchr(buf + a1 + 1, ' ', end - a1 - 1));
    if (!s2) return -1;                              // no timestamp
    int64_t a2 = s2 - buf;
    if (memchr(buf + a2 + 1, ' ', end - a2 - 1)) return -1;
    const uint8_t* e1 = static_cast<const uint8_t*>(
        memchr(buf + a1 + 1, '=', a2 - a1 - 1));
    if (!e1) return -1;                              // field without '='
    int64_t b1 = e1 - buf;
    if (b1 == a1 + 1) return -1;                     // empty field name
    if (memchr(buf + b1 + 1, '=', a2 - b1 - 1)) return -1;
    if (memchr(buf + a1 + 1, ',', a2 - a1 - 1)) return -1;  // multi-field
    if (b1 + 1 >= a2) return -1;                     // empty value
    // strtod is laxer than Python float(): it accepts C99 hex floats
    // and "nan(...)" forms.  Reject those up front so acceptance never
    // depends on whether the native library is loaded ("the fast path
    // is never wrong, only absent").
    {
      int64_t v0 = b1 + 1;
      if (buf[v0] == '+' || buf[v0] == '-') ++v0;
      if (v0 + 1 < a2 && buf[v0] == '0' &&
          (buf[v0 + 1] == 'x' || buf[v0 + 1] == 'X'))
        return -1;
      if (memchr(buf + b1 + 1, '(', a2 - b1 - 1)) return -1;
    }
    char* endp = nullptr;
    double v = strtod(reinterpret_cast<const char*>(buf) + b1 + 1, &endp);
    if (endp != reinterpret_cast<const char*>(buf) + a2)
      return -1;          // int/bool/string field value
    if (a2 + 1 >= end || end - (a2 + 1) > 19) return -1;
    unsigned long long t = 0;
    for (int64_t k = a2 + 1; k < end; ++k) {
      uint8_t c = buf[k];
      if (c < '0' || c > '9') return -1;             // sign/garbage ts
      t = t * 10ULL + (c - '0');
    }
    if (t > 9223372036854775807ULL) return -1;
    starts[nl] = i;
    sp1[nl] = a1;
    eq1[nl] = b1;
    values[nl] = v;
    ts_ns[nl] = static_cast<long long>(t);
    ++nl;
    i = j + 1;
  }
  return nl;
}

// Concatenate per-line [starts[k], ends[k]) byte ranges into `out`
// (caller sizes it as sum(ends-starts)).  Replaces the numpy
// arange+repeat flat-index gather on the gateway parse hot path.
// Spans are validated against buf_len (starts[k] >= 0, ends[k] <=
// buf_len) so a malformed span returns -1 instead of a silent
// out-of-bounds read — matching the len < 0 guard.
long long gather_ranges(const uint8_t* buf, int64_t buf_len,
                        const int64_t* starts, const int64_t* ends,
                        int64_t n, uint8_t* out) {
  int64_t pos = 0;
  for (int64_t k = 0; k < n; ++k) {
    int64_t len = ends[k] - starts[k];
    if (len < 0 || starts[k] < 0 || ends[k] > buf_len) return -1;
    memcpy(out + pos, buf + starts[k], len);
    pos += len;
  }
  return pos;
}

// Per-line 2x64-bit positional head hashes (same formulation as the
// numpy reduceat path in gateway/influx.py: sum(byte * pow[rel]) per
// stream, stream 2 xor'd with the head length).  pow tables are
// caller-provided so Python and C stay bit-identical.  Spans are
// bounds-checked against buf_len like gather_ranges.
long long head_hash128(const uint8_t* buf, int64_t buf_len,
                       const int64_t* starts, const int64_t* ends,
                       int64_t n, const uint64_t* p1, const uint64_t* p2,
                       int64_t npow, uint64_t* h1, uint64_t* h2) {
  for (int64_t k = 0; k < n; ++k) {
    int64_t len = ends[k] - starts[k];
    if (len < 0 || len >= npow || starts[k] < 0 || ends[k] > buf_len)
      return -1;
    const uint8_t* p = buf + starts[k];
    uint64_t a = 0, b = 0;
    for (int64_t r = 0; r < len; ++r) {
      uint64_t c = p[r];
      a += c * p1[r];
      b += c * p2[r];
    }
    h1[k] = a;
    h2[k] = b ^ static_cast<uint64_t>(len);
  }
  return n;
}

// Hash-collision guard: every line's head bytes must equal its group
// representative's (rep[k] indexes into the same line arrays).
// Returns 1 when all match, 0 on any mismatch (caller falls back to
// the per-line parser), -1 on malformed spans (including spans outside
// [0, buf_len)).
long long verify_heads(const uint8_t* buf, int64_t buf_len,
                       const int64_t* starts, const int64_t* ends,
                       const int64_t* rep, int64_t n) {
  for (int64_t k = 0; k < n; ++k) {
    int64_t len = ends[k] - starts[k];
    int64_t rk = rep[k];
    if (len < 0 || starts[k] < 0 || ends[k] > buf_len ||
        rk < 0 || rk >= n)
      return -1;
    if (ends[rk] - starts[rk] != len) return 0;
    if (starts[rk] < 0 || ends[rk] > buf_len) return -1;
    if (memcmp(buf + starts[k], buf + starts[rk],
               static_cast<size_t>(len)) != 0)
      return 0;
  }
  return 1;
}

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, reflected 0x82F63B78) — the per-chunk checksum of
// the integrity subsystem (filodb_tpu/integrity/).  Hardware SSE4.2
// crc32 instruction when the CPU has it (~15 GB/s), slicing-by-8 table
// kernel otherwise (~1 GB/s): computed over the framed vectors blob at
// flush time and re-verified on every ODP page-in and bulk read-back.
// Bit-identical to the pure-Python fallback in integrity/__init__.py
// (standard CRC32C: crc32c("123456789") == 0xE3069283).

}  // extern "C" (internal CRC kernels are C++-linkage)

namespace {

struct Crc32cTables {
  uint32_t t[8][256];
  Crc32cTables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int s = 1; s < 8; ++s)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
  }
};

uint32_t crc32c_sw(const uint8_t* buf, long long n, uint32_t crc) {
  static const Crc32cTables tabs;  // magic-static init: thread-safe
  const uint32_t(*t)[256] = tabs.t;
  long long i = 0;
  for (; i + 8 <= n; i += 8) {
    uint32_t lo;
    std::memcpy(&lo, buf + i, 4);
    lo ^= crc;
    uint32_t hi;
    std::memcpy(&hi, buf + i + 4, 4);
    crc = t[7][lo & 0xFF] ^ t[6][(lo >> 8) & 0xFF] ^
          t[5][(lo >> 16) & 0xFF] ^ t[4][lo >> 24] ^
          t[3][hi & 0xFF] ^ t[2][(hi >> 8) & 0xFF] ^
          t[1][(hi >> 16) & 0xFF] ^ t[0][hi >> 24];
  }
  for (; i < n; ++i) crc = (crc >> 8) ^ t[0][(crc ^ buf[i]) & 0xFF];
  return crc;
}

#if defined(__x86_64__) || defined(__i386__)
__attribute__((target("sse4.2")))
uint32_t crc32c_hw(const uint8_t* buf, long long n, uint32_t crc0) {
  uint64_t crc = crc0;
  long long i = 0;
  for (; i + 8 <= n; i += 8) {
    uint64_t v;
    std::memcpy(&v, buf + i, 8);
    crc = __builtin_ia32_crc32di(crc, v);
  }
  uint32_t c = static_cast<uint32_t>(crc);
  for (; i < n; ++i) c = __builtin_ia32_crc32qi(c, buf[i]);
  return c;
}

bool crc32c_have_hw() {
  static const bool have = __builtin_cpu_supports("sse4.2");
  return have;
}
#else
uint32_t crc32c_hw(const uint8_t* buf, long long n, uint32_t c) {
  return crc32c_sw(buf, n, c);
}
bool crc32c_have_hw() { return false; }
#endif

inline uint32_t crc32c_run(const uint8_t* buf, long long n, uint32_t seed) {
  uint32_t crc = ~seed;
  crc = crc32c_have_hw() ? crc32c_hw(buf, n, crc) : crc32c_sw(buf, n, crc);
  return ~crc;
}

}  // namespace

extern "C" {

unsigned crc32c_buf(const uint8_t* buf, long long n, unsigned seed) {
  return crc32c_run(buf, n, seed);
}

}  // extern "C" (interleaved batch kernel below is C++-linkage)

namespace {

#if defined(__x86_64__) || defined(__i386__)
// Three independent blobs per iteration: the crc32 instruction has
// 3-cycle latency but 1/cycle throughput, so three interleaved streams
// run ~3x faster than one — and because the streams are SEPARATE blobs
// there is no polynomial-combine step at all.
__attribute__((target("sse4.2")))
void crc3_hw(const uint8_t* b0, const uint8_t* b1, const uint8_t* b2,
             int64_t l0, int64_t l1, int64_t l2, uint32_t* out) {
  uint64_t c0 = 0xFFFFFFFFu, c1 = 0xFFFFFFFFu, c2 = 0xFFFFFFFFu;
  int64_t m = l0 < l1 ? l0 : l1;
  if (l2 < m) m = l2;
  m &= ~int64_t(7);
  int64_t i = 0;
  for (; i < m; i += 8) {
    uint64_t a, b, c;
    std::memcpy(&a, b0 + i, 8);
    std::memcpy(&b, b1 + i, 8);
    std::memcpy(&c, b2 + i, 8);
    c0 = __builtin_ia32_crc32di(c0, a);
    c1 = __builtin_ia32_crc32di(c1, b);
    c2 = __builtin_ia32_crc32di(c2, c);
  }
  out[0] = ~crc32c_hw(b0 + i, l0 - i, static_cast<uint32_t>(c0));
  out[1] = ~crc32c_hw(b1 + i, l1 - i, static_cast<uint32_t>(c1));
  out[2] = ~crc32c_hw(b2 + i, l2 - i, static_cast<uint32_t>(c2));
}
#endif

}  // namespace

extern "C" {

// Batched per-blob verify for the store read-back hot path: ONE ctypes
// call for a whole page-in's rows, blobs passed as a pointer array (no
// Python-side join/copy).  ok[i]=1 when blob i's CRC32C equals
// expect[i]; a computed value of 0 maps to 1, matching
// integrity.chunk_crc's never-zero rule.  Returns the mismatch count.
long long crc32c_verify_batch(const uint8_t* const* blobs,
                              const int64_t* lens, int64_t n,
                              const uint32_t* expect, uint8_t* ok) {
  long long bad = 0;
  int64_t i = 0;
#if defined(__x86_64__) || defined(__i386__)
  if (crc32c_have_hw()) {
    uint32_t c3[3];
    for (; i + 3 <= n; i += 3) {
      crc3_hw(blobs[i], blobs[i + 1], blobs[i + 2],
              lens[i], lens[i + 1], lens[i + 2], c3);
      for (int k = 0; k < 3; ++k) {
        uint32_t c = c3[k] ? c3[k] : 1;
        ok[i + k] = (c == expect[i + k]);
        bad += ok[i + k] ? 0 : 1;
      }
    }
  }
#endif
  for (; i < n; ++i) {
    uint32_t c = crc32c_run(blobs[i], lens[i], 0);
    if (!c) c = 1;
    ok[i] = (c == expect[i]);
    bad += ok[i] ? 0 : 1;
  }
  return bad;
}

// Joined-span form of the batch verify: spans are the consecutive
// regions [offs[i], offs[i+1]) of one buffer — EXACTLY the frame the
// bulk page decoder already builds, so the ODP hot path verifies
// checksums on the decoder's own join with zero extra Python-side
// copies (see _BatchDecodeNative.page_decode).  expect[i]==0 means
// "no checksum recorded" (legacy row) and passes.  Returns the
// mismatch count.
long long crc32c_verify_spans(const uint8_t* buf, const int64_t* offs,
                              int64_t n, const uint32_t* expect,
                              uint8_t* ok) {
  long long bad = 0;
  int64_t i = 0;
#if defined(__x86_64__) || defined(__i386__)
  if (crc32c_have_hw()) {
    uint32_t c3[3];
    for (; i + 3 <= n; i += 3) {
      crc3_hw(buf + offs[i], buf + offs[i + 1], buf + offs[i + 2],
              offs[i + 1] - offs[i], offs[i + 2] - offs[i + 1],
              offs[i + 3] - offs[i + 2], c3);
      for (int k = 0; k < 3; ++k) {
        uint32_t c = c3[k] ? c3[k] : 1;
        ok[i + k] = !expect[i + k] || c == expect[i + k];
        bad += ok[i + k] ? 0 : 1;
      }
    }
  }
#endif
  for (; i < n; ++i) {
    uint32_t c = crc32c_run(buf + offs[i], offs[i + 1] - offs[i], 0);
    if (!c) c = 1;
    ok[i] = !expect[i] || c == expect[i];
    bad += ok[i] ? 0 : 1;
  }
  return bad;
}

}  // extern "C"
